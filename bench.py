"""Benchmark: Yahoo-Streaming-Benchmark-style keyed sliding-window count.

Workload (BASELINE.json config 2): events keyed by campaign (dense int
keys), 10s windows sliding by 1s, event-time, watermark advanced per batch.

Device path (round 3): the fused PALLAS superscan — the whole T-step window
dispatch (MXU one-hot ingest + fire + purge) as ONE kernel with the
slice-ring state resident in VMEM (flink_tpu/ops/pallas_superscan.py).
The record stream is synthesized ON DEVICE with jax threefry PRNG from a
fixed integer schedule; the host regenerates bit-identical records (threefry
is backend-deterministic) for the single-core numpy baseline and the
window-by-window parity check. Only kilobyte-sized plan arrays cross the
host link per dispatch, so the measurement reflects the operator, not the
relay's ~50 MB/s host<->device tunnel (staging-bandwidth numbers are still
reported for transparency).

CPU baseline: an optimized single-core numpy implementation of the same
slice-decomposed algorithm (np.bincount segment sums) — a deliberately
*stronger* baseline than a per-record port of the reference's JVM
WindowOperator (see BASELINE.md; hot path WindowOperator.java:293).

Robustness: the TPU is reached over a single-client relay whose backend
init can wedge for minutes. This file is a *supervisor*: it runs the
measurement in child processes that stream incremental JSON progress lines
and always prints one final JSON line picked from, in order of preference:

  1. completed full-scale TPU run        (device: "tpu", parity checked)
  2. partial / small-scale TPU run       (device: "tpu", partial: true) —
     the tiny first measurement is parity-checked within ~1 min of
     backend_ready; later partials carry parity "deferred"
  3. completed CPU-backend run of the XLA superscan ("cpu-jit")
  4. numpy-baseline-only sentinel (only if even the CPU child dies)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

import numpy as np

NUM_KEYS = 8192
WINDOW_MS = 10_000
SLIDE_MS = 1_000
OOO_MS = 500                  # out-of-orderness jitter bound
WM_DELAY_MS = 1_000
STEP_MS = 655                 # event-time span of one step (int schedule)
NSB = 4
SEED = 42

# main (TPU) workload scale
LOG2_BATCH = int(os.environ.get("BENCH_LOG2_BATCH", "20"))
SPAN_STEPS = int(os.environ.get("BENCH_SPAN_STEPS", "48"))   # steps per dispatch
SPANS = int(os.environ.get("BENCH_SPANS", "8"))
PIPE_DEPTH = int(os.environ.get("BENCH_PIPE_DEPTH", "3"))

# total wall budget and init window for the TPU attempt
BUDGET_S = int(os.environ.get("BENCH_WATCHDOG_S", "1200"))
INIT_S = int(os.environ.get("BENCH_INIT_S", "420"))

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")


def _emit(obj):
    print(json.dumps(obj), flush=True)


def observability_snapshot(stage_time_s: Optional[dict], elapsed_s: float) -> dict:
    """Per-stage device-time attribution + backpressure ratio for the bench
    result JSON, plus a measured overhead check of the metric hot path (one
    histogram update is what a latency marker costs per operator hop)."""
    from flink_tpu.metrics.registry import Histogram

    h = Histogram()
    n = 10_000
    t0 = time.perf_counter()
    for i in range(n):
        h.update(float(i))
    marker_us = (time.perf_counter() - t0) / n * 1e6
    stage_ms = {k: round(v * 1000.0, 1)
                for k, v in (stage_time_s or {}).items()}
    resolve_s = (stage_time_s or {}).get("superscan_resolve_block", 0.0)
    return {
        "per_stage_device_time_ms": stage_ms,
        # host blocked on device readback / wall — the run loop's
        # backPressuredTimeRatio analogue for the bench pipeline
        "backpressure_ratio": round(resolve_s / max(elapsed_s, 1e-9), 4),
        "marker_record_us": round(marker_us, 3),
        "overhead_ok": marker_us < 50.0,
    }


# ---------------------------------------------------------------------------
# deterministic stream schedule (integer math, identical on host and device)
#
#   step t, record b (0-based):
#     base  = t*STEP_MS + ((b+1)*STEP_MS)//B
#     ts    = max(base - jitter, 0),  jitter = bits >> 13 mod (OOO_MS+1)
#     key   = bits & (NUM_KEYS-1)     bits = threefry(fold_in(seed, t))
#   watermark after step t: (t+1)*STEP_MS - WM_DELAY_MS
# ---------------------------------------------------------------------------

def hbm_gbps(events: int, elapsed_s: float, *, batch: int,
             num_keys: int = NUM_KEYS, num_slices: int = 32,
             bytes_per_record: int = 8) -> float:
    """Achieved HBM bandwidth implied by a measured run (roofline seed).

    Pure arithmetic from quantities already in hand (T, B, K, S) — no
    profiler: each ingested record streams its key + slice id through the
    kernel (2 x int32 = 8 B; value aggs pass bytes_per_record=12), and
    every step reads AND writes the [K, S] int32 slice ring
    (2*K*S*4 B, steps = events/batch). Fire/purge readbacks and padding
    are ignored, so this is a LOWER bound on real traffic — paired with
    the chip's HBM spec it answers "how close to the roofline?" for
    BENCH_*.json consumers."""
    steps = events / max(batch, 1)
    bytes_moved = events * bytes_per_record + steps * 2 * num_keys * num_slices * 4
    return bytes_moved / max(elapsed_s, 1e-9) / 1e9


# ---------------------------------------------------------------------------
# zipf key sampling — THE stateless skewed-key sampler, single-sourced:
# every skewed bench leg (multichip, millikey, the skew matrix) draws keys
# through this, so "zipf(1.0)" means the same distribution in every
# scenario and skew numbers are comparable across the whole artifact
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=2)
def zipf_bounded_cdf(num_keys: int, s: float = 1.0):
    """Bounded zipf cdf over ranks 1..num_keys: p_k ~ 1/k^s, normalized.
    np.random.zipf is unbounded and undefined at s=1.0, so every skewed
    leg inverse-cdf samples this instead. Cached small: the millikey
    vocabulary's cdf is ~80 MB and two scenarios never need more."""
    ranks = np.arange(1, int(num_keys) + 1, dtype=np.float64)
    cdf = np.cumsum(1.0 / ranks ** float(s))
    cdf /= cdf[-1]
    cdf.setflags(write=False)
    return cdf


def zipf_keys(idx: np.ndarray, num_keys: int, s: float = 1.0,
              hot_perm: Optional[np.ndarray] = None) -> np.ndarray:
    """STATELESS bounded-zipf key draw for element indices `idx`.

    - the uniform variate is a splitmix64-style hash of the element index,
      NOT a chunk-seeded rng: host oracles re-generate the stream under
      different chunk boundaries, and a per-chunk seed would diverge;
    - rank -> key id is identity by default (key 0 is the hottest), or
      `hot_perm` (any permutation of [0, num_keys)) to place the hot
      RANKS deliberately — spread them to model independent hot tenants,
      or cluster them into one device's key range to model the adjacent
      hot-key-group shape the skew rebalancer exists to fix."""
    idx = np.asarray(idx)
    z = (idx.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    u = z.astype(np.float64) / 2.0 ** 64
    rank = np.searchsorted(zipf_bounded_cdf(num_keys, s), u)
    if hot_perm is not None:
        rank = np.asarray(hot_perm)[rank]
    return rank.astype(np.int64)


def step_bounds(t: int, B: int, slide_ms: int = SLIDE_MS):
    """Inclusive (smin, smax) slice bounds of step t's records."""
    smin = max((t * STEP_MS + STEP_MS // B - OOO_MS) // slide_ms, 0)
    smax = ((t + 1) * STEP_MS) // slide_ms
    return smin, smax


def host_step(t: int, B: int, bits_fn):
    """Regenerate step t's (keys, ts) on host, bit-identical to the device."""
    bits = bits_fn(t)
    keys = (bits & (NUM_KEYS - 1)).astype(np.int64)
    jitter = ((bits >> 13) % (OOO_MS + 1)).astype(np.int64)
    base = t * STEP_MS + ((np.arange(1, B + 1, dtype=np.int64) * STEP_MS) // B)
    ts = np.maximum(base - jitter, 0)
    return keys, ts


def make_bits_fn(B: int):
    """Host-side threefry bit stream (jitted on the cpu backend)."""
    import jax

    cpu = jax.devices("cpu")[0]
    base = jax.random.PRNGKey(SEED)

    @jax.jit
    def _bits(t):
        return jax.random.bits(jax.random.fold_in(base, t), (B,), "uint32")

    def bits_fn(t: int) -> np.ndarray:
        with jax.default_device(cpu):
            return np.asarray(_bits(t))

    return bits_fn


def make_device_gen(T: int, B: int, slide_ms: int = SLIDE_MS,
                    with_vals: bool = False, flat: bool = True,
                    nsb: int = NSB):
    """Jitted on-device generator: span of T steps -> idx [T*B] (or [T,B])
    int32, optionally with a value column derived from the same bits."""
    import jax
    import jax.numpy as jnp

    base = jax.random.PRNGKey(SEED)
    bb = jnp.arange(1, B + 1, dtype=jnp.int32)

    @jax.jit
    def gen(t0, smin_abs):
        def one(tr):
            t = t0 + tr
            bits = jax.random.bits(jax.random.fold_in(base, t), (B,), "uint32")
            kid = (bits & jnp.uint32(NUM_KEYS - 1)).astype(jnp.int32)
            jit_ = ((bits >> jnp.uint32(13)) % jnp.uint32(OOO_MS + 1)).astype(jnp.int32)
            ts = jnp.maximum(t * STEP_MS + (bb * STEP_MS) // B - jit_, 0)
            srel = ts // slide_ms - smin_abs[tr]
            idx = kid * nsb + srel
            if with_vals:
                val = ((bits >> jnp.uint32(23)) & jnp.uint32(0xFF)).astype(jnp.float32)
                return idx, val
            return idx

        out = jax.vmap(one)(jnp.arange(T, dtype=jnp.int32))
        if with_vals:
            idx, vals = out
            return (idx.reshape(-1), vals.reshape(-1)) if flat else (idx, vals)
        return out.reshape(-1) if flat else out

    return gen


def host_vals(bits: np.ndarray) -> np.ndarray:
    return ((bits >> 23) & 0xFF).astype(np.float32)


# ---------------------------------------------------------------------------
# CPU baseline: same slice-decomposed algorithm, single core, numpy
# ---------------------------------------------------------------------------

class NumpyWindower:
    """Incremental single-core reference; alg_seconds excludes generation."""

    S = 64

    def __init__(self, window_ms: int = WINDOW_MS, slide_ms: int = SLIDE_MS,
                 agg: str = "count"):
        self.window_ms = window_ms
        self.slide_ms = slide_ms
        self.agg = agg
        fill = 0 if agg in ("count", "sum") else -np.inf
        self.counts = np.full((NUM_KEYS, self.S), fill, dtype=np.float64)
        self.fired_upto = None
        self.fired = {}
        self.alg_seconds = 0.0
        self.events = 0

    def step(self, keys, ts, wm, vals=None):
        S, spw = self.S, self.window_ms // self.slide_ms
        t0 = time.perf_counter()
        s_abs = ts // self.slide_ms
        flat = keys * S + (s_abs % S)
        if self.agg == "count":
            self.counts += np.bincount(flat, minlength=NUM_KEYS * S).reshape(
                NUM_KEYS, S)
        elif self.agg == "sum":
            np.add.at(self.counts.reshape(-1), flat, vals)
        else:  # max
            np.maximum.at(self.counts.reshape(-1), flat, vals)
        self.events += len(keys)
        j_hi = (wm + 1 - self.window_ms) // self.slide_ms
        j_lo = self.fired_upto + 1 if self.fired_upto is not None else j_hi
        combine = np.max if self.agg == "max" else np.sum
        fill = 0 if self.agg in ("count", "sum") else -np.inf
        for j in range(j_lo, j_hi + 1):
            # windows with negative start exist for early records, matching
            # the reference's getWindowStartWithOffset arithmetic
            pos = np.arange(j, j + spw) % S
            self.fired[j] = combine(self.counts[:, pos], axis=1)
            self.counts[:, j % S] = fill
        if self.fired_upto is None or j_hi > self.fired_upto:
            self.fired_upto = j_hi
        self.alg_seconds += time.perf_counter() - t0


def _parity(cpu_fired, dev_fired, require_all: bool = True):
    """Window-by-window equality; with require_all=False (partial runs) only
    the windows the device actually fired are compared."""
    mismatches = 0
    checked = 0
    for j, crow in cpu_fired.items():
        drow = dev_fired.get(j)
        if drow is None:
            if require_all and crow.any():
                mismatches += 1
            continue
        checked += 1
        if not np.array_equal(crow.astype(np.int64), np.asarray(drow).astype(np.int64)):
            mismatches += 1
    ok = mismatches == 0 and (checked > 0 or not require_all)
    if require_all:
        nonempty = len([j for j, c in cpu_fired.items() if c.any()])
        ok = ok and len(dev_fired) >= nonempty
    return ok, checked


# ---------------------------------------------------------------------------
# TPU child
# ---------------------------------------------------------------------------

def _new_pipe(chunk: int, backend: str = "auto", window_ms: int = WINDOW_MS,
              slide_ms: int = SLIDE_MS, agg: str = "count",
              num_slices: int = 32, nsb: int = NSB, out_rows: int = 64,
              scope: str = "keyed"):
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.runtime.fused_window_pipeline import (
        FusedGlobalWindowPipeline,
        FusedWindowPipeline,
    )

    if scope == "global":
        # per-window GLOBAL aggregate (Q7 shape): keyed-partial ->
        # cross-segment fold, [S] state, scalar fire rows — on TPU the
        # whole dispatch is one pallas kernel (build_global_superscan)
        return FusedGlobalWindowPipeline(
            SlidingEventTimeWindows.of(window_ms, slide_ms),
            agg,
            num_slices=num_slices,
            nsb=nsb,
            fires_per_step=4,
            out_rows=out_rows,
            chunk=chunk,
            backend=backend,
        )
    if agg == "max8":
        # bounded-domain max (values are 8-bit here): rides the pallas MXU
        # nibble-histogram path, ~3x the scatter unit
        from flink_tpu.ops.aggregators import max_agg

        agg = max_agg(domain_bits=8)
    return FusedWindowPipeline(
        SlidingEventTimeWindows.of(window_ms, slide_ms),
        agg,
        key_capacity=NUM_KEYS,
        num_slices=num_slices,
        nsb=nsb,
        fires_per_step=4,
        out_rows=out_rows,
        chunk=chunk,
        backend=backend,
    )


def run_tpu_stream(T: int, B: int, spans: int, depth: int, t0_step: int = 0,
                   warmup: bool = True, window_ms: int = WINDOW_MS,
                   slide_ms: int = SLIDE_MS, agg: str = "count",
                   backend: str = "auto", resolve_field: Optional[str] = None,
                   postproc=None, num_slices: int = 32, nsb: int = NSB,
                   out_rows: int = 64, scope: str = "keyed"):
    """Pipelined on-device-generated stream; yields progress per resolve.

    agg 'count' streams only key/slice ids; 'sum'/'max' also stream a value
    column derived from the same threefry bits. `postproc(count_row,
    field_row)` maps a fired window's device rows before banking (e.g. the
    Q5 top-k cut); default keeps the count row (count agg) or field row.
    scope 'global' runs the global-window pipeline (scalar rows per fire)
    over the SAME staged idx streams — the kid part folds out by % NSB.
    """
    import jax
    import jax.numpy as jnp

    with_vals = agg != "count"
    pallas = backend != "xla"
    # count-only pallas dispatches fit CH=32768 int8 one-hots in VMEM
    # (measured ~1.7x the 8192-chunk rate); weighted stays at 8192 bf16;
    # max8's nibble-pass transients cap the chunk at 1024 with S=32/R=64
    chunk = (32768 if not with_vals else 8192) if pallas else 4096
    if agg == "max8":
        chunk = 1024

    def mk():
        return _new_pipe(chunk=chunk, backend=backend,
                         window_ms=window_ms, slide_ms=slide_ms, agg=agg,
                         num_slices=num_slices, nsb=nsb, out_rows=out_rows,
                         scope=scope)

    pipe = mk()
    gen = make_device_gen(T, B, slide_ms=slide_ms, with_vals=with_vals,
                          flat=pallas, nsb=nsb)

    def stage(p, lo):
        bounds = [step_bounds(lo + r, B, slide_ms) for r in range(T)]
        wms = [(lo + r + 1) * STEP_MS - WM_DELAY_MS for r in range(T)]
        plan, smin_abs = p.plan_superbatch(bounds, wms)
        out = gen(jnp.int32(lo), jnp.asarray(smin_abs))
        if with_vals:
            idx, vals = out
        else:
            idx, vals = out, jnp.zeros((T, 1), jnp.float32)
        return (idx, vals, plan)

    if warmup:
        # compile gen + superscan + staging shapes on a throwaway pipe (the
        # compiled executables are shared via module-level caches), so the
        # timed region below measures steady-state streaming only
        wpipe = mk()
        wpipe.process_superbatch(None, None, staged=stage(wpipe, t0_step))
        del wpipe

    # observability: host time split per pipeline stage — plan+generate+
    # enqueue (dispatch) vs blocked in resolve (readback; the host's
    # "backpressured by the device" condition)
    stage_time = {"plan_stage_dispatch": 0.0, "superscan_resolve_block": 0.0}

    def enqueue(i):
        t0 = time.perf_counter()
        d = pipe.process_superbatch(
            None, None, staged=stage(pipe, t0_step + i * T), defer=True,
        )
        stage_time["plan_stage_dispatch"] += time.perf_counter() - t0
        return d, time.perf_counter()

    fired = {}
    span_lat = []
    t_first = time.perf_counter()
    inflight = []
    for i in range(min(depth, spans)):
        inflight.append(enqueue(i))
    next_i = len(inflight)
    resolved = 0
    while inflight:
        d, t_enq = inflight.pop(0)
        t_res0 = time.perf_counter()
        for window, counts, fields in d.resolve():
            row = fields[resolve_field] if resolve_field else counts
            if postproc is not None:
                row = postproc(counts, row)
            fired[window.start // slide_ms] = row
        stage_time["superscan_resolve_block"] += time.perf_counter() - t_res0
        span_lat.append((time.perf_counter() - t_enq) * 1000.0)
        resolved += 1
        if next_i < spans:
            inflight.append(enqueue(next_i))
            next_i += 1
        yield_partial = resolved < spans
        elapsed = time.perf_counter() - t_first
        yield {
            "events": resolved * T * B,
            "elapsed": elapsed,
            "fired": fired,
            "span_latency_ms": span_lat,
            "stage_time_s": dict(stage_time),
            # the pipeline's ACTUAL kernel decision, not a backend guess:
            # a geometry that trips the pallas support gate must show up
            # in the artifact as the XLA fallback it really ran
            "used_pallas": bool(pipe._use_pallas()),
            "final": not yield_partial,
        }


def child_tpu(T: int, B: int, spans: int) -> None:
    import jax

    _emit({"event": "start", "device": "tpu", "pid": os.getpid()})
    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass
    t0 = time.perf_counter()
    devs = jax.devices()
    _emit({"event": "backend_ready", "platform": devs[0].platform,
           "init_s": round(time.perf_counter() - t0, 1)})

    def result_json(tps, vsb, parity, checked, lat_ms, events, extra,
                    batch_size=B):
        res = {
            "metric": "ysb_sliding_count_tuples_per_sec",
            "value": round(tps, 1),
            "unit": "tuples/s/chip",
            "vs_baseline": round(vsb, 3),
            "hbm_gbps": float(f"{hbm_gbps(events, events / max(tps, 1e-9), batch=batch_size):.3g}"),
            "parity": parity,
            "windows_checked": checked,
            "p99_flush_latency_ms": round(
                float(np.percentile(lat_ms, 99)), 1) if lat_ms else 0.0,
            "events": events,
            "num_keys": NUM_KEYS,
            "window_ms": WINDOW_MS,
            "slide_ms": SLIDE_MS,
            "device": "tpu",
            "kernel": "pallas_superscan",
            "data_source": "on_device_threefry_generator",
        }
        res.update(extra)
        return res

    # ---- quick numpy-baseline estimate (for partial-result ratios) ----
    bits_small = make_bits_fn(1 << 18)
    est = NumpyWindower()
    for t in range(8):
        keys, ts = host_step(t, 1 << 18, bits_small)
        est.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    cpu_tps_est = est.events / max(est.alg_seconds, 1e-9)
    _emit({"event": "cpu_baseline_estimate", "tuples_per_sec": round(cpu_tps_est)})

    # ---- tiny first measurement: parity-checked TPU number, banked fast ----
    tiny_T, tiny_B, tiny_spans = 8, 1 << 18, 2
    t0 = time.perf_counter()
    last = None
    for prog in run_tpu_stream(tiny_T, tiny_B, tiny_spans, depth=2):
        last = prog
    ref = NumpyWindower()
    for t in range(tiny_T * tiny_spans):
        keys, ts = host_step(t, tiny_B, bits_small)
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    ok, checked = _parity(ref.fired, last["fired"], require_all=True)
    tiny_tps = last["events"] / last["elapsed"]
    _emit({"event": "span_done", "phase": "tiny",
           "partial_result": result_json(
               tiny_tps, tiny_tps / cpu_tps_est, bool(ok), checked,
               last["span_latency_ms"], last["events"],
               {"partial": True, "scale": "small",
                "observability": observability_snapshot(
                    last.get("stage_time_s"), last["elapsed"]),
                "wall_from_backend_ready_s": round(time.perf_counter() - t0, 1)},
               batch_size=tiny_B)})

    # ---- main run ----
    t_compile = time.perf_counter()
    last = None
    for prog in run_tpu_stream(T, B, spans, depth=PIPE_DEPTH):
        last = prog
        if not prog["final"]:
            tps = prog["events"] / prog["elapsed"]
            _emit({"event": "span_done", "phase": "main",
                   "partial_result": result_json(
                       tps, tps / cpu_tps_est, "deferred", 0,
                       prog["span_latency_ms"], prog["events"],
                       {"partial": True})})
    tps = last["events"] / last["elapsed"]
    _emit({"event": "main_done", "tuples_per_sec": round(tps),
           "elapsed_s": round(last["elapsed"], 3),
           "incl_warmup_s": round(time.perf_counter() - t_compile, 1)})

    # ---- untimed: full host replay for parity + the real baseline ----
    bits_fn = make_bits_fn(B)
    ref = NumpyWindower()
    for t in range(T * spans):
        keys, ts = host_step(t, B, bits_fn)
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
        if t % 64 == 63:
            _emit({"event": "replay_progress", "steps": t + 1})
    cpu_tps = ref.events / max(ref.alg_seconds, 1e-9)
    ok, checked = _parity(ref.fired, last["fired"], require_all=True)
    res = result_json(
        tps, tps / cpu_tps, bool(ok), checked,
        last["span_latency_ms"], last["events"],
        {"cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
         "span_steps": T, "batch": B, "spans": spans,
         "pipeline_depth": PIPE_DEPTH,
         "late_dropped": 0,
         "observability": observability_snapshot(
             last.get("stage_time_s"), last["elapsed"])},
    )
    _emit({"event": "result", "result": res})

    # secondary BASELINE configs ride the same artifact; the banked headline
    # above survives any failure here
    if os.environ.get("BENCH_SECONDARY", "1") == "1":
        res["secondary"] = run_secondary_configs(headline_ref=ref)
    _emit({"event": "result_final", "result": res})


# ---------------------------------------------------------------------------
# secondary BASELINE configs (1: WordCount tumbling, 3: session reduce,
# 4: Nexmark Q5 top-k, 5: Nexmark Q7 global max) — each guarded so the
# headline result survives any secondary failure
# ---------------------------------------------------------------------------

def roofline_keys(events: int, tps: float, *, batch: int,
                  num_keys: int = NUM_KEYS, num_slices: int = 32,
                  bytes_per_record: int = 8,
                  flops_per_record: float = 2.0) -> dict:
    """Per-scenario roofline attribution for the secondary blocks: the
    same analytic lower-bound traffic model as `hbm_gbps` (records
    streamed + ring read/write per step) over the platform peak table
    (metrics/device_stats.platform_peaks — calibrate with
    observability.device.hbm-gbps on real chips). These keys make a
    laggard regression ATTRIBUTABLE from the artifact alone: a scenario
    whose throughput drops while hbm_utilization_pct holds is
    compute/overhead-bound, one whose utilization drops with it lost
    memory-level parallelism."""
    from flink_tpu.metrics.device_stats import platform_peaks

    hbm_peak_gbps, peak_tflops = platform_peaks(0, 0)
    elapsed = events / max(tps, 1e-9)
    gbps = hbm_gbps(events, elapsed, batch=batch, num_keys=num_keys,
                    num_slices=num_slices, bytes_per_record=bytes_per_record)
    tflops = events * flops_per_record / max(elapsed, 1e-9) / 1e12
    return {
        "hbm_utilization_pct": round(100.0 * gbps / max(hbm_peak_gbps, 1e-9), 2),
        "flops_utilization_pct": round(100.0 * tflops / max(peak_tflops, 1e-9), 3),
    }

def _replay(window_ms, slide_ms, agg, T, B, bits_fn):
    ref = NumpyWindower(window_ms, slide_ms, agg)
    for t in range(T):
        bits = bits_fn(t)
        keys = (bits & (NUM_KEYS - 1)).astype(np.int64)
        jitter = ((bits >> 13) % (OOO_MS + 1)).astype(np.int64)
        base = t * STEP_MS + ((np.arange(1, B + 1, dtype=np.int64) * STEP_MS) // B)
        ts = np.maximum(base - jitter, 0)
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS,
                 vals=host_vals(bits))
    return ref


def secondary_wordcount(bits_fn) -> dict:
    """Config 1: WordCount keyBy().sum() over 1s tumbling windows (the
    count of 1s == sum of ones; pallas superscan, tumbling geometry)."""
    T, B, spans = 24, 1 << 20, 2
    last = None
    for prog in run_tpu_stream(T, B, spans, depth=2, t0_step=0,
                               window_ms=1000, slide_ms=1000):
        last = prog
    ref = _replay(1000, 1000, "count", T * spans, B, bits_fn)
    ok, checked = _parity(ref.fired, last["fired"], require_all=True)
    tps = last["events"] / last["elapsed"]
    return {
        "metric": "wordcount_tumbling_count_tuples_per_sec",
        "value": round(tps, 1),
        "vs_baseline": round(tps / (ref.events / max(ref.alg_seconds, 1e-9)), 3),
        "parity": bool(ok),
        "windows_checked": checked,
        "events": last["events"],
        **roofline_keys(last["events"], tps, batch=B, num_slices=32),
    }


def secondary_q5_topk(headline_ref) -> dict:
    """Config 4: Nexmark Q5 hot items — sliding count + top-10 per window.
    The top-k cut runs per fired window; parity compares the sorted top-10
    multiset (tie-insensitive). Reuses the headline replay (same stream
    prefix) instead of re-running minutes of single-core numpy."""
    N = 10
    T, B, spans = SPAN_STEPS, 1 << LOG2_BATCH, 2

    def topk(counts, _row):
        part = np.partition(counts, len(counts) - N)[-N:]
        return np.sort(part)[::-1]

    last = None
    for prog in run_tpu_stream(T, B, spans, depth=2, postproc=topk):
        last = prog
    ref = headline_ref
    mismatch = 0
    for j, row in last["fired"].items():
        expect = np.sort(np.partition(ref.fired[j], NUM_KEYS - N)[-N:])[::-1]
        if not np.array_equal(np.asarray(row, dtype=np.int64),
                              expect.astype(np.int64)):
            mismatch += 1
    tps = last["events"] / last["elapsed"]
    return {
        "metric": "nexmark_q5_topk_tuples_per_sec",
        "value": round(tps, 1),
        "vs_baseline": round(tps / (ref.events / max(ref.alg_seconds, 1e-9)), 3),
        "parity": mismatch == 0 and len(last["fired"]) > 0,
        "windows_checked": len(last["fired"]),
        "top_n": N,
        "events": last["events"],
        **roofline_keys(last["events"], tps, batch=B, num_slices=32),
    }


def secondary_q7_global_max(bits_fn_small) -> dict:
    """Config 5: Nexmark Q7 — global per-window max. ISSUE-14 moved this
    laggard (14.6x at r05) off the dense keyed nibble-histogram reduction
    onto the GLOBAL-window superscan: keyed partials per rel-slice fold
    cross-segment into a [S] ring (the single-chip analogue of the mesh's
    psum/pmax merge), window fires are ONE scalar each, and on TPU the
    whole T-step dispatch is one pallas kernel with the ring resident in
    a single VMEM row (ops/pallas_superscan.build_global_superscan). The
    per-chunk cost drops from two conditional [16*NSB*K/128, CH] nibble
    histograms + a [R, K] readback to NSB masked whole-chunk folds + R
    scalars. Values stay 8-bit for the baseline replay, but the fold is
    elementwise — unbounded max has a device form on this path."""
    T, B, spans = 96, 1 << 18, 5

    def gmax(_counts, row):
        return float(np.max(row))

    last = None
    for prog in run_tpu_stream(T, B, spans, depth=3, window_ms=10_000,
                               slide_ms=10_000, agg="max",
                               resolve_field="max", postproc=gmax,
                               num_slices=8, nsb=2, out_rows=16,
                               backend="auto", scope="global"):
        last = prog
    import jax
    if jax.default_backend() == "tpu" and not last["used_pallas"]:
        # the 25x bar is judged on the pallas kernel; a geometry change
        # that trips supports_global must fail the scenario loudly, not
        # silently bank the XLA fallback's number under the same metric
        raise RuntimeError(
            "q7 global-max ran the XLA scan fallback on TPU — "
            "pallas_superscan.supports_global stopped selecting")
    ref = _replay(10_000, 10_000, "max", T * spans, B, bits_fn_small)
    mismatch = 0
    for j, got in last["fired"].items():
        if abs(float(np.max(ref.fired[j])) - got) > 1e-3:
            mismatch += 1
    tps = last["events"] / last["elapsed"]

    # the global path replaced the keyed nibble-histogram reduction HERE,
    # but the bounded-domain max8 MXU path stays shipped and selectable —
    # keep one bench driver on it (short keyed leg, same stream prefix +
    # replay) so a nibble-kernel regression stays visible in the artifact;
    # backend='pallas' raises rather than silently falling back, as before
    k_last = None
    for prog in run_tpu_stream(24, B, 2, depth=2, window_ms=10_000,
                               slide_ms=10_000, agg="max8",
                               resolve_field="max", postproc=gmax,
                               num_slices=8, nsb=2, out_rows=16,
                               backend="pallas"):
        k_last = prog
    k_mismatch = 0
    for j, got in k_last["fired"].items():
        if abs(float(np.max(ref.fired[j])) - got) > 1e-3:
            k_mismatch += 1
    keyed_parity = k_mismatch == 0 and len(k_last["fired"]) > 0

    return {
        "metric": "nexmark_q7_global_max_tuples_per_sec",
        "value": round(tps, 1),
        "vs_baseline": round(tps / (ref.events / max(ref.alg_seconds, 1e-9)), 3),
        "parity": mismatch == 0 and len(last["fired"]) > 0 and keyed_parity,
        "windows_checked": len(last["fired"]),
        "events": last["events"],
        "kernel": ("pallas_global_superscan" if last["used_pallas"]
                   else "global_superscan_xla"),
        "keyed_max8_tuples_per_sec": round(
            k_last["events"] / k_last["elapsed"], 1),
        "keyed_max8_windows_checked": len(k_last["fired"]),
        # the global scan holds a [S] ring, not [K, S]: the traffic model
        # is the streamed records themselves (num_keys=1 zeroes the ring
        # term, which is bytes-exact here)
        **roofline_keys(last["events"], tps, batch=B, num_keys=1,
                        num_slices=8, bytes_per_record=8),
    }


def _numpy_sessionize(keys, ts, vals, gap):
    """Single-core batch sessionizer: sort by (key, ts), split where the key
    changes or the gap exceeds `gap`, segment-sum the values."""
    order = np.lexsort((ts, keys))
    k, t, v = keys[order], ts[order], vals[order]
    brk = np.empty(len(k), dtype=bool)
    brk[0] = True
    brk[1:] = (k[1:] != k[:-1]) | (t[1:] - t[:-1] > gap)
    starts = np.flatnonzero(brk)
    sums = np.add.reduceat(v, starts)
    ends = np.r_[starts[1:], len(k)] - 1
    return {
        (int(k[s]), int(t[s]), int(t[e]) + gap): float(sv)
        for s, e, sv in zip(starts, ends, sums)
    }


def secondary_sessions() -> dict:
    """Config 3: clickstream sessionization (session windows + sum reduce)
    on the device session operator. ISSUE-14 moved this laggard (9.8x at
    r05) onto the fused session superspan: 16 staged ingest steps AND
    their in-scan gap-merges run as ONE device dispatch with ONE packed
    emission readback (ops/superscan.make_session_superscan) — sessions
    coalesce inside the scan carry and never round-trip to host per merge,
    where the old path paid one ingest dispatch + one merge dispatch + one
    packed D2H per 8 steps. The stream rotates its active key set so
    sessions actually close; records are synthesized ON DEVICE with the
    host replaying identical bits for the single-core baseline + parity,
    like the headline config."""
    from flink_tpu.api.windowing.assigners import EventTimeSessionWindows
    from flink_tpu.runtime.tpu_session_operator import TpuSessionWindowOperator

    import jax
    import jax.numpy as jnp

    gap = 2000
    B, nb = 1 << 20, 16
    SPAN = 8                       # merge cadence (= the key-rotation
    #                                period; worst-case session emission
    #                                lag stays under 3 gaps)
    SUPER = 16                     # steps fused per superspan dispatch
    #                                (the whole 16-step workload: every
    #                                ingest and both merges in ONE program)
    S = 64
    base_key = jax.random.PRNGKey(SEED + 7)
    cpu = jax.devices("cpu")[0]
    bb_i32 = jnp.arange(1, B + 1, dtype=jnp.int32)

    @jax.jit
    def gen_super(t0):
        """SUPER steps generated in one dispatch as [T, B] staged arrays
        for one fused superspan — one generator + one operator dispatch
        per 16 steps instead of per 8."""
        def one(tr):
            t = t0 + tr
            bits = jax.random.bits(jax.random.fold_in(base_key, t), (B,), "uint32")
            active = (t >> 2) & 3
            kid = ((bits & jnp.uint32(4095)) | (active.astype(jnp.uint32) << 12)
                   ).astype(jnp.int32)
            jit_ = ((bits >> jnp.uint32(13)) % jnp.uint32(OOO_MS + 1)).astype(jnp.int32)
            ts = jnp.maximum(t * STEP_MS + (bb_i32 * STEP_MS) // B - jit_, 0)
            s_abs = ts // gap
            return kid, (s_abs % S).astype(jnp.int32), (ts - s_abs * gap), \
                ((bits >> jnp.uint32(23)) & jnp.uint32(0xFF)).astype(jnp.float32)

        return jax.vmap(one)(jnp.arange(SUPER, dtype=jnp.int32))

    def host_batch(t):
        with jax.default_device(cpu):
            bits = np.asarray(jax.random.bits(
                jax.random.fold_in(base_key, jnp.int32(t)), (B,), "uint32"))
        active = (t >> 2) & 3
        keys = ((bits & 4095) | (active << 12)).astype(np.int64)
        jitter = ((bits >> 13) % (OOO_MS + 1)).astype(np.int64)
        bb = np.arange(1, B + 1, dtype=np.int64)
        ts = np.maximum(t * STEP_MS + (bb * STEP_MS) // B - jitter, 0)
        return keys, host_vals(bits), ts

    def bounds(t):
        smin = max((t * STEP_MS + STEP_MS // B - OOO_MS) // gap, 0)
        smax = ((t + 1) * STEP_MS) // gap
        return smin, smax

    def mk():
        return TpuSessionWindowOperator(
            EventTimeSessionWindows.with_gap(gap), "sum",
            key_capacity=1 << 14, num_slices=S,
            defer_emissions=True,    # merge scans enqueue without syncs
        )

    def superspan_args(lo):
        """[T, B] staged arrays + per-step bounds + merge schedule for one
        fused superspan starting at step `lo` (merge every SPAN steps —
        the same watermark cadence the per-span path used, so emissions
        are bit-identical; only the dispatch count changes)."""
        k, sp, rel, v = gen_super(jnp.int32(lo))
        step_bounds = [bounds(lo + r) for r in range(SUPER)]
        merge_wms = [
            ((lo + r + 1) * STEP_MS - WM_DELAY_MS)
            if (r + 1) % SPAN == 0 else None
            for r in range(SUPER)
        ]
        return k, sp, rel, v, step_bounds, merge_wms

    # warmup: replay the WHOLE loop on a throwaway operator so the fused
    # superspan (and generator) shapes are compiled — threefry determinism
    # makes this an exact dry run of the timed region
    warm = mk()
    for lo in range(0, nb, SUPER):
        warm.process_superspan_staged(*superspan_args(lo))
    warm.process_watermark(1 << 60)
    warm.drain_output()
    del warm

    op = mk()
    out = []
    t0 = time.perf_counter()
    for lo in range(0, nb, SUPER):
        op.process_superspan_staged(*superspan_args(lo))
    op.process_watermark(1 << 60)
    out.extend(op.drain_output())   # resolves the deferred packed arrays
    elapsed = time.perf_counter() - t0
    events = nb * B

    data = [host_batch(t) for t in range(nb)]
    all_k = np.concatenate([d[0] for d in data])
    all_v = np.concatenate([d[1] for d in data])
    all_t = np.concatenate([d[2] for d in data])
    t0 = time.perf_counter()
    expect = _numpy_sessionize(all_k, all_t, all_v, gap)
    base_s = time.perf_counter() - t0
    got = {
        (int(k), w.start, w.end): float(r) for (k, w, r, _t) in out
    }
    parity = (
        len(got) > 0
        and got.keys() == expect.keys()
        and all(abs(got[k] - expect[k]) <= 1e-3 * max(1.0, abs(expect[k]))
                for k in got)
    )
    tps = events / elapsed
    return {
        "metric": "session_sum_tuples_per_sec",
        "value": round(tps, 1),
        "vs_baseline": round(tps / (events / max(base_s, 1e-9)), 3),
        "parity": bool(parity),
        "sessions_emitted": len(got),
        "gap_ms": gap,
        "events": events,
        "kernel": "session_superscan",
        "dispatches": -(-nb // SUPER),
        "data_source": "on_device_threefry_generator",
        # session ring: cnt+mn+mx+sum = 4 arrays of [K, S] i32/f32; each
        # record streams (kid, spos, rel, val) = 16 B
        **roofline_keys(events, tps, batch=B, num_keys=4 * (1 << 14),
                        num_slices=S, bytes_per_record=16),
    }


def run_secondary_configs(headline_ref=None) -> dict:
    sec = {}
    bits_big = make_bits_fn(1 << 20)
    bits_small = make_bits_fn(1 << 18)
    if headline_ref is None:
        headline_ref = _replay(WINDOW_MS, SLIDE_MS, "count",
                               SPAN_STEPS * 2, 1 << LOG2_BATCH,
                               make_bits_fn(1 << LOG2_BATCH))
    for name, fn in (
        ("wordcount_tumbling_count", lambda: secondary_wordcount(bits_big)),
        ("nexmark_q5_topk", lambda: secondary_q5_topk(headline_ref)),
        ("nexmark_q7_global_max", lambda: secondary_q7_global_max(bits_small)),
        ("session_sum", secondary_sessions),
    ):
        t0 = time.perf_counter()
        try:
            sec[name] = fn()
            sec[name]["wall_s"] = round(time.perf_counter() - t0, 1)
        except Exception as e:  # noqa: BLE001 — headline must survive
            sec[name] = {"error": repr(e)[:300]}
        _emit({"event": "secondary_done", "config": name, "result": sec[name]})
    return sec


# ---------------------------------------------------------------------------
# CPU safety-net child: XLA superscan on the cpu backend, host-staged
# ---------------------------------------------------------------------------

def child_cpu(T: int, B: int, spans: int) -> None:
    _emit({"event": "start", "device": "cpu-jit", "pid": os.getpid()})
    import jax

    # The TPU relay's sitecustomize hook force-sets jax_platforms="axon,cpu";
    # the relay is single-client and a probe from a second process wedges.
    # Drop the factory so the safety-net child can never touch the chip.
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
    _xb._topology_factories.pop("axon", None)

    devs = jax.devices()
    _emit({"event": "backend_ready", "platform": devs[0].platform})

    bits_fn = make_bits_fn(B)
    ref = NumpyWindower()
    steps_data = []
    for t in range(T * spans):
        keys, ts = host_step(t, B, bits_fn)
        steps_data.append((keys.astype(np.int32), None, ts))
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    cpu_tps = ref.events / max(ref.alg_seconds, 1e-9)
    _emit({"event": "cpu_baseline", "tuples_per_sec": round(cpu_tps)})

    pipe = _new_pipe(chunk=4096, backend="xla")
    wms = [(t + 1) * STEP_MS - WM_DELAY_MS for t in range(T * spans)]
    # warmup compile on the first span shape
    warm = _new_pipe(chunk=4096, backend="xla")
    warm.process_superbatch(steps_data[:T], wms[:T])

    fired = {}
    lat = []
    stage_time = {"plan_stage_dispatch": 0.0, "superscan_resolve_block": 0.0}
    t0 = time.perf_counter()
    prev = None
    n = 0
    for i in range(spans):
        lo, hi = i * T, (i + 1) * T
        t_enq = time.perf_counter()
        d = pipe.process_superbatch(steps_data[lo:hi], wms[lo:hi], defer=True)
        stage_time["plan_stage_dispatch"] += time.perf_counter() - t_enq
        if prev is not None:
            pd, pt, pn = prev
            t_res = time.perf_counter()
            for w, c, _f in pd.resolve():
                fired[w.start // SLIDE_MS] = c
            stage_time["superscan_resolve_block"] += time.perf_counter() - t_res
            lat.append((time.perf_counter() - pt) * 1000.0)
            n += pn
        prev = (d, t_enq, sum(len(b[2]) for b in steps_data[lo:hi]))
    pd, pt, pn = prev
    t_res = time.perf_counter()
    for w, c, _f in pd.resolve():
        fired[w.start // SLIDE_MS] = c
    stage_time["superscan_resolve_block"] += time.perf_counter() - t_res
    lat.append((time.perf_counter() - pt) * 1000.0)
    n += pn
    elapsed = time.perf_counter() - t0
    ok, checked = _parity(ref.fired, fired, require_all=True)
    tps = n / elapsed
    _emit({"event": "result", "result": {
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": round(tps, 1),
        "unit": "tuples/s/chip",
        "vs_baseline": round(tps / cpu_tps, 3),
        "hbm_gbps": float(f"{hbm_gbps(n, elapsed, batch=B):.3g}"),
        "cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
        "parity": bool(ok),
        "windows_checked": checked,
        "p99_flush_latency_ms": round(float(np.percentile(lat, 99)), 1),
        "events": n,
        "device": "cpu-jit",
        "kernel": "xla_superscan",
        "observability": observability_snapshot(stage_time, elapsed),
    }})


# ---------------------------------------------------------------------------
# parent: supervisor
# ---------------------------------------------------------------------------

class Child:
    def __init__(self, name: str, env: dict, argv_extra: list):
        self.name = name
        self.best_partial = None
        self.result = None
        full_env = dict(os.environ)
        full_env.update(env)
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"] + argv_extra,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=full_env, text=True,
        )
        self.events = {}
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            ev = obj.get("event")
            if ev:
                self.events[ev] = obj
            if ev == "span_done" and obj.get("partial_result"):
                pr = obj["partial_result"]
                # prefer parity-checked partials; otherwise latest/biggest
                if (self.best_partial is None
                        or pr.get("parity") is True
                        or (self.best_partial.get("parity") is not True
                            and pr.get("events", 0) >= self.best_partial.get("events", 0))):
                    self.best_partial = pr
            if ev == "result":
                self.result = obj["result"]

    def alive(self):
        return self.proc.poll() is None

    def join_output(self, timeout: float = 5.0):
        self._t.join(timeout)

    def kill(self):
        try:
            self.proc.send_signal(signal.SIGKILL)
        except Exception:
            pass


_CHILDREN: list = []


# ---------------------------------------------------------------------------
# dataplane microbench: localhost exchange, 1 MiB columnar batches
# ---------------------------------------------------------------------------

def dataplane_microbench(batches: int = 24, max_sweeps: int = 12,
                         min_sweeps: int = 6, budget_s: float = 120.0) -> dict:
    """Cross-host exchange throughput over the REAL dataplane stack
    (ExchangeServer + OutputChannel on loopback): 1 MiB columnar batches
    — 64k float64 values + 64k int64 timestamps — on the zero-copy binary
    columnar wire vs the legacy pickle wire, with transport auth on and
    off. Emits exchange_gbps_{pickle,binary}[_noauth] so the serialization
    tax removed by ISSUE-3 stays tracked in the bench trajectory.

    Protocol: configurations are sampled in interleaved sweeps (so a calm
    or noisy scheduling window hits all of them, not just one) and each
    reports the BEST sweep — throughput microbenchmarks on shared or
    sandboxed hosts see multi-x scheduler noise, and max-of-N estimates
    the wire's capability the way min-of-N estimates latency. Ring
    capacity exceeds the batch count so credit flow never throttles the
    measurement. Sweeping stops early only on CONVERGENCE — two
    consecutive sweeps that improve no configuration's best by more than
    3% — never on the value of the ratio itself, so the stop rule cannot
    bias the reported numbers toward any threshold."""
    import threading as _threading

    from flink_tpu.runtime.dataplane import ExchangeServer, OutputChannel
    from flink_tpu.security.transport import SecurityConfig

    vals = np.random.default_rng(0).random(1 << 16)       # 512 KiB float64
    ts = np.arange(1 << 16, dtype=np.int64)               # 512 KiB int64
    payload = ("b", vals, ts)
    nbytes = vals.nbytes + ts.nbytes

    def one_rep(wire_format: str, security) -> float:
        warm = 4
        server = ExchangeServer(capacity=batches + warm + 1,
                                wire_format=wire_format, security=security)
        ch = server.channel("bench")
        out = OutputChannel(server.address, "bench",
                            wire_format=wire_format, security=security)
        done = _threading.Event()

        def consume():
            for _ in range(batches + warm):
                ch.poll(timeout=30)
            done.set()

        t = _threading.Thread(target=consume, daemon=True)
        t.start()
        for _ in range(warm):
            out.send(payload)
        t0 = time.perf_counter()
        for _ in range(batches):
            out.send(payload)
        done.wait(timeout=60)
        dt = time.perf_counter() - t0
        out.end()
        out.close()
        server.stop()
        return batches * nbytes / dt / 1e9

    configs = {
        "exchange_gbps_pickle": ("pickle", None),
        "exchange_gbps_binary": ("binary", None),
        "exchange_gbps_pickle_noauth": ("pickle", SecurityConfig.disabled()),
        "exchange_gbps_binary_noauth": ("binary", SecurityConfig.disabled()),
    }
    seen: dict = {k: 0.0 for k in configs}
    sweeps = 0
    flat_sweeps = 0
    # hard wall-clock cap: the microbench shares the bench's fixed budget
    # with the TPU attempts — a deadlocked exchange (60 s rep timeouts)
    # must not eat the window that produces the headline metric
    bench_deadline = time.perf_counter() + budget_s
    for sweep in range(max_sweeps):
        improved = False
        for key, (fmt, sec) in configs.items():
            if time.perf_counter() > bench_deadline:
                break
            got = one_rep(fmt, sec)
            if got > seen[key] * 1.03:
                improved = True
            seen[key] = max(seen[key], got)
        sweeps = sweep + 1
        flat_sweeps = 0 if improved else flat_sweeps + 1
        if sweeps >= min_sweeps and flat_sweeps >= 2:
            break
        if time.perf_counter() > bench_deadline:
            break

    res: dict = {"batch_bytes": nbytes, "batches": batches, "sweeps": sweeps}
    res.update({k: round(v, 3) for k, v in seen.items()})
    res["binary_vs_pickle_auth"] = round(
        res["exchange_gbps_binary"] / max(res["exchange_gbps_pickle"], 1e-9), 2)
    return res


def checkpoint_microbench(events: int = 100_000, reps: int = 2) -> dict:
    """Checkpoint overhead on the windowed hot path: the keyed tumbling
    pipeline at a FIXED event count, checkpointing off vs on (Fs storage,
    25 ms interval — several snapshots per run), best-of-reps each (wall
    time is latency-like: min-of-N estimates the cost floor). Emits
    checkpoint.{overhead_pct, last_duration_ms, last_size_bytes} so the
    fault-tolerance tax stays tracked in the bench trajectory alongside
    the throughput headline."""
    import shutil
    import tempfile

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import (
        CheckpointingOptions,
        Configuration,
        ExecutionOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.utils.arrays import obj_array

    def gen(idx):
        vals = obj_array([(int(i) & 63, 1.0) for i in idx])
        return Batch(vals, (idx * 10).astype(np.int64))

    def run_once(chk_dir):
        config = Configuration()
        config.set(ExecutionOptions.BATCH_SIZE, 8192)
        if chk_dir is not None:
            config.set(CheckpointingOptions.INTERVAL_MS, 25)
            config.set(CheckpointingOptions.DIRECTORY, chk_dir)
        env = StreamExecutionEnvironment(config)
        stream = env.from_source(
            DataGeneratorSource(gen, count=events),
            watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
        )
        (stream.key_by(lambda x: x[0])
               .window(TumblingEventTimeWindows.of(1000)).count()
               .sink_to(CollectSink()))
        t0 = time.perf_counter()
        client = env.execute_async("checkpoint-bench")
        status = client.wait(240)
        dt = time.perf_counter() - t0
        if status.value != "FINISHED":
            raise RuntimeError(f"bench job ended {status.value}")
        return dt, client

    best_off = best_on = float("inf")
    best_on_client = None
    run_once(None)        # warmup: jit compiles must not bill the OFF config
    for _ in range(reps):
        dt, _c = run_once(None)
        best_off = min(best_off, dt)
        chk = tempfile.mkdtemp(prefix="flink-tpu-cpbench-")
        try:
            dt, client = run_once(chk)
        finally:
            shutil.rmtree(chk, ignore_errors=True)
        if dt < best_on:
            best_on, best_on_client = dt, client
    gauges = best_on_client.checkpoint_stats.gauge_values()
    return {
        "events": events,
        "elapsed_off_s": round(best_off, 3),
        "elapsed_on_s": round(best_on, 3),
        "checkpoints_completed": int(gauges["numberOfCompletedCheckpoints"]),
        "overhead_pct": round((best_on - best_off) / max(best_off, 1e-9) * 100, 2),
        "last_duration_ms": round(float(gauges["lastCheckpointDuration"]), 3),
        "last_size_bytes": int(gauges["lastCheckpointSize"]),
    }


class _ScenarioWindows:
    """Tumbling assigner with an amortized per-record service cost that
    releases the GIL (bulk sleeps), so extra shard threads genuinely add
    capacity: the saturation the autoscaler must detect is real, and the
    recovery it buys is measurable, even inside one bench process."""

    def __init__(self, size_ms, cost_s, bulk=150):
        from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows

        self._inner = TumblingEventTimeWindows.of(size_ms)
        self.cost_s = cost_s
        self.bulk = bulk
        self._n = 0

    def __getattr__(self, name):
        if name.startswith("_"):      # never proxy dunders/privates: the
            raise AttributeError(name)  # unpickle path probes them before
        return getattr(self._inner, name)  # _inner exists

    def assign_windows(self, element, timestamp):
        self._n += 1
        if self._n % self.bulk == 0:
            time.sleep(self.cost_s * self.bulk)
        return self._inner.assign_windows(element, timestamp)


class _ScenarioSource:
    """Arrival-paced 2x load-step source (picklable): profile[s] records in
    step s across shards, sliced per shard; step s blocks until its
    scheduled arrival (re-anchored per attempt, so replay stays paced)."""

    def __init__(self, profile, interval_s):
        self.profile = list(profile)
        self.interval_s = interval_s

    def __call__(self, shard, num_shards):
        outer = self

        class _Paced(list):
            def __init__(self):
                super().__init__(range(len(outer.profile)))
                self._anchor = None

            def __getitem__(self, s):
                now = time.monotonic()
                if self._anchor is None:
                    self._anchor = (now, s)
                due = self._anchor[0] + (s - self._anchor[1]) * outer.interval_s
                if due > now:
                    time.sleep(due - now)
                rng = np.random.default_rng(4000 + s)
                n = outer.profile[s]
                keys = rng.integers(0, 64, n).astype(np.int64)
                vals = np.ones(n, dtype=np.float64)
                ts = (s * 1000 + rng.integers(0, 1000, n)).astype(np.int64)
                sl = slice(shard, None, num_shards)
                return keys[sl], vals[sl], ts[sl], s * 1000 + 500

        return _Paced()


def autoscaler_scenario(pre_steps: int = 30, high_steps: int = 100,
                        interval_s: float = 0.062,
                        cost_s: float = 0.0002) -> dict:
    """Adaptation-speed microbench (ROADMAP item 2 gate): an arrival-paced
    keyed job at ~0.65 utilization takes a 2x load step that saturates
    parallelism 1; the autoscaler must scale up by checkpoint rewind +
    key-group remap. Emits autoscaler.{rescales, time_to_adapt_s,
    throughput_ratio_post_step} so adaptation speed is tracked per PR
    (time_to_adapt = load step crossing the wire -> rescaled attempt
    RUNNING; throughput ratio = the coordinator's settled post-rescale
    rate over the pre-step offered rate)."""
    from flink_tpu.config import AutoscalerOptions, Configuration
    from flink_tpu.runtime.cluster import (
        DistributedJobSpec,
        JobManagerEndpoint,
        TaskExecutorEndpoint,
    )
    from flink_tpu.runtime.rpc import RpcService

    import tempfile

    pre, high = 162, 324
    profile = [pre] * pre_steps + [high] * high_steps
    pre_rate = pre / interval_s
    cfg = (Configuration()
           .set(AutoscalerOptions.ENABLED, True)
           .set(AutoscalerOptions.POLICY, "threshold")
           .set(AutoscalerOptions.MAX_PARALLELISM, 2)
           .set(AutoscalerOptions.INTERVAL_MS, 200)
           .set(AutoscalerOptions.SIGNAL_WINDOW, 6)
           .set(AutoscalerOptions.STABILIZATION_INTERVAL_MS, 1500)
           .set(AutoscalerOptions.SCALE_UP_THRESHOLD, 0.9)
           # up-adaptation only: the e2e suite covers scale-down, and a
           # noisy low reading mid-scenario would pollute the timing
           .set(AutoscalerOptions.SCALE_DOWN_THRESHOLD, 0.05))
    spec = DistributedJobSpec(
        name="autoscaler-scenario",
        source_factory=_ScenarioSource(profile, interval_s),
        assigner=_ScenarioWindows(2000, cost_s),
        aggregate="sum",
        max_parallelism=16,
    )
    chk = tempfile.mkdtemp(prefix="flink-tpu-asbench-")
    svc_jm, svc_tm = RpcService(), RpcService()
    jm = JobManagerEndpoint(
        svc_jm, checkpoint_dir=chk, checkpoint_interval=0.3,
        heartbeat_interval=0.2, heartbeat_timeout=15.0,
        autoscaler_config=cfg,
    )
    te = TaskExecutorEndpoint(svc_tm, slots=2, shipping_interval_ms=200)
    te.connect(svc_jm.address)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    try:
        job_id = client.submit_job(spec.to_bytes(), 1)
        # nominal arrival time of the 2x step (the source's pacing anchor
        # is its first batch, within startup jitter of submit)
        t_step = time.monotonic() + pre_steps * interval_s
        t_adapted = None
        deadline = time.monotonic() + 180
        status = {}
        while time.monotonic() < deadline:
            status = client.job_status(job_id)
            if (t_adapted is None and status["rescales"] >= 1
                    and status["status"] == "RUNNING"):
                t_adapted = time.monotonic()
            if status["status"] in ("FINISHED", "FAILED"):
                break
            time.sleep(0.1)
        auto = client.job_autoscaler(job_id)
        settled = [d for d in auto["decisions"]
                   if d["action"] == "scale-up" and d["outcome"] == "executed"
                   and d.get("throughput_after")]
        # decision log is newest-first: [0] is the LATEST settled scale-up
        post_tput = settled[0]["throughput_after"] if settled else 0.0
        return {
            "status": status.get("status"),
            "rescales": int(status.get("rescales", 0)),
            "time_to_adapt_s": (round(max(t_adapted - t_step, 0.0), 3)
                                if t_adapted is not None else None),
            "throughput_ratio_post_step": round(post_tput / pre_rate, 3),
            "last_rescale_duration_ms": round(
                float(auto.get("last_rescale_duration_ms") or 0.0), 3),
            "pre_rate_records_per_s": pre_rate,
        }
    finally:
        te.stop()
        jm.heartbeats.stop()
        svc_jm.stop()
        svc_tm.stop()
        import shutil

        shutil.rmtree(chk, ignore_errors=True)


def child_autoscaler() -> None:
    """Autoscaler-scenario child: CPU-pinned like child_checkpoint (the
    oracle path never needs a device, and the parent must never lose the
    TPU relay to a control-plane bench)."""
    _emit({"event": "start", "device": "cpu-autoscaler", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": autoscaler_scenario()})


def run_autoscaler_scenario_child(timeout_s: float = 240.0) -> dict:
    """Autoscaler load-step scenario in a CPU-pinned child."""
    return _run_cpu_child('autoscaler', timeout_s)


def api_path_microbench(events: Optional[int] = None,
                        batch: int = 8192,
                        span_event_ms: int = 64_000) -> dict:
    """The api_vs_fused scenario (BENCH_r02), permanent: a FULL DataStream
    program — from_source().filter().key_by().window().aggregate().sink()
    — on the YSB sliding-count workload, run through BOTH execution paths
    in the same process on the same data:

      - whole-graph fusion (execution.chain.device-fusion true, the
        default): traceable filter + key extraction + window aggregate
        compile into one jitted multi-step device program
        (DeviceChainRunner, docs/fusion.md);
      - the legacy path (device-fusion false): host ChainRunner transforms
        + WindowStepRunner with per-batch host key/value extraction.

    Emits api_path_tuples_per_sec (fused) and chain_runner_tuples_per_sec
    (legacy) so the API-vs-kernel gap is tracked in every BENCH_*.json —
    it silently disappeared after r02. `parity` is exact result equality
    between the two paths; `fused_selected` pins that the fused runner was
    actually chosen (a silent reroute back to the slow runner would
    otherwise still report parity true)."""
    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import build_runners

    events = events or int(os.environ.get("BENCH_API_EVENTS", str(1 << 21)))

    def source(n):
        def gen(idx):
            # deterministic YSB-ish columns: (campaign, event_type); the
            # filter keeps event_type 0 ("view"), 1/3 of the stream
            camp = (idx * 2654435761) % NUM_KEYS
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // n
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, n)

    # one set of UDF OBJECTS shared by warmup and measured runs: compiled
    # chain executables are memoized on the fn identities, so the warmup
    # pays compilation and the measured runs bill steady-state throughput
    # (exactly a long-running job's economics)
    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731
    s_filter = lambda r: r[1] < 0.5                           # noqa: E731
    s_key = lambda r: int(r[0])                               # noqa: E731

    def build(n, mode, columnar=True):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, mode == "fused")
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, NUM_KEYS)
        # columnar sinks for the TIMED runs: the measurement targets the
        # execution paths, not the per-row Python expansion tax a naive
        # sink adds equally to every path; parity runs in row mode below,
        # where every operator emits raw keys
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, columnar)
        env = StreamExecutionEnvironment.get_execution_environment(cfg)
        ds = env.from_source(
            source(n),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        if mode == "scalar":
            # the r02 api_vs_fused program: per-record UDFs through host
            # Python loops — what a user writes first, and the gap the
            # whole-graph fusion refactor exists to close
            ds = ds.filter(s_filter)
            keyed = ds.key_by(s_key)
        else:
            ds = ds.filter(t_filter, traceable=True)
            keyed = ds.key_by(t_key, traceable=True)
        win = (
            keyed.window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
            .aggregate("count")
        )
        sink = win.collect()
        return env, sink

    def run(n, mode, columnar=True):
        env, sink = build(n, mode, columnar)
        t0 = time.perf_counter()
        env.execute()
        return sink.results, n / max(time.perf_counter() - t0, 1e-9)

    env_probe, _ = build(batch, "fused")
    runners, _ = build_runners(plan(env_probe._sinks), env_probe.config)
    fused_selected = any(
        type(r).__name__ == "DeviceChainRunner" for r in runners)

    # ---- parity gate: row mode (every operator emits raw keys there),
    # THREE-way exact equality — fused vs today's chain path vs the
    # per-record scalar program; counts are ints, comparison is exact
    n_parity = max(events // 8, batch)
    rows = {
        mode: sorted((int(k), int(v)) for k, v in
                     run(n_parity, mode, columnar=False)[0])
        for mode in ("fused", "chain", "scalar")
    }
    parity = (
        len(rows["fused"]) > 0
        and rows["fused"] == rows["chain"] == rows["scalar"]
    )

    # ---- timed runs: interleaved max-of-N sweeps, the PR-3 dataplane
    # protocol — the sandboxed 2-vCPU host sees multi-x scheduler noise,
    # and interleaving means a calm window benefits every configuration
    # (max-of-N estimates capability the way min-of-N estimates latency).
    # The parity pass above compiled the small shapes; one warmup per
    # jitted mode covers the full-size shapes. The slow paths run fewer
    # events (their per-event rate is flat; they are the gap being
    # measured, not re-validated).
    run(batch * 12, "fused")
    run(batch * 12, "chain")
    tps_fused = tps_chain = tps_scalar = 0.0
    res_fused = []
    for _sweep in range(3):
        res_fused, t = run(events, "fused")
        tps_fused = max(tps_fused, t)
        _r, t = run(max(events // 4, batch), "chain")
        tps_chain = max(tps_chain, t)
        _r, t = run(max(events // 8, batch), "scalar")
        tps_scalar = max(tps_scalar, t)
    return {
        "api_path_tuples_per_sec": round(tps_fused, 1),
        "chain_runner_tuples_per_sec": round(tps_chain, 1),
        "scalar_api_tuples_per_sec": round(tps_scalar, 1),
        "speedup_vs_chain_runner": round(tps_fused / max(tps_chain, 1e-9), 2),
        "speedup_vs_scalar_api": round(tps_fused / max(tps_scalar, 1e-9), 2),
        "parity": bool(parity),
        "fused_selected": bool(fused_selected),
        "windows_emitted": len(res_fused),
        "events": events,
        "num_keys": NUM_KEYS,
        "window_ms": WINDOW_MS,
        "slide_ms": SLIDE_MS,
        "columnar_output": True,
        "workload": "ysb_sliding_count_datastream_api",
    }


def correlated_windows_microbench(events: Optional[int] = None,
                                  batch: int = 65536,
                                  sweeps: int = 3) -> dict:
    """Shared-partials scenario (ISSUE-14, Factor Windows): ONE keyed
    stream aggregated into THREE correlated tumbling windows — 1m, 5m,
    1h — through two execution shapes on the same data:

      - shared (execution.window.shared-partials true, the default): the
        sharing optimizer (graph/window_sharing.py) collapses the three
        window() siblings into ONE shared-partial device program — slices
        ingest once at the gcd granule (1m) and every member window
        derives its result from the shared ring at fire time;
      - independent (shared-partials false): three separate fused device
        programs, each re-scanning the stream — exactly what the job paid
        before the optimizer existed.

    `parity` is exact per-window result equality between the two shapes;
    `shared_selected` pins that translation actually built ONE
    SharedWindowRunner (the reroute gate). A mesh leg re-runs both shapes
    sharded over the visible device mesh (the virtual 8-device CPU mesh
    in the gate; real chips on hardware), so the sharing speedup is
    tracked on BOTH the single-chip and mesh paths."""
    import jax
    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import Configuration, ExecutionOptions, ParallelOptions
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.fusion import plan_device_chains
    from flink_tpu.graph.transformation import plan
    from flink_tpu.graph.window_sharing import plan_shared_windows
    from flink_tpu.runtime.executor import build_runners

    # default batch 65536 (the executor default): the sharing win is the
    # (N-1) saved ingest scans, a PER-RECORD cost — small batches leave the
    # per-step ring traffic dominant and bury it
    events = events or int(
        os.environ.get("BENCH_CORRELATED_EVENTS", str(1 << 22)))
    span_event_ms = 2 * 3_600_000       # 2h of event time: two 1h windows
    window_sizes_ms = (60_000, 300_000, 3_600_000)

    def source(n):
        def gen(idx):
            camp = (idx * 2654435761) % NUM_KEYS
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // n
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, n)

    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731

    def build(n, shared: bool, mesh: bool, columnar: bool = True):
        cfg = Configuration()
        cfg.set(ExecutionOptions.SHARED_PARTIALS, shared)
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, NUM_KEYS)
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, columnar)
        if mesh:
            cfg.set(ParallelOptions.MESH_ENABLED, True)
        env = StreamExecutionEnvironment.get_execution_environment(cfg)
        ds = env.from_source(
            source(n),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        ds = ds.filter(t_filter, traceable=True)
        keyed = ds.key_by(t_key, traceable=True)
        sinks = [
            keyed.window(TumblingEventTimeWindows.of(sz)).aggregate("count")
            .collect()
            for sz in window_sizes_ms
        ]
        return env, sinks

    def run(n, shared, mesh, columnar=True):
        env, sinks = build(n, shared, mesh, columnar)
        t0 = time.perf_counter()
        env.execute()
        return ([s.results for s in sinks],
                n / max(time.perf_counter() - t0, 1e-9))

    # planner probe: the optimizer must classify ONE group of 3 and the
    # executor must build ONE SharedWindowRunner (the reroute gate)
    env_probe, _ = build(batch, shared=True, mesh=False)
    graph = plan(env_probe._sinks)
    chain_plans, _abs = plan_device_chains(graph)
    sw_plans = plan_shared_windows(graph, chain_plans)
    runners, _ = build_runners(graph, env_probe.config)
    shared_selected = any(
        type(r).__name__ == "SharedWindowRunner" for r in runners)
    est_factor = (sw_plans[0].estimated_sharing_factor if sw_plans else 0.0)

    def leg(mesh: bool) -> dict:
        # parity: row mode, exact per-window equality shared vs independent
        n_parity = max(events // 8, batch)
        rows_s = [sorted((int(k), int(v)) for k, v in r)
                  for r in run(n_parity, True, mesh, columnar=False)[0]]
        rows_i = [sorted((int(k), int(v)) for k, v in r)
                  for r in run(n_parity, False, mesh, columnar=False)[0]]
        parity = all(len(a) > 0 and a == b for a, b in zip(rows_s, rows_i))
        # timed: interleaved max-of-3 sweeps (the PR-3 protocol — a calm
        # scheduler window benefits both shapes)
        run(batch * 12, True, mesh)
        run(batch * 12, False, mesh)
        tps_s = tps_i = 0.0
        for _sweep in range(sweeps):
            _r, t = run(events, True, mesh)
            tps_s = max(tps_s, t)
            _r, t = run(events, False, mesh)
            tps_i = max(tps_i, t)
        return {
            "shared_tuples_per_sec": round(tps_s, 1),
            "independent_tuples_per_sec": round(tps_i, 1),
            "speedup_vs_independent": round(tps_s / max(tps_i, 1e-9), 2),
            "parity": bool(parity),
            "windows_emitted": [len(r) for r in rows_s],
        }

    result = {
        **leg(mesh=False),
        "shared_selected": bool(shared_selected),
        "groups_planned": len(sw_plans),
        "sharing_factor_estimate": round(est_factor, 2),
        "granule_ms": sw_plans[0].granule_ms if sw_plans else None,
        "events": events,
        "num_keys": NUM_KEYS,
        "window_sizes_ms": list(window_sizes_ms),
        "workload": "correlated_1m_5m_1h_tumbling_count",
    }
    n_dev = len(jax.devices())
    if n_dev >= 2 and NUM_KEYS % n_dev == 0:
        mesh_leg = leg(mesh=True)
        mesh_leg["devices"] = n_dev
        result["mesh"] = mesh_leg
    else:
        result["mesh"] = {"skipped": f"{n_dev} device(s) visible"}
    return result


def child_correlated() -> None:
    """Correlated-windows child: CPU-pinned with the 8-device virtual mesh
    forced, so the mesh leg of the sharing scenario exercises a real
    sharded shared-partial program."""
    _emit({"event": "start", "device": "cpu-correlated", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": correlated_windows_microbench()})


def run_correlated_child(timeout_s: float = 420.0) -> dict:
    """Correlated-windows microbench in a CPU-pinned child on the forced
    8-device virtual mesh (single-chip leg + mesh leg in one child)."""
    return _run_cpu_child('correlated', timeout_s, force_mesh=True)


def sql_path_microbench(events: Optional[int] = None,
                        batch: int = 8192,
                        span_event_ms: int = 64_000) -> dict:
    """SQL front-door scenario (ISSUE-13): the YSB sliding count written
    as SQL — `SELECT campaign, COUNT(*) ... GROUP BY campaign, HOP(...)`
    over a columnar table — through THREE paths in one process on the
    same data:

      - SQL-fused (table.device-fusion true, the default): the planner
        (flink_tpu/planner) lowers the statement onto the same
        whole-graph-fusion StepGraph a hand-built DataStream job takes —
        DeviceChainRunner runs filter + key/value extraction + window as
        ONE compiled superscan;
      - interpreted table path (table.device-fusion false): the legacy
        TableEnvironment translation — per-record row view, host keying,
        per-batch device window — what every SQL statement paid before;
      - hand-built DataStream-fused: the SAME program written against the
        fluent API with traceable UDFs, with the SAME SQL-shaped output
        row assembly, so `ratio_vs_datastream_fused` isolates what the
        SQL front door costs over hand fusion (the ~1.2x acceptance bar)
        rather than re-measuring the row-materialization tax both pay.

    `parity` is exact three-way row equality; `fused_selected` pins that
    graph translation actually chose DeviceChainRunner for the SQL job
    (the reroute gate) AND the planner reported the fused path. A session
    -window statement additionally runs through the same TableEnvironment
    to pin the fallback contract: it must EXECUTE on the interpreted path
    with its catalogued reason attributed, not fail."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import Configuration, ExecutionOptions, TableOptions
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import build_runners
    from flink_tpu.table import TableEnvironment, TableSchema

    events = events or int(os.environ.get("BENCH_SQL_EVENTS", str(1 << 21)))

    def source(n):
        def gen(idx):
            camp = (idx * 2654435761) % NUM_KEYS
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // n
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, n)

    SQL = (
        "SELECT campaign, COUNT(*) AS views, WINDOW_END AS wend FROM ysb "
        "WHERE event_type < 0.5 GROUP BY campaign, "
        f"HOP(rowtime, INTERVAL '{SLIDE_MS}' MILLISECOND, "
        f"INTERVAL '{WINDOW_MS}' MILLISECOND)"
    )

    def config(fused: bool) -> Configuration:
        cfg = Configuration()
        cfg.set(TableOptions.DEVICE_FUSION, fused)
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, NUM_KEYS)
        return cfg

    def build_sql(n, fused):
        env = StreamExecutionEnvironment.get_execution_environment(config(fused))
        tenv = TableEnvironment(env)
        stream = env.from_source(
            source(n),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        tenv.register_table(
            "ysb", stream,
            TableSchema(["campaign", "event_type", "rowtime"],
                        rowtime="rowtime",
                        field_types=["int", "float", "int"]),
            columnar=True,
        )
        sink = tenv.sql_query(SQL).collect()
        return env, tenv, sink

    # shared UDF objects across runs: compiled chain executables memoize on
    # fn identity, so warmup pays compilation once (api_path economics)
    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype("int32")             # noqa: E731

    def ds_to_row(rec, ts):
        # the SQL statement's output shape, hand-written: what a user
        # replacing SQL with the fluent API would still have to emit
        return {"campaign": rec[0], "views": rec[1], "wend": ts + 1}

    def build_ds(n):
        env = StreamExecutionEnvironment.get_execution_environment(config(True))
        ds = env.from_source(
            source(n),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        win = (
            ds.filter(t_filter, traceable=True)
            .key_by(t_key, traceable=True)
            .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
            .aggregate("count")
        )
        sink = win.map_with_timestamp(ds_to_row, name="sql_shape_output").collect()
        return env, sink

    def norm(rows):
        return sorted((int(r["campaign"]), int(r["wend"]), int(r["views"]))
                      for r in rows)

    def run_sql(n, fused):
        env, _tenv, sink = build_sql(n, fused)
        t0 = time.perf_counter()
        env.execute()
        return sink.results, n / max(time.perf_counter() - t0, 1e-9)

    def run_ds(n):
        env, sink = build_ds(n)
        t0 = time.perf_counter()
        env.execute()
        return sink.results, n / max(time.perf_counter() - t0, 1e-9)

    # ---- reroute gate: the SQL program's own graph must translate to
    # DeviceChainRunner AND the planner must report the fused path
    env_probe, tenv_probe, _ = build_sql(batch, True)
    probe_runners, _ = build_runners(plan(env_probe._sinks), env_probe.config)
    report = tenv_probe.last_plan_report
    fused_selected = bool(
        any(type(r).__name__ == "DeviceChainRunner" for r in probe_runners)
        and report is not None and report.fused
    )

    # ---- fallback contract: an unsupported statement EXECUTES on the
    # interpreted path with its reason attributed (never fails)
    env_fb = StreamExecutionEnvironment.get_execution_environment(config(True))
    tenv_fb = TableEnvironment(env_fb)
    tenv_fb.from_rows(
        "pay",
        [{"user": i % 5, "amount": float(i % 3), "rowtime": i * 100}
         for i in range(512)],
        TableSchema(["user", "amount", "rowtime"], rowtime="rowtime",
                    field_types=["int", "float", "int"]),
    )
    fb_rows = tenv_fb.execute_sql_to_list(
        "SELECT user, COUNT(*) AS n FROM pay "
        "GROUP BY user, SESSION(rowtime, INTERVAL '1' SECOND)")
    fb_report = tenv_fb.last_plan_report
    fallback_attributed = bool(
        fb_rows and fb_report is not None
        and fb_report.path == "interpreted"
        and fb_report.reason == "session-window")

    # ---- parity gate: exact three-way row equality. The interpreted path
    # is per-record host work; a reduced slice keeps the gate O(seconds)
    # while still covering every window shape the others see.
    n_parity = max(events // 16, batch)
    rows_fused = norm(run_sql(n_parity, True)[0])
    rows_interp = norm(run_sql(n_parity, False)[0])
    rows_ds = norm(run_ds(n_parity)[0])
    parity = bool(len(rows_fused) > 0
                  and rows_fused == rows_interp == rows_ds)

    # ---- timed runs: interleaved max-of-N sweeps (PR-3 protocol); the
    # interpreted path runs fewer events — its per-event rate is flat and
    # it IS the gap being measured
    run_sql(batch * 12, True)
    run_ds(batch * 12)
    tps_sql = tps_interp = tps_ds = 0.0
    res_sql = []
    for _sweep in range(3):
        res_sql, t = run_sql(events, True)
        tps_sql = max(tps_sql, t)
        _r, t = run_sql(max(events // 16, batch), False)
        tps_interp = max(tps_interp, t)
        _r, t = run_ds(events)
        tps_ds = max(tps_ds, t)
    return {
        "sql_tuples_per_sec": round(tps_sql, 1),
        "interpreted_tuples_per_sec": round(tps_interp, 1),
        "datastream_fused_tuples_per_sec": round(tps_ds, 1),
        "speedup_vs_interpreted": round(tps_sql / max(tps_interp, 1e-9), 2),
        "ratio_vs_datastream_fused": round(tps_ds / max(tps_sql, 1e-9), 3),
        "parity": parity,
        "fused_selected": fused_selected,
        "fallback_attributed": fallback_attributed,
        "fallback_reason_demo": getattr(fb_report, "reason", None),
        "windows_emitted": len(res_sql),
        "events": events,
        "num_keys": NUM_KEYS,
        "window_ms": WINDOW_MS,
        "slide_ms": SLIDE_MS,
        "statement": SQL,
        "workload": "ysb_sliding_count_sql",
    }


def device_plane_microbench(events: Optional[int] = None,
                            batch: int = 8192,
                            num_keys: Optional[int] = None,
                            span_event_ms: int = 64_000,
                            sweeps: int = 3) -> dict:
    """Device-plane observability scenario (ISSUE-8): the YSB sliding-count
    DataStream program on the fused device chain, run with the device
    plane ON and OFF in interleaved max-of-N sweeps.

    Emits the `device` block every BENCH_*.json now tracks:

      - compile observability: nonzero compile count, the recompile-event
        ring with cause attribution (the tail dispatch's power-of-two
        shape is a REAL batch-geometry recompile; a secondary small-key
        classic-path run grows its key dictionary past the initial
        capacity to induce a ring-doubling recompile),
      - per-operator roofline utilization (hbm/flops pct from XLA cost
        analysis over the DeviceTimer wall time),
      - per-phase ingest/fire/purge step counters from the superscan
        carry,
      - key-skew telemetry (uniform YSB keys read skew ~1; a hot-key
        regression shows up as the coefficient rising toward the
        key-group count),
      - measured overhead of the enabled plane vs gates-off (the <= 2%
        acceptance bar). The overhead RATIO uses median-of-N on both
        sides: max-of-N estimates capability for absolute throughput, but
        for an A/B ratio a single lucky scheduler draw on one side skews
        the quotient by tens of percent on the sandboxed 2-vCPU host —
        the median is the unbiased comparator (absolute tuples/s are
        still reported max-of-N for continuity with the other
        scenarios)."""
    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
    )
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import JobRuntime

    events = events or int(os.environ.get("BENCH_DEVICE_EVENTS", str(1 << 20)))
    num_keys = num_keys or NUM_KEYS

    def source(n):
        def gen(idx):
            camp = (idx * 2654435761) % num_keys
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // n
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, n)

    # fresh UDF objects per call: the chained executable cache keys on fn
    # identity, so the first stats-on run always observes its own compiles
    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731

    def build_runtime(n, stats_on):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, True)
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, num_keys)
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, True)
        # dispatch every 8 steps so the key-stats fold sees resident
        # device state mid-stream even at smoke scale (both sides of the
        # overhead A/B run the same geometry, so the ratio is unaffected)
        cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 8)
        cfg.set(ObservabilityOptions.DEVICE_STATS_ENABLED, stats_on)
        env = StreamExecutionEnvironment(cfg)
        ds = env.from_source(
            source(n),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        (ds.filter(t_filter, traceable=True)
           .key_by(t_key, traceable=True)
           .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
           .aggregate("count")
           .collect())
        return JobRuntime(plan(env._sinks), cfg)

    # warmup both configurations AT FULL SCALE (the phase-counter flag is
    # part of the executable cache key, so each side owns its compiles and
    # an asymmetric warmup would bill one side's jit to its measured run),
    # banking the FIRST stats-on runtime's snapshot — it observed the
    # compiles
    rt_on = build_runtime(events, True)
    rt_on.run()
    snap = rt_on.device_snapshot()
    build_runtime(events, False).run()

    samples: dict = {True: [], False: []}
    for sweep in range(sweeps):
        # alternate the within-sweep order so a drifting machine biases
        # neither side
        order = (True, False) if sweep % 2 == 0 else (False, True)
        for stats_on in order:
            rt = build_runtime(events, stats_on)
            t0 = time.perf_counter()
            rt.run()
            samples[stats_on].append(
                events / max(time.perf_counter() - t0, 1e-9))
    tps_on, tps_off = max(samples[True]), max(samples[False])
    med = lambda xs: sorted(xs)[len(xs) // 2]               # noqa: E731

    # ring-doubling induction: the CLASSIC fused path starts its key
    # capacity at min(1024, configured) and doubles with the key
    # dictionary — a >1024-key stream recompiles with cause attribution
    ring_causes: list = []
    try:
        from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.metrics.device_stats import CompileTracker
        from flink_tpu.runtime.fused_window_operator import FusedWindowOperator

        op = FusedWindowOperator(TumblingEventTimeWindows.of(1000), "count",
                                 key_capacity=1 << 10, superbatch_steps=4,
                                 chunk=256)
        tracker = CompileTracker()
        op.attach_device_stats(tracker)
        rng = np.random.default_rng(7)
        for s in range(12):
            # narrow key range first so dispatches run at the initial
            # capacity, THEN widen past it — the dictionary growth doubles
            # the ring and the next dispatch recompiles with cause
            # attribution
            hi = 512 if s < 6 else 1536
            keys = rng.integers(0, hi, 512)
            op.process_batch(keys, np.ones(512, np.float32),
                             np.full(512, s * 300, np.int64))
            op.process_watermark(s * 300)
        from flink_tpu.core.time import MAX_WATERMARK

        op.process_watermark(MAX_WATERMARK)
        ring_causes = [e["cause"] for e in tracker.events()
                       if e.get("recompile")]
    except Exception as e:  # noqa: BLE001 — the block must survive
        ring_causes = [f"error: {e!r}"[:120]]

    comp = snap["compile"]
    op_entries = [e for e in snap["operators"].values() if "compile" in e]
    roof = op_entries[0] if op_entries else {}
    keys_blk = (op_entries[0].get("keys", {}) if op_entries else {})
    med_on, med_off = med(samples[True]), med(samples[False])
    overhead = ((med_off - med_on) / max(med_off, 1e-9)) * 100.0
    return {
        "tuples_per_sec_on": round(tps_on, 1),
        "tuples_per_sec_off": round(tps_off, 1),
        "overhead_pct": round(overhead, 2),
        "numCompiles": int(comp["numCompiles"]),
        "numRecompiles": int(comp["numRecompiles"]),
        "compileTimeMsTotal": comp["compileTimeMsTotal"],
        "recompileStorm": int(comp["recompileStorm"]),
        "recompile_causes": sorted({e["cause"] for e in comp["events"]
                                    if e.get("recompile")} | set(ring_causes)),
        "hbmUtilizationPct": roof.get("hbmUtilizationPct", 0.0),
        "flopsUtilizationPct": roof.get("flopsUtilizationPct", 0.0),
        "phases": roof.get("phases", {}),
        "keySkew": keys_blk.get("keySkew"),
        "activeKeys": keys_blk.get("activeKeys", 0),
        "hotKeys": (keys_blk.get("hotKeys") or [])[:3],
        "events": events,
        "num_keys": num_keys,
        "workload": "ysb_sliding_count_datastream_api",
    }


def child_device_plane() -> None:
    """Device-plane child: CPU-pinned like child_api_path (same-backend
    overhead comparison; the parent must never lose the TPU relay)."""
    _emit({"event": "start", "device": "cpu-device-plane", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": device_plane_microbench()})


def _run_cpu_child(label: str, timeout_s: float, *,
                   force_mesh: bool = False) -> dict:
    """Run `bench.py --child <label>` CPU-pinned and return its result
    event (or an error dict — the headline must survive). This is THE
    child protocol (env merge + reversed-stdout scan for the result
    event), single-sourced: six scenarios ride it and a per-scenario copy
    must never drift. `force_mesh` forces an 8-device virtual CPU mesh via
    XLA_FLAGS — the multichip scenario and the chaos chip-loss scenario
    need devices to lose."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    if force_mesh:
        env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             label, "0", "0", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            timeout=timeout_s, env=env,
        )
        for line in reversed(r.stdout.splitlines()):
            if line.startswith("{"):
                obj = json.loads(line)
                if obj.get("event") == "result":
                    return obj["result"]
        return {"error": f"no result event from {label} child"}
    except Exception as e:  # noqa: BLE001
        return {"error": repr(e)[:300]}


def run_device_plane_child(timeout_s: float = 300.0) -> dict:
    """Device-plane microbench in a CPU-pinned child."""
    return _run_cpu_child('device-plane', timeout_s)


def child_api_path() -> None:
    """API-path child: CPU-pinned like child_cpu — the comparison is
    CPU-jit vs CPU-jit (same backend both paths), and the parent must
    never lose the single-client TPU relay to it."""
    _emit({"event": "start", "device": "cpu-api-path", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": api_path_microbench()})


def run_api_path_microbench_child(timeout_s: float = 300.0) -> dict:
    """API-path microbench in a CPU-pinned child (same backend both paths)."""
    return _run_cpu_child('api-path', timeout_s)


def latency_frontier_microbench(events: Optional[int] = None,
                                batch: int = 8192) -> dict:
    """The latency x throughput frontier of the flagship fused YSB job.

    Throughput numbers alone hide the quantity a serving user feels: how
    long after a window's event-time close its result is host-visible.
    This scenario drives the fused filter→key_by→sliding-count program
    through an OPEN-LOOP, arrival-paced generator — event timestamps
    follow a fixed wall-clock arrival schedule (t0 + i/rate), so when the
    pipeline falls behind, the backlog shows up as emission latency
    instead of being absorbed by the source slowing down (closed-loop
    sources measure the pipeline's speed; open-loop measures its lag).

    Legs: measured peak (unpaced, plane on vs off — the <2% overhead
    budget of the emission-latency plane), then 25/50/100% of that peak,
    each reporting p50/p99/p999 emission latency from the job's own
    log-bucket histograms (client.latency_report(), the /jobs/:id/latency
    payload) plus the stall-attribution counts (checkpointing runs during
    the paced legs so tail outliers have control spans to land on).

    Parity: every paced leg's (key, count) multiset must EXACTLY equal a
    host-side numpy oracle computed from the same deterministic arrival
    schedule — pacing must never change results, only their timing.
    """
    import shutil
    import tempfile

    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import (
        CheckpointingOptions,
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
    )
    from flink_tpu.connectors.source import (
        Batch,
        Source,
        SourceReader,
        SourceSplit,
        SplitEnumerator,
    )
    from flink_tpu.core.watermarks import WatermarkStrategy

    events = events or int(
        os.environ.get("BENCH_LATENCY_EVENTS", str(1 << 20)))
    leg_s = float(os.environ.get("BENCH_LATENCY_LEG_S", "2.5"))
    sweeps = int(os.environ.get("BENCH_LATENCY_SWEEPS", "3"))
    # distinct geometry from the api-path scenario (the bench-gate rule:
    # never share another family's cached superscan shapes); windows turn
    # over every FR_SLIDE ms of WALL time here, so even a short paced leg
    # fires hundreds of windows to sample
    FR_KEYS, FR_WINDOW, FR_SLIDE = 512, 2_000, 500

    class _FrontierReader(SourceReader):
        """YSB columns on a wall-anchored arrival schedule. Paced mode
        stamps ts from the SCHEDULE (t0 + i/rate) and sleeps only when
        ahead of it — never when behind (open loop); unpaced mode stamps
        the current wall clock and never sleeps (the peak probe)."""

        def __init__(self, rate: Optional[float]):
            self._rate = rate
            self._next = 0
            self._end = 0
            self.t0_ms: Optional[float] = None

        def add_split(self, split: SourceSplit) -> None:
            self._next = split.payload["start"]
            self._end = split.payload["end"]

        def poll_batch(self, max_records: int) -> Optional[Batch]:
            if self._next >= self._end:
                return None
            n = min(max_records, self._end - self._next)
            idx = np.arange(self._next, self._next + n, dtype=np.int64)
            self._next += n
            now = time.time() * 1000.0
            if self.t0_ms is None:
                self.t0_ms = now
            if self._rate is None:
                ts = np.full(n, int(now), dtype=np.int64)
            else:
                ts = (self.t0_ms + idx * (1000.0 / self._rate)
                      ).astype(np.int64)
                due = self.t0_ms + (self._next / self._rate) * 1000.0
                wait_s = (due - now) / 1000.0
                if wait_s > 0:
                    time.sleep(wait_s)
            camp = (idx * 2654435761) % FR_KEYS
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            return Batch(col, ts)

        def snapshot_position(self) -> dict:
            return {"next": self._next, "end": self._end}

        def restore_position(self, state: dict) -> None:
            self._next = state["next"]
            self._end = state["end"]

    class _FrontierSource(Source):
        def __init__(self, n: int, rate: Optional[float]):
            self.n = n
            self.rate = rate
            self.reader: Optional[_FrontierReader] = None

        def create_enumerator(self) -> SplitEnumerator:
            return SplitEnumerator(
                [SourceSplit("frontier-0", {"start": 0, "end": self.n})])

        def create_reader(self) -> SourceReader:
            self.reader = _FrontierReader(self.rate)
            return self.reader

    # one set of UDF objects for every leg: compiled chain executables
    # memoize on fn identity, so the warmup leg pays compilation once
    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731

    lat_target_ms = int(os.environ.get("BENCH_LATENCY_TARGET_MS", "10"))

    def run_leg(n, rate, *, plane_on=True, chk_dir=None, latency=False,
                name="frontier"):
        cfg = Configuration()
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, FR_KEYS)
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, False)
        if latency:
            # latency-mode leg: same program, execution.latency.* on —
            # the controller shrinks the superbatch at light load and the
            # in-flight ring overlaps host prep with device dispatch
            from flink_tpu.config import LatencyOptions
            cfg.set(LatencyOptions.TARGET_MS, lat_target_ms)
            cfg.set(LatencyOptions.MAX_INFLIGHT, 2)
            # smoke legs last ~1 s; the production default half-second
            # dwell would pin the rung near the full span for most of a
            # short leg, measuring the warm-up hold instead of the mode
            cfg.set(LatencyOptions.MIN_DWELL_MS, 100)
        if not plane_on:
            cfg.set(ObservabilityOptions.EMISSION_LATENCY_ENABLED, False)
        if chk_dir is not None:
            cfg.set(CheckpointingOptions.INTERVAL_MS, 250)
            cfg.set(CheckpointingOptions.DIRECTORY, chk_dir)
        env = StreamExecutionEnvironment(cfg)
        src = _FrontierSource(n, rate)
        ds = env.from_source(
            src,
            watermark_strategy=WatermarkStrategy
            .for_bounded_out_of_orderness(0),
        )
        ds = ds.filter(t_filter, traceable=True)
        keyed = ds.key_by(t_key, traceable=True)
        win = (keyed.window(SlidingEventTimeWindows.of(FR_WINDOW, FR_SLIDE))
               .aggregate("count"))
        sink = win.collect()
        t0 = time.perf_counter()
        client = env.execute_async(name)
        client.wait(240.0)
        wall = time.perf_counter() - t0
        return sink.results, wall, client, src

    def oracle(n, t0_ms, rate):
        """Host numpy oracle over the SAME deterministic schedule: the
        (key, count) multiset of every sliding window with content (the
        terminal watermark flushes them all)."""
        idx = np.arange(n, dtype=np.int64)
        kept = (idx % 3) == 0
        key = ((idx * 2654435761) % FR_KEYS)[kept]
        ts = (t0_ms + idx * (1000.0 / rate)).astype(np.int64)[kept]
        nwin = FR_WINDOW // FR_SLIDE
        last_start = (ts // FR_SLIDE) * FR_SLIDE
        kk = np.tile(key, nwin)
        starts = np.concatenate(
            [last_start - j * FR_SLIDE for j in range(nwin)])
        sid = starts // FR_SLIDE
        codes = kk * np.int64(1 << 40) + (sid - sid.min())
        uniq, counts = np.unique(codes, return_counts=True)
        return sorted(zip((uniq >> 40).tolist(), counts.tolist()))

    # ---- peak probe: unpaced, plane on vs off, interleaved max-of-N
    # (max-of-N estimates capability under scheduler noise — the PR-3
    # dataplane protocol); the plane's throughput budget is <2% here
    # warm up at the MEASURED size: superscan executables specialize on
    # the superbatch group shape, so a smaller warmup would leave the
    # first measured leg paying the compile (and bias the on/off delta)
    run_leg(events, None)
    run_leg(events, None, plane_on=False)
    tps_on = tps_off = 0.0
    for _sweep in range(sweeps):
        _r, wall, _c, _s = run_leg(events, None, plane_on=True)
        tps_on = max(tps_on, events / max(wall, 1e-9))
        _r, wall, _c, _s = run_leg(events, None, plane_on=False)
        tps_off = max(tps_off, events / max(wall, 1e-9))
    peak = tps_on
    overhead_pct = (100.0 * (tps_off - tps_on) / tps_off
                    if tps_off > 0 else 0.0)

    # ---- the frontier: 25/50/100% of measured peak, open-loop
    points = {}
    all_parity = True
    samples_total = 0
    p99_at_full = 0.0
    for frac in (0.25, 0.5, 1.0):
        rate = max(peak * frac, batch * 2.0)
        n = int(min(max(rate * leg_s, batch * 4), events * 4))
        n = max(batch, n - n % batch)               # whole batches
        chk = tempfile.mkdtemp(prefix="flink-tpu-frontier-")
        try:
            results, wall, client, src = run_leg(
                n, rate, chk_dir=chk, name=f"frontier-{int(frac * 100)}")
        finally:
            shutil.rmtree(chk, ignore_errors=True)
        rep = client.latency_report()
        got = sorted((int(k), int(v)) for k, v in results)
        exp = oracle(n, src.reader.t0_ms, rate)
        parity = len(got) > 0 and got == exp
        all_parity = all_parity and parity
        att = rep.get("attribution") or {}
        samples_total += int(rep.get("samples", 0))
        points[str(int(frac * 100))] = {
            "target_rate_tuples_per_sec": round(rate, 1),
            "achieved_rate_tuples_per_sec": round(n / max(wall, 1e-9), 1),
            "events": n,
            "p50_emission_ms": rep.get("p50_ms", 0.0),
            "p99_emission_ms": rep.get("p99_ms", 0.0),
            "p999_emission_ms": rep.get("p999_ms", 0.0),
            "samples": int(rep.get("samples", 0)),
            "watermark_lag_ms": rep.get("watermarkLagMs", 0.0),
            "parity": bool(parity),
            "stall_outliers": int(att.get("outliers", 0)),
            "stall_attributed": {k: int(v.get("count", 0)) for k, v in
                                 (att.get("attributed") or {}).items()},
            "stall_unattributed": int(att.get("unattributed", 0)),
        }
        if frac == 1.0:
            p99_at_full = rep.get("p99_ms", 0.0)

    # ---- latency mode: the SAME program with execution.latency.* on.
    # Peak probe first (unpaced = 100% load): the controller must read
    # the saturated arrival rate, escalate to the full span, and keep
    # throughput within budget of throughput mode (peak_fraction) — the
    # mode's cost when the fleet is busy. The donated executables live in
    # separate cache entries, so warm up at the measured size first, then
    # a short paced warm leg pre-compiles the small-rung geometries the
    # 25% leg will pick (bounded by the pow2 ladder — never a storm).
    run_leg(events, None, latency=True)
    lat_peak = 0.0
    for _sweep in range(sweeps):
        _r, wall, _c, _s = run_leg(events, None, latency=True)
        lat_peak = max(lat_peak, events / max(wall, 1e-9))
    # warm leg with the SAME n, rate, and checkpointing as the measured
    # 25% point: the controller walks the same rung descent, periodic
    # checkpoints flush the same mid-stream tails, and the end-of-stream
    # flush pads the same pow2 tails, so every donated geometry the
    # measured leg dispatches is already compiled (compile stalls would
    # otherwise land on the few windows a smoke leg fires and swamp its
    # p99)
    warm_rate = max(peak * 0.25, batch * 2.0)
    warm_n = int(min(max(warm_rate * leg_s, batch * 4), events * 4))
    warm_n = max(batch, warm_n - warm_n % batch)
    warm_chk = tempfile.mkdtemp(prefix="flink-tpu-frontier-lat-warm-")
    try:
        run_leg(warm_n, warm_rate, chk_dir=warm_chk, latency=True,
                name="frontier-lat-warm")
    finally:
        shutil.rmtree(warm_chk, ignore_errors=True)

    lat_points = {}
    lat_parity = True
    lat_p99_at_25 = 0.0
    lat_ach_at_100 = 0.0
    for frac in (0.25, 0.5, 1.0):
        rate = max(peak * frac, batch * 2.0)
        n = int(min(max(rate * leg_s, batch * 4), events * 4))
        n = max(batch, n - n % batch)               # whole batches
        # the 100% point is judged as a fraction of the throughput-mode
        # peak — itself the best of `sweeps` unpaced legs — so it gets
        # the same best-of-sweeps treatment: a one-off stall (e.g. a
        # checkpoint flush landing on a tail pad the warm leg never
        # compiled) must not masquerade as a throughput regression.
        # Parity still folds over EVERY repetition.
        best_ach = -1.0
        best_entry = None
        best_rep = None
        for _rep in range(sweeps if frac == 1.0 else 1):
            chk = tempfile.mkdtemp(prefix="flink-tpu-frontier-lat-")
            try:
                results, wall, client, src = run_leg(
                    n, rate, chk_dir=chk, latency=True,
                    name=f"frontier-lat-{int(frac * 100)}")
            finally:
                shutil.rmtree(chk, ignore_errors=True)
            rep = client.latency_report()
            got = sorted((int(k), int(v)) for k, v in results)
            exp = oracle(n, src.reader.t0_ms, rate)
            parity = len(got) > 0 and got == exp
            lat_parity = lat_parity and parity
            ach = n / max(wall, 1e-9)
            entry = {
                "target_rate_tuples_per_sec": round(rate, 1),
                "achieved_rate_tuples_per_sec": round(ach, 1),
                "events": n,
                "p50_emission_ms": rep.get("p50_ms", 0.0),
                "p99_emission_ms": rep.get("p99_ms", 0.0),
                "p999_emission_ms": rep.get("p999_ms", 0.0),
                "samples": int(rep.get("samples", 0)),
                "parity": bool(parity),
                # the /jobs/:id/latency controller block: rung, ring
                # depth, distinct compiled geometries (ladder-bounded)
                "controller": rep.get("latency_mode") or {},
            }
            if ach > best_ach:
                best_ach, best_entry, best_rep = ach, entry, rep
        lat_points[str(int(frac * 100))] = best_entry
        if frac == 0.25:
            lat_p99_at_25 = best_rep.get("p99_ms", 0.0)
        if frac == 1.0:
            lat_ach_at_100 = best_ach
    # the tracked peak fraction is the PACED comparison the acceptance bar
    # names: latency-mode throughput at the 100% load point over the
    # throughput-mode peak (the unpaced probe's wall clock folds in job
    # setup and is scheduler-noise-bound on a shared host; the paced
    # point is the apples-to-apples sustained-rate question)
    peak_fraction = lat_ach_at_100 / max(peak, 1e-9)

    return {
        "latency_frontier": {
            "peak_tuples_per_sec": round(peak, 1),
            "plane_on_tuples_per_sec": round(tps_on, 1),
            "plane_off_tuples_per_sec": round(tps_off, 1),
            "plane_overhead_pct": round(overhead_pct, 2),
            "load_points": points,
            "parity": bool(all_parity),
            "samples": samples_total,
            "window_ms": FR_WINDOW,
            "slide_ms": FR_SLIDE,
            "num_keys": FR_KEYS,
            "pacing": "open-loop-arrival",
            "workload": "ysb_sliding_count_paced_wall_clock",
            "latency_mode": {
                "target_ms": lat_target_ms,
                "max_inflight": 2,
                "peak_tuples_per_sec": round(lat_peak, 1),
                "peak_fraction": round(peak_fraction, 4),
                "load_points": lat_points,
                "parity": bool(lat_parity),
            },
        },
        "p99_emission_latency_ms": p99_at_full,
        "latency_mode_p99_ms": lat_p99_at_25,
        "latency_mode_peak_fraction": round(peak_fraction, 4),
    }


def child_latency_frontier() -> None:
    """Latency-frontier child: CPU-pinned like child_api_path (pacing is
    wall-clock-sensitive; the parent must never lose the TPU relay)."""
    _emit({"event": "start", "device": "cpu-latency-frontier",
           "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": latency_frontier_microbench()})


def run_latency_frontier_child(timeout_s: float = 420.0) -> dict:
    """Latency-frontier microbench in a CPU-pinned child."""
    return _run_cpu_child('latency-frontier', timeout_s)


def health_microbench(events: Optional[int] = None,
                      batch: int = 8192,
                      num_keys: Optional[int] = None,
                      interval_ms: int = 50) -> dict:
    """History/doctor plane scenario (ISSUE-19): the flagship YSB-shaped
    keyed tumbling count through the MiniCluster with the metric-history
    sampler ticking at an aggressive `interval_ms` (20x the default rate
    — a conservative overestimate of steady-state sampler cost), then
    read back the two new planes the way a user would:

      - ``GET /jobs/:id/history`` (via client.history_report): the rings
        must be non-empty — counters recorded as rates, the emission
        histogram as per-sample p50/p99 sub-series;
      - ``GET /jobs/:id/doctor`` (via client.doctor_report): an
        undisturbed healthy run must produce a verdict (not "unknown" —
        that means the sampler never ticked);
      - sampler overhead measured from the history's own perf_counter
        self-timing (`sample_time_ms` / job wall time) — the <= 2%
        acceptance bar is judged on this number, measured not claimed.
    """
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy

    events = events or int(os.environ.get("BENCH_HEALTH_EVENTS",
                                          str(1 << 19)))
    num_keys = num_keys or NUM_KEYS

    def source(n):
        def gen(idx):
            keys = ((idx * 2654435761) % num_keys).astype(np.int64)
            ts = 10_000 + idx * 64_000 // n
            return Batch(keys, ts.astype(np.int64))

        return DataGeneratorSource(gen, n)

    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, batch)
    config.set(ExecutionOptions.KEY_CAPACITY, num_keys)
    config.set(ObservabilityOptions.HISTORY_INTERVAL_MS, interval_ms)
    env = StreamExecutionEnvironment(config)
    stream = env.from_source(
        source(events),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = CollectSink()
    (stream.key_by(lambda col: col, vectorized=True)
           .window(TumblingEventTimeWindows.of(1000)).count()
           .sink_to(sink))
    t0 = time.perf_counter()
    client = env.execute_async("bench-health")
    client.wait(240)
    wall_s = max(time.perf_counter() - t0, 1e-9)

    hist = client.history_report()
    doc = client.doctor_report()
    series = hist.get("series", {})
    points = sum(len(s.get("points", ())) for s in series.values())
    rate_series = sum(1 for s in series.values()
                      if s.get("kind") == "counter-rate")
    overhead = (hist.get("sample_time_ms", 0.0) / (wall_s * 1000.0)) * 100.0
    return {
        "verdict": doc.get("verdict"),
        "verdict_score": doc.get("score"),
        "diagnoses": [{k: d.get(k) for k in ("family", "score")}
                      for d in doc.get("diagnoses", [])[:3]],
        "watchdog_events": doc.get("watchdog_events", 0),
        "sampler_overhead_pct": round(overhead, 4),
        "sample_count": hist.get("sample_count", 0),
        "sample_time_ms": hist.get("sample_time_ms", 0.0),
        "history_series": len(series),
        "history_points": points,
        "rate_series": rate_series,
        "interval_ms": interval_ms,
        "tuples_per_sec": round(events / wall_s, 1),
        "events": events,
        "num_keys": num_keys,
        "workload": "ysb_tumbling_count_minicluster",
    }


def child_health() -> None:
    """Health-plane child: CPU-pinned like child_api_path (sampler
    overhead is a same-backend wall-clock ratio; the parent must never
    lose the TPU relay)."""
    _emit({"event": "start", "device": "cpu-health", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": health_microbench()})


def run_health_child(timeout_s: float = 300.0) -> dict:
    """History/doctor microbench in a CPU-pinned child."""
    return _run_cpu_child('health', timeout_s)


def lint_summary() -> dict:
    """Full-registry lint over the installed package, timed — the
    `lint: {modules, rules, violations, analysis_ms}` block stamped into
    every BENCH_*.json next to `health`. Runs in-process (pure AST, no
    device), against the checked-in baseline so `violations` counts
    ACTIVE findings, not justified debt."""
    t0 = time.perf_counter()
    try:
        import pathlib

        import flink_tpu
        from flink_tpu.lint import Baseline, run_lint

        pkg = pathlib.Path(flink_tpu.__file__).parent
        bl_path = pkg.parent / "lint_baseline.json"
        baseline = Baseline.load(bl_path) if bl_path.exists() else None
        report = run_lint(pkg, baseline=baseline)
        return {
            "modules": report.modules_scanned,
            "rules": len(report.rules),
            "violations": len(report.violations),
            "analysis_ms": round((time.perf_counter() - t0) * 1e3, 1),
        }
    except Exception as e:  # noqa: BLE001 — the stamp must never sink a run
        return {"error": f"{type(e).__name__}: {e}",
                "analysis_ms": round((time.perf_counter() - t0) * 1e3, 1)}


def child_sql_path() -> None:
    """SQL-path child: CPU-pinned like child_api_path — the three-way
    comparison is CPU-jit vs CPU-jit (same backend all paths), and the
    parent must never lose the single-client TPU relay to it."""
    _emit({"event": "start", "device": "cpu-sql-path", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": sql_path_microbench()})


def run_sql_path_microbench_child(timeout_s: float = 300.0) -> dict:
    """SQL-path microbench in a CPU-pinned child (same backend all paths)."""
    return _run_cpu_child('sql-path', timeout_s)


def child_checkpoint() -> None:
    """Checkpoint-microbench child: CPU-pinned like child_cpu (the relay is
    single-client — a jax backend probe from the parent would wedge the TPU
    attempt), and the control-plane cost being measured is host-side."""
    _emit({"event": "start", "device": "cpu-checkpoint", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": checkpoint_microbench()})


def run_checkpoint_microbench_child(timeout_s: float = 300.0) -> dict:
    """Checkpoint microbench in a CPU-pinned child."""
    return _run_cpu_child('checkpoint', timeout_s)


def multichip_microbench(events: Optional[int] = None,
                         batch: int = 8192,
                         num_keys: Optional[int] = None,
                         span_event_ms: int = 64_000,
                         sweeps: int = 2,
                         devices: int = 0,
                         zipf_s: float = 1.0) -> dict:
    """Multichip SPMD scenario (ISSUE-11): the SAME fused DataStream YSB
    program — from_source().filter().key_by().window().count() with
    traceable UDFs — run single-chip and sharded over the device mesh
    (parallel.mesh.enabled), same backend, same data:

      - `fused_selected` pins that graph translation chose the
        DeviceChainRunner (the user-facing path, not a hand-built kernel),
        and `sharded_selected` that the runner's operator actually targets
        the mesh (mesh_devices > 1) — a silent fall-back to single-chip
        would otherwise still read as perfect parity;
      - `parity` is exact row-mode result equality mesh vs single-chip
        (the single-chip fused path is itself oracle-gated by the api_path
        scenario, so the chain of custody reaches the host oracle);
      - `scaling_efficiency` = mesh tuples/s / (single-chip tuples/s x
        devices). On a real n-chip mesh the acceptance bar is >= 0.8x
        linear; on the virtual CPU mesh (this child, and CI) every "chip"
        timeshares one host, so the ratio only gates against catastrophic
        regressions — the structural keys are the contract;
      - the zipf(`zipf_s`) SKEWED variant re-runs both sides with a
        power-law key distribution and reports
        `skewed_scaling_efficiency` plus the per-device telemetry it
        exercises (meshLoadSkew, per-device records) — an imbalanced mesh
        must be measurable, not inferred (ROADMAP item 4a's first step).
    """
    import jax
    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
        ParallelOptions,
    )
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import JobRuntime, build_runners

    events = events or int(
        os.environ.get("BENCH_MULTICHIP_EVENTS", str(1 << 20)))
    num_keys = num_keys or NUM_KEYS
    from flink_tpu.parallel.mesh import usable_mesh_size

    avail = len(jax.devices())
    n = usable_mesh_size(devices, avail, num_keys)
    if n < 2:
        return {"error": f"no usable mesh ({avail} device(s), "
                         f"{num_keys} keys)", "devices": int(n)}

    # zipf keys via the single-sourced stateless sampler (zipf_keys), hot
    # ranks spread over the key-id space so the hot key-GROUPS (and with
    # contiguous ranges, the hot DEVICES) are deterministic
    perm = np.random.default_rng(11).permutation(num_keys)

    def source(count, skewed: bool):
        def gen(idx):
            if skewed:
                camp = zipf_keys(idx, num_keys, zipf_s, hot_perm=perm)
            else:
                camp = (idx * 2654435761) % num_keys
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // count
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, count)

    t_filter = lambda col: col[:, 1] < 0.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731

    def build(count, mesh_on, *, skewed=False, columnar=True, stats=False):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, True)
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, num_keys)
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, columnar)
        cfg.set(ParallelOptions.MESH_ENABLED, mesh_on)
        if mesh_on:
            cfg.set(ParallelOptions.MESH_DEVICES, n)
        cfg.set(ObservabilityOptions.DEVICE_STATS_ENABLED, stats)
        if stats:
            # collect on every due tick so the smoke-scale run still folds
            cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 8)
            cfg.set(ObservabilityOptions.DEVICE_KEY_STATS_INTERVAL_MS, 0)
        env = StreamExecutionEnvironment(cfg)
        ds = env.from_source(
            source(count, skewed),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        sink = (ds.filter(t_filter, traceable=True)
                  .key_by(t_key, traceable=True)
                  .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
                  .aggregate("count")
                  .collect())
        return env, sink

    # ---- reroute gate: translation must pick the fused runner AND the
    # runner must actually target the sharded pipeline
    env_probe, _ = build(batch, True)
    runners, _ = build_runners(plan(env_probe._sinks), env_probe.config)
    fused = [r for r in runners if type(r).__name__ == "DeviceChainRunner"]
    fused_selected = bool(fused)
    mesh_devices = fused[0].op.mesh_devices() if fused else 1
    sharded_selected = mesh_devices > 1

    def run(count, mesh_on, *, skewed=False, columnar=True):
        env, sink = build(count, mesh_on, skewed=skewed, columnar=columnar)
        t0 = time.perf_counter()
        env.execute()
        return sink.results, count / max(time.perf_counter() - t0, 1e-9)

    # ---- parity gates in row mode (raw keys), exact equality
    n_parity = max(events // 8, batch)
    parity = {}
    for skewed in (False, True):
        rows = {
            mesh_on: sorted((int(k), int(v)) for k, v in
                            run(n_parity, mesh_on, skewed=skewed,
                                columnar=False)[0])
            for mesh_on in (True, False)
        }
        parity[skewed] = (len(rows[True]) > 0 and rows[True] == rows[False])

    # ---- timed runs: interleaved max-of-N sweeps (the PR-3 protocol)
    run(batch * 12, True)
    run(batch * 12, False)
    tps = {(m, s): 0.0 for m in (True, False) for s in (True, False)}
    for _sweep in range(sweeps):
        for skewed in (False, True):
            for mesh_on in (True, False):
                _r, t = run(events, mesh_on, skewed=skewed)
                tps[(mesh_on, skewed)] = max(tps[(mesh_on, skewed)], t)

    # ---- per-device telemetry under imbalance: one skewed mesh run with
    # the device plane on; the [n, K_local] fold must SEE the hot devices
    mesh_load_skew = None
    per_device = []
    key_skew = None
    try:
        env_t, _sink = build(max(events // 4, batch * 8), True,
                             skewed=True, stats=True)
        rt = JobRuntime(plan(env_t._sinks), env_t.config)
        rt.run()
        snap = rt.device_snapshot()
        for entry in snap["operators"].values():
            keys_blk = entry.get("keys") or {}
            if keys_blk.get("perDevice"):
                mesh_load_skew = keys_blk.get("meshLoadSkew")
                per_device = [e["records"] for e in keys_blk["perDevice"]]
                key_skew = keys_blk.get("keySkew")
                break
    except Exception as e:  # noqa: BLE001 — the block must survive
        per_device = [f"error: {e!r}"[:120]]

    eff = tps[(True, False)] / max(tps[(False, False)] * n, 1e-9)
    eff_skewed = tps[(True, True)] / max(tps[(False, True)] * n, 1e-9)
    return {
        "devices": int(n),
        "tuples_per_sec": round(tps[(True, False)], 1),
        "single_chip_tuples_per_sec": round(tps[(False, False)], 1),
        "scaling_efficiency": round(eff, 4),
        "skewed_tuples_per_sec": round(tps[(True, True)], 1),
        "skewed_single_chip_tuples_per_sec": round(tps[(False, True)], 1),
        "skewed_scaling_efficiency": round(eff_skewed, 4),
        "parity": bool(parity[False]),
        "skewed_parity": bool(parity[True]),
        "fused_selected": bool(fused_selected),
        "sharded_selected": bool(sharded_selected),
        "mesh_load_skew": mesh_load_skew,
        "per_device_records": per_device[:16],
        "key_skew": key_skew,
        "zipf_s": zipf_s,
        "events": events,
        "num_keys": num_keys,
        "window_ms": WINDOW_MS,
        "slide_ms": SLIDE_MS,
        "workload": "ysb_sliding_count_datastream_api_spmd",
    }


def child_multichip() -> None:
    """Multichip child: CPU-pinned with a FORCED 8-device virtual mesh —
    the single-client TPU relay exposes one chip, so the mesh promotion is
    exercised on host devices (the same program rides ICI on real
    multi-chip hardware; the driver's dryrun covers compile-correctness
    there)."""
    _emit({"event": "start", "device": "cpu-multichip", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": multichip_microbench()})


def run_multichip_child(timeout_s: float = 420.0) -> dict:
    """Multichip microbench in a CPU-pinned child on the 8-device virtual
    mesh (the single-client TPU relay exposes one chip; the same program
    rides ICI on real multi-chip hardware)."""
    return _run_cpu_child('multichip', timeout_s, force_mesh=True)


def millikey_microbench(events: Optional[int] = None,
                        batch: int = 8192,
                        num_keys: Optional[int] = None,
                        hot_capacity: int = 4096,
                        parity_keys: int = 1 << 15,
                        span_event_ms: int = 64_000,
                        zipf_s: float = 1.0,
                        admission_min_count: int = 2,
                        mesh: bool = True) -> dict:
    """Million-key state plane scenario (ISSUE-12, ROADMAP item 2): the
    YSB sliding-count DataStream job over a key vocabulary three orders
    of magnitude larger than the resident HBM capacity
    (state.tier.enabled): at most `hot_capacity` keys own device ring
    rows, the rest aggregate in the cold tier, and checkpoints are
    incremental (state.changelog.enabled).

    Gates, per variant (uniform + zipf(`zipf_s`)):

      - `parity`: exact row-mode equality of the TIERED run against the
        UNTIRED fused run at `parity_keys` cardinality (the untired
        operator materializes every key as an HBM row, so the oracle
        cannot hold the full vocabulary — that impossibility is the
        feature's premise) AND of the full-cardinality tiered run
        against a numpy host oracle over the identical record stream;
      - `resident_keys <= hot_capacity` with `evictions > 0`: the
        vocabulary actually bounds HBM instead of growing;
      - `incremental_ratio`: median per-checkpoint-interval changelog
        bytes / the materialized full-state base size — the < 0.25
        acceptance bar for delta-scaled snapshot cost;
      - `sharded_parity`: the same tiered job over the device mesh
        (parallel.mesh.enabled) when >= 2 devices are visible.
    """
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import (
        CheckpointingOptions,
        Configuration,
        ExecutionOptions,
        ParallelOptions,
        StateTierOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    import statistics as _stats
    import tempfile as _tempfile

    events = events or int(
        os.environ.get("BENCH_MILLIKEY_EVENTS", str(1 << 18)))
    num_keys = num_keys or int(
        os.environ.get("BENCH_MILLIKEY_KEYS", str(10_000_000)))

    def keys_of(idx: np.ndarray, n_keys: int, skewed: bool) -> np.ndarray:
        if skewed:
            # the single-sourced STATELESS sampler (zipf_keys): the host
            # oracle re-generates the stream under different chunk
            # boundaries, so a chunk-seeded rng would diverge
            return zipf_keys(idx, n_keys, zipf_s)
        return ((idx * 2654435761) % n_keys).astype(np.int64)

    def ts_of(idx: np.ndarray, count: int) -> np.ndarray:
        return (10_000 + idx * span_event_ms // count).astype(np.int64)

    def source(count, n_keys, skewed):
        def gen(idx):
            return Batch(keys_of(idx, n_keys, skewed), ts_of(idx, count))

        return DataGeneratorSource(gen, count)

    def build(count, n_keys, skewed, *, tiered, cap, mesh_on=False,
              chk=None, admission=None):
        cfg = Configuration()
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, max(n_keys, 1024))
        if tiered:
            cfg.set(StateTierOptions.TIER_ENABLED, True)
            cfg.set(StateTierOptions.HOT_KEY_CAPACITY, cap)
            cfg.set(StateTierOptions.CHANGELOG_ENABLED, True)
            # the tiny-LFU doorkeeper: one-touch keys of the heavy tail
            # aggregate cold instead of churning hot rows — the realistic
            # operating point at key cardinality >> capacity
            cfg.set(StateTierOptions.ADMISSION_MIN_COUNT,
                    admission_min_count if admission is None else admission)
            if chk is not None:
                cfg.set(StateTierOptions.CHANGELOG_DIR,
                        os.path.join(chk, "changelog"))
                cfg.set(StateTierOptions.COLD_DIR, os.path.join(chk, "cold"))
        if chk is not None:
            cfg.set(CheckpointingOptions.INTERVAL_MS, 1)
            cfg.set(CheckpointingOptions.DIRECTORY, os.path.join(chk, "chk"))
        if mesh_on:
            cfg.set(ParallelOptions.MESH_ENABLED, True)
        env = StreamExecutionEnvironment(cfg)
        ds = env.from_source(
            source(count, n_keys, skewed),
            watermark_strategy=WatermarkStrategy
            .for_bounded_out_of_orderness(0),
        )
        sink = CollectSink()
        (ds.key_by(lambda col: col, vectorized=True)
           .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
           .count()
           .sink_to(sink))
        return env, sink

    def run(count, n_keys, skewed, *, tiered, cap, mesh_on=False,
            chk=None, admission=None):
        env, sink = build(count, n_keys, skewed, tiered=tiered, cap=cap,
                          mesh_on=mesh_on, chk=chk, admission=admission)
        t0 = time.perf_counter()
        client = env.execute_async("millikey")
        client.wait(600)
        dt = max(time.perf_counter() - t0, 1e-9)
        rows = sorted((int(k), int(n)) for k, n in sink.results)
        return client, rows, count / dt

    def host_oracle(count, n_keys, skewed):
        """Expected (key, count) rows over ALL fired windows: every
        record lands in spw sliding windows — a pure numpy fold that
        holds the full vocabulary where the untired operator cannot."""
        out: dict = {}
        spw = WINDOW_MS // SLIDE_MS
        for lo in range(0, count, 1 << 18):
            idx = np.arange(lo, min(lo + (1 << 18), count), dtype=np.int64)
            k = keys_of(idx, n_keys, skewed)
            s = ts_of(idx, count) // SLIDE_MS
            for shift in range(spw):
                # window j = s - shift contains every record whose slide
                # granule is s, for shift in [0, spw)
                pairs, cnts = np.unique(
                    np.stack([k, s - shift], axis=1), axis=0,
                    return_counts=True)
                for (kk, jj), c in zip(pairs.tolist(), cnts.tolist()):
                    out[(kk, jj)] = out.get((kk, jj), 0) + c
        return sorted((kk, c) for (kk, _jj), c in out.items())

    result: dict = {"events": events, "num_keys": num_keys,
                    "hot_key_capacity": hot_capacity,
                    "parity_keys": parity_keys, "zipf_s": zipf_s,
                    "window_ms": WINDOW_MS, "slide_ms": SLIDE_MS,
                    "workload": "ysb_sliding_count_datastream_tiered"}

    for skewed, label in ((False, "uniform"), (True, "zipf")):
        blk: dict = {}
        # ---- reduced-cardinality exact parity: tiered vs untired fused
        n_par = min(events, max(batch * 8, 1 << 16))
        p_keys = min(parity_keys, num_keys)
        _c, rows_ref, _t = run(n_par, p_keys, skewed, tiered=False,
                               cap=hot_capacity)
        chk = _tempfile.mkdtemp(prefix="flink-tpu-millikey-")
        try:
            c_t, rows_t, _t2 = run(n_par, p_keys, skewed, tiered=True,
                                   cap=min(hot_capacity, p_keys // 8),
                                   chk=chk)
            blk["parity_vs_untired"] = (len(rows_t) > 0
                                        and rows_t == rows_ref)
            tier_par = _tier_payload(c_t)
            blk["parity_run_evictions"] = (tier_par or {}).get("evictions")
        finally:
            import shutil as _sh

            _sh.rmtree(chk, ignore_errors=True)

        # ---- full-cardinality tiered run: host-oracle parity, bounded
        # residency, throughput, incremental checkpoint ratio
        chk = _tempfile.mkdtemp(prefix="flink-tpu-millikey-")
        try:
            client, rows, tps = run(events, num_keys, skewed, tiered=True,
                                    cap=hot_capacity, chk=chk)
            expected = host_oracle(events, num_keys, skewed)
            blk["parity"] = len(rows) > 0 and rows == expected
            blk["tuples_per_sec"] = round(tps, 1)
            tier = _tier_payload(client)
            if tier is not None:
                blk.update(
                    vocab_size=tier["vocabSize"],
                    resident_keys=tier["residentKeys"],
                    evictions=tier["evictions"],
                    promotions=tier["promotions"],
                    spilled_bytes=tier["spilledBytes"],
                    cold_records=tier["coldRecords"],
                )
                blk["resident_bounded"] = \
                    tier["residentKeys"] <= hot_capacity
            mgr = _tier_manager(client)
            if mgr is not None and mgr.interval_bytes_history \
                    and mgr.last_base_bytes() > 0:
                med = _stats.median(mgr.interval_bytes_history)
                blk["changelog_interval_bytes_p50"] = int(med)
                blk["full_snapshot_bytes"] = mgr.last_base_bytes()
                blk["incremental_ratio"] = round(
                    med / mgr.last_base_bytes(), 6)
                blk["checkpoints"] = len(mgr.interval_bytes_history)
        finally:
            import shutil as _sh

            _sh.rmtree(chk, ignore_errors=True)
        result[label] = blk

    # ---- sharded variant: the same tiered job over the mesh
    import jax as _jax

    from flink_tpu.parallel.mesh import usable_mesh_size

    n_mesh = usable_mesh_size(0, len(_jax.devices()), hot_capacity) \
        if mesh else 1
    if n_mesh >= 2:
        n_par = min(events, max(batch * 4, 1 << 15))
        p_keys = min(parity_keys, num_keys)
        _c, rows_ref, _t = run(n_par, p_keys, False, tiered=False,
                               cap=hot_capacity)
        # admission doorkeeper off for this leg: the point is the
        # demote/promote machinery ON the mesh, so force churn. chk dir
        # given so the changelog/cold temp dirs are cleaned up with it.
        chk = _tempfile.mkdtemp(prefix="flink-tpu-millikey-")
        try:
            c_m, rows_m, _t2 = run(n_par, p_keys, False, tiered=True,
                                   cap=min(hot_capacity, p_keys // 8),
                                   mesh_on=True, admission=1, chk=chk)
            tier_m = _tier_payload(c_m)
        finally:
            import shutil as _sh

            _sh.rmtree(chk, ignore_errors=True)
        result["sharded"] = {
            "devices": int(n_mesh),
            "parity": len(rows_m) > 0 and rows_m == rows_ref,
            "evictions": (tier_m or {}).get("evictions"),
            "mesh_selected": bool(
                c_m._runtime is not None
                and c_m._runtime.mesh_devices() > 1),
        }
    else:
        result["sharded"] = {"devices": int(n_mesh), "skipped": True}

    # headline continuity keys
    result["parity"] = bool(result["uniform"].get("parity")
                            and result["zipf"].get("parity")
                            and result["uniform"].get("parity_vs_untired")
                            and result["zipf"].get("parity_vs_untired"))
    result["tuples_per_sec"] = result["uniform"].get("tuples_per_sec", 0.0)
    result["incremental_ratio"] = result["uniform"].get("incremental_ratio")
    return result


def _tier_payload(client) -> Optional[dict]:
    """The tier block of the job's device snapshot (MiniCluster path)."""
    try:
        snap = client._runtime.device_snapshot()
        for entry in snap["operators"].values():
            if entry.get("tier"):
                return entry["tier"]
    except Exception:  # noqa: BLE001 — the bench must survive
        return None
    return None


def _tier_manager(client):
    """The live TieredStateManager of the job's window runner."""
    try:
        for r in client._runtime.runners:
            t = getattr(getattr(r, "op", None), "tier", None)
            if t is not None:
                return t
    except Exception:  # noqa: BLE001
        return None
    return None


def child_millikey() -> None:
    """Millikey child: CPU-pinned with the 8-device virtual mesh forced,
    so the sharded tiered variant exercises a real mesh (single-client
    TPU relay exposes one chip)."""
    _emit({"event": "start", "device": "cpu-millikey", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": millikey_microbench()})


def run_millikey_child(timeout_s: float = 600.0) -> dict:
    """Millikey microbench in a CPU-pinned child on the virtual mesh."""
    return _run_cpu_child('millikey', timeout_s, force_mesh=True)


def skew_matrix_microbench(events: Optional[int] = None,
                           batch: int = 2048,
                           num_keys: Optional[int] = None,
                           span_event_ms: int = 64_000,
                           zipf_s: float = 1.0,
                           sweeps: int = 1) -> dict:
    """Skew scenario matrix (ISSUE-15, ROADMAP 4c): the PDSP-Bench
    parallelism x workload x skew reporting grid over the fused
    DataStream chain, plus the skew-ADAPTIVE flagship leg.

      - `cells`: every (workload, parallelism, skew) combination —
        workloads ysb_count (filter+keyBy+sliding count) and ysb_sum
        (same chain, value aggregation), parallelism 1 and the mesh,
        keys uniform and zipf(`zipf_s`) via the single-sourced stateless
        sampler (`zipf_keys`) — tuples/s per cell, with EXACT mesh vs
        single-chip row parity per (workload, skew);
      - the zipf leg's hot ranks are deliberately CLUSTERED into device
        0's key-groups (one hot key per group, so the placement is
        pathological but splittable) — the adjacent-hot-keys shape the
        static owner function cannot fix and the rebalancer exists to;
      - `combine_parity`: parallel.mesh.local-combine on vs off, byte
        parity (the perf-switch-not-semantics-switch proof at bench
        scale), plus `local_combine_active` pinning the combiner
        actually engaged;
      - the ADAPTIVE leg (`adaptive` block): the mesh zipf job with
        local-combine + skew-rebalance enabled on the in-process job
        master — `rebalances` (must be > 0 under this traffic),
        `post_rebalance_mesh_load_skew` vs `static_mesh_load_skew`, and
        `skewed_uniform_ratio` = adaptive zipf tput / uniform tput (the
        >= 0.8 acceptance bar is judged on real TPU hardware; the CPU
        mesh gates only catastrophic regressions).
    """
    import jax
    import jax.numpy as jnp

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ObservabilityOptions,
        ParallelOptions,
    )
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.parallel.mesh import usable_mesh_size
    from flink_tpu.parallel.routing import choose_key_groups
    from flink_tpu.runtime.executor import build_runners

    # half the multichip scale by default: the matrix runs 8 timed cells
    # + 2 adaptive legs + 14 parity runs, and the child must leave the
    # parent's budget room for the TPU attempt
    events = events or int(
        os.environ.get("BENCH_SKEW_EVENTS", str(1 << 19)))
    num_keys = num_keys or NUM_KEYS
    avail = len(jax.devices())
    n = usable_mesh_size(0, avail, num_keys)
    if n < 2:
        return {"error": f"no usable mesh ({avail} device(s), "
                         f"{num_keys} keys)", "devices": int(n)}

    # adversarial hot placement: the top G/n zipf ranks land one per
    # key-group of DEVICE 0's contiguous range (kids 0, Kg, 2*Kg, ...) —
    # maximally imbalanced under static routing, fully splittable by a
    # key-group rebalance; the tail fills the rest of the id space
    G = choose_key_groups(num_keys, n)
    kg = num_keys // G
    hot_ids = np.arange(G // n, dtype=np.int64) * kg
    rest = np.setdiff1d(np.arange(num_keys, dtype=np.int64), hot_ids)
    perm = np.concatenate(
        [hot_ids, np.random.default_rng(7).permutation(rest)])

    def keys_of(idx, skewed: bool):
        if skewed:
            return zipf_keys(idx, num_keys, zipf_s, hot_perm=perm)
        return ((idx * 2654435761) % num_keys).astype(np.int64)

    def source(count, skewed: bool):
        def gen(idx):
            camp = keys_of(idx, skewed)
            etype = idx % 3
            col = np.stack([camp, etype], axis=1).astype(np.float32)
            ts = 10_000 + idx * span_event_ms // count
            return Batch(col, ts.astype(np.int64))

        return DataGeneratorSource(gen, count)

    t_filter = lambda col: col[:, 1] < 2.5                    # noqa: E731
    t_key = lambda col: col[:, 0].astype(jnp.int32)           # noqa: E731
    t_val = lambda col: col[:, 1]                             # noqa: E731
    WORKLOADS = ("ysb_count", "ysb_sum")

    def build(count, mesh_on, *, skewed, workload, combine=False,
              rebalance=False, columnar=True, stats=False):
        cfg = Configuration()
        cfg.set(ExecutionOptions.CHAIN_FUSION, True)
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, num_keys)
        cfg.set(ExecutionOptions.COLUMNAR_OUTPUT, columnar)
        # dispatch every 8 steps: the rebalancer (and the key-stats fold
        # it reads) needs device-resident state EARLY in the run, and
        # every leg shares the geometry so the ratio isolates traffic
        # shape, not dispatch cadence
        cfg.set(ExecutionOptions.SUPERBATCH_STEPS, 8)
        cfg.set(ParallelOptions.MESH_ENABLED, mesh_on)
        if mesh_on:
            cfg.set(ParallelOptions.MESH_DEVICES, n)
        cfg.set(ParallelOptions.MESH_LOCAL_COMBINE, combine)
        cfg.set(ParallelOptions.MESH_SKEW_REBALANCE, rebalance)
        cfg.set(ParallelOptions.MESH_REBALANCE_SKEW_THRESHOLD, 1.2)
        cfg.set(ParallelOptions.MESH_REBALANCE_INTERVAL_MS, 0)
        cfg.set(ObservabilityOptions.DEVICE_STATS_ENABLED, stats)
        if stats:
            cfg.set(ObservabilityOptions.DEVICE_KEY_STATS_INTERVAL_MS, 0)
        env = StreamExecutionEnvironment(cfg)
        ds = env.from_source(
            source(count, skewed),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0),
        )
        chain = (ds.filter(t_filter, traceable=True)
                   .key_by(t_key, traceable=True)
                   .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS)))
        if workload == "ysb_sum":
            sink = chain.aggregate("sum", t_val,
                                   value_traceable=True).collect()
        else:
            sink = chain.aggregate("count").collect()
        return env, sink

    # ---- reroute gate: the fused runner must target the mesh, and with
    # the combiner flag on, the decomposable count/sum aggregates must
    # actually engage the pre-exchange combine
    env_probe, _ = build(batch, True, skewed=False, workload="ysb_count",
                         combine=True)
    runners, _ = build_runners(plan(env_probe._sinks), env_probe.config)
    fused = [r for r in runners if type(r).__name__ == "DeviceChainRunner"]
    fused_selected = bool(fused)
    mesh_devices = fused[0].op.mesh_devices() if fused else 1
    sharded_selected = mesh_devices > 1
    local_combine_active = bool(
        fused and getattr(fused[0].op.pipe, "local_combine", False))

    def run(count, mesh_on, *, skewed, workload, combine=False,
            columnar=True):
        env, sink = build(count, mesh_on, skewed=skewed, workload=workload,
                          combine=combine, columnar=columnar)
        t0 = time.perf_counter()
        env.execute()
        return sink.results, count / max(time.perf_counter() - t0, 1e-9)

    def rows_of(results):
        return sorted((int(k), float(v)) for k, v in results)

    # ---- parity gates, row mode: single-chip vs mesh vs mesh+combine
    n_parity = max(events // 8, batch)
    parity: dict = {}
    combine_parity = True
    for workload in WORKLOADS:
        for skewed, label in ((False, "uniform"), (True, "zipf")):
            ref = rows_of(run(n_parity, False, skewed=skewed,
                              workload=workload, columnar=False)[0])
            mesh_rows = rows_of(run(n_parity, True, skewed=skewed,
                                    workload=workload, columnar=False)[0])
            comb_rows = rows_of(run(n_parity, True, skewed=skewed,
                                    workload=workload, combine=True,
                                    columnar=False)[0])
            parity[f"{workload}/{label}"] = (len(ref) > 0
                                             and mesh_rows == ref)
            combine_parity = combine_parity and comb_rows == ref

    # ---- the matrix cells: interleaved max-of-N sweeps
    tps: dict = {}
    for _sweep in range(sweeps):
        for workload in WORKLOADS:
            for skewed, label in ((False, "uniform"), (True, "zipf")):
                for par in (1, n):
                    _r, t = run(events, par > 1, skewed=skewed,
                                workload=workload)
                    cell = (workload, par, label)
                    tps[cell] = max(tps.get(cell, 0.0), t)
    cells = [
        {"workload": w, "parallelism": p, "skew": s,
         "tuples_per_sec": round(t, 1)}
        for (w, p, s), t in sorted(tps.items())
    ]

    # ---- static-routing skew telemetry under the adversarial zipf leg
    static_skew = None
    try:
        from flink_tpu.runtime.executor import JobRuntime

        env_t, _ = build(max(events // 4, batch * 8), True, skewed=True,
                         workload="ysb_count", stats=True)
        rt = JobRuntime(plan(env_t._sinks), env_t.config)
        rt.run()
        for entry in rt.device_snapshot()["operators"].values():
            blk = entry.get("keys") or {}
            if blk.get("meshLoadSkew") is not None:
                static_skew = blk["meshLoadSkew"]
                break
    except Exception as e:  # noqa: BLE001 — the block must survive
        static_skew = f"error: {e!r}"[:120]

    # ---- the adaptive leg: local-combine + skew-rebalance on the
    # in-process job master (the rebalancer lives there), uniform AND
    # zipf, telemetry from the final attempt's device snapshot
    adaptive: dict = {}
    post_skew = None
    rebalances = 0
    try:
        def run_adaptive(skewed: bool):
            # stats on for BOTH legs: the ratio must isolate the traffic
            # shape, not the observability plane's cost
            env, _sink = build(events, True, skewed=skewed,
                               workload="ysb_count", combine=True,
                               rebalance=True, stats=True)
            t0 = time.perf_counter()
            client = env.execute_async(
                "skew-adaptive" if skewed else "uniform-adaptive")
            client.wait(600)
            dt = max(time.perf_counter() - t0, 1e-9)
            return client, events / dt

        ref_rows = rows_of(run(n_parity, False, skewed=True,
                               workload="ysb_count", columnar=False)[0])
        client_u, tps_u = run_adaptive(False)
        client_z, tps_z = run_adaptive(True)
        rebalances = int(client_z.mesh_rebalances)
        for entry in client_z._runtime.device_snapshot()[
                "operators"].values():
            blk = entry.get("keys") or {}
            if blk.get("meshLoadSkew") is not None:
                post_skew = blk["meshLoadSkew"]
                break
        # adaptive parity at reduced scale: the rebalanced job's rows
        # must equal the single-chip reference's
        env_p, sink_p = build(n_parity, True, skewed=True,
                              workload="ysb_count", combine=True,
                              rebalance=True, columnar=False)
        client_p = env_p.execute_async("skew-adaptive-parity")
        client_p.wait(600)
        adaptive = {
            "uniform_tuples_per_sec": round(tps_u, 1),
            "zipf_tuples_per_sec": round(tps_z, 1),
            "skewed_uniform_ratio": round(tps_z / max(tps_u, 1e-9), 4),
            "rebalances": rebalances,
            "routing_version":
                client_z._runtime.mesh_routing_version(),
            "parity": rows_of(sink_p.results) == ref_rows
                and len(ref_rows) > 0,
        }
    except Exception as e:  # noqa: BLE001 — the block must survive
        adaptive = {"error": repr(e)[:300]}

    matrix_parity = all(parity.values())
    return {
        "devices": int(n),
        "zipf_s": zipf_s,
        "num_keys": num_keys,
        "events": events,
        "workloads": list(WORKLOADS),
        "cells": cells,
        "cell_parity": parity,
        "parity": bool(matrix_parity),
        "combine_parity": bool(combine_parity),
        "fused_selected": bool(fused_selected),
        "sharded_selected": bool(sharded_selected),
        "local_combine_active": bool(local_combine_active),
        "static_mesh_load_skew": static_skew,
        "post_rebalance_mesh_load_skew": post_skew,
        "rebalances": rebalances,
        "adaptive": adaptive,
        "skewed_uniform_ratio": adaptive.get("skewed_uniform_ratio"),
        "workload": "ysb_skew_matrix_datastream_spmd",
    }


def child_skew_matrix() -> None:
    """Skew-matrix child: CPU-pinned with the FORCED 8-device virtual mesh
    (the single-client TPU relay exposes one chip; the same programs ride
    ICI on real multi-chip hardware)."""
    _emit({"event": "start", "device": "cpu-skew-matrix", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": skew_matrix_microbench()})


def run_skew_matrix_child(timeout_s: float = 600.0) -> dict:
    """Skew matrix in a CPU-pinned child on the forced 8-device virtual
    mesh."""
    return _run_cpu_child('skew-matrix', timeout_s, force_mesh=True)


def join_microbench(events: Optional[int] = None,
                    batch: int = 1024,
                    num_keys: int = 2048,
                    span_event_ms: int = 64_000,
                    zipf_s: float = 1.0) -> dict:
    """NEXMark-derived streaming-join scenarios (ISSUE-16): the two-input
    keyed join on the device bucket ring vs the host join oracle.

      - `nexmark_q3` (local item): persons JOIN auctions ON seller with a
        category filter on the auction side, SLIDING window — the
        filter+join shape;
      - `nexmark_q8` (monitor new users): persons JOIN auctions ON seller
        over a TUMBLING window — the pure windowed equi-join;
      - both scenarios run UNIFORM and ZIPF(`zipf_s`) key legs (the zipf
        leg concentrates records per (key, bucket), forcing the adaptive
        bucket-capacity growth path), each at EXACT row parity against
        the same job with execution.join.device-enabled off — the host
        `WindowJoinRunner` oracle;
      - `join_tuples_per_sec` / `host_join_tuples_per_sec` /
        `speedup_vs_host_join` per scenario — the >= 20x bar is judged on
        real TPU hardware (the CPU child gates parity and selection, not
        the ratio);
      - `sql` block: the q8 shape as SQL through the planner's JOIN
        lowering — `sql_fused_selected` (the fused runner actually
        chosen), the explain describing the device path, row parity vs
        the interpreted leg, and `fallback_attributed` pinning that a
        FULL OUTER query refuses with the catalogued reason instead of a
        bare error;
      - `sharded` block: the q8 job on the forced 8-device mesh (the
        sharded ring pipeline), parity vs single-chip.
    """
    import jax

    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import (
        SlidingEventTimeWindows,
        TumblingEventTimeWindows,
    )
    from flink_tpu.config import (
        Configuration,
        ExecutionOptions,
        ParallelOptions,
    )
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.graph.transformation import plan
    from flink_tpu.runtime.executor import build_runners
    from flink_tpu.utils.arrays import obj_array

    events = events or int(
        os.environ.get("BENCH_JOIN_EVENTS", str(1 << 14)))
    devices = len(jax.devices())

    def keys_of(idx, skewed: bool):
        if skewed:
            return zipf_keys(idx, num_keys, zipf_s)
        return ((idx * 2654435761) % num_keys).astype(np.int64)

    def source(count, side: str, skewed: bool):
        """Person/auction record stream: (key, payload, category)."""
        def gen(idx):
            ks = keys_of(idx, skewed)
            cat = idx % 3
            rows = obj_array([(int(k), f"{side}{int(i)}", int(c))
                              for k, i, c in zip(ks, idx, cat)])
            ts = 10_000 + idx * span_event_ms // count
            return Batch(rows, ts.astype(np.int64))

        return DataGeneratorSource(gen, count)

    def build(count, scenario: str, *, device, skewed, mesh_on=False):
        cfg = Configuration()
        cfg.set(ExecutionOptions.BATCH_SIZE, batch)
        cfg.set(ExecutionOptions.KEY_CAPACITY, num_keys)
        cfg.set(ExecutionOptions.DEVICE_JOINS, device)
        cfg.set(ParallelOptions.MESH_ENABLED, mesh_on)
        env = StreamExecutionEnvironment(cfg)
        wm = WatermarkStrategy.for_bounded_out_of_orderness(0)
        persons = env.from_source(source(count, "p", skewed),
                                  watermark_strategy=wm)
        auctions = env.from_source(source(count, "a", skewed),
                                   watermark_strategy=wm)
        if scenario == "nexmark_q3":
            auctions = auctions.filter(lambda r: r[2] == 0)
            window = SlidingEventTimeWindows.of(2000, 1000)
        else:
            window = TumblingEventTimeWindows.of(1000)
        sink = (persons.join(auctions)
                .where(lambda r: r[0]).equal_to(lambda r: r[0])
                .window(window)
                .apply(lambda p, a: (p[0], p[1], a[1]))
                .collect())
        return env, sink

    # ---- reroute gate: the factory must pick the device runner
    env_probe, _ = build(batch, "nexmark_q8", device=True, skewed=False)
    runners, _ = build_runners(plan(env_probe._sinks), env_probe.config)
    fused_selected = any(
        type(r).__name__ == "DeviceJoinRunner" for r in runners)

    def run(count, scenario, *, device, skewed, mesh_on=False):
        env, sink = build(count, scenario, device=device, skewed=skewed,
                          mesh_on=mesh_on)
        t0 = time.perf_counter()
        env.execute()
        dt = max(time.perf_counter() - t0, 1e-9)
        return sorted(sink.results), 2 * count / dt

    scenarios: dict = {}
    all_parity = True
    n_parity = max(events // 4, batch)
    for scenario in ("nexmark_q3", "nexmark_q8"):
        blk: dict = {"window": ("sliding(2000,1000)"
                                if scenario == "nexmark_q3"
                                else "tumble(1000)")}
        for skewed, label in ((False, "uniform"), (True, "zipf")):
            ref, _ = run(n_parity, scenario, device=False, skewed=skewed)
            dev, _ = run(n_parity, scenario, device=True, skewed=skewed)
            blk[f"parity_{label}"] = (len(ref) > 0 and dev == ref)
            all_parity = all_parity and blk[f"parity_{label}"]
        rows_d, tps_d = run(events, scenario, device=True, skewed=True)
        rows_h, tps_h = run(events, scenario, device=False, skewed=True)
        blk["matches"] = len(rows_d)
        blk["join_tuples_per_sec"] = round(tps_d, 1)
        blk["host_join_tuples_per_sec"] = round(tps_h, 1)
        blk["speedup_vs_host_join"] = round(tps_d / max(tps_h, 1e-9), 4)
        scenarios[scenario] = blk

    # ---- sharded leg: q8 on the forced mesh vs the single-chip rows
    sharded: dict = {}
    try:
        ref, _ = run(n_parity, "nexmark_q8", device=True, skewed=True)
        env_m, sink_m = build(n_parity, "nexmark_q8", device=True,
                              skewed=True, mesh_on=True)
        runners_m, _ = build_runners(plan(env_m._sinks), env_m.config)
        djr = [r for r in runners_m
               if type(r).__name__ == "DeviceJoinRunner"]
        env_m2, sink_m2 = build(n_parity, "nexmark_q8", device=True,
                                skewed=True, mesh_on=True)
        env_m2.execute()
        sharded = {
            "sharded_selected": bool(djr and djr[0].sharded),
            "parity": sorted(sink_m2.results) == ref and len(ref) > 0,
            "devices": devices,
        }
    except Exception as e:  # noqa: BLE001 — the block must survive
        sharded = {"error": repr(e)[:300]}

    # ---- SQL front door: q8 as SQL through the planner's JOIN lowering
    sql: dict = {}
    try:
        from flink_tpu.table.table_env import TableEnvironment, TableSchema

        def sql_env(device: bool):
            cfg = Configuration()
            cfg.set(ExecutionOptions.BATCH_SIZE, batch)
            cfg.set(ExecutionOptions.DEVICE_JOINS, device)
            env = StreamExecutionEnvironment(cfg)
            tenv = TableEnvironment(env)
            n = min(n_parity, 4096)
            idx = np.arange(n)
            pk, ak = keys_of(idx, True), keys_of(idx + n, True)
            ts = (10_000 + idx * span_event_ms // n).astype(np.int64)
            tenv.from_rows("person", [
                {"id": int(k), "name": f"p{i}", "ptime": int(t)}
                for i, (k, t) in enumerate(zip(pk, ts))],
                TableSchema(["id", "name", "ptime"], rowtime="ptime"))
            tenv.from_rows("auction", [
                {"seller": int(k), "itemid": f"a{i}", "atime": int(t)}
                for i, (k, t) in enumerate(zip(ak, ts))],
                TableSchema(["seller", "itemid", "atime"],
                            rowtime="atime"))
            return env, tenv

        q8_sql = ("SELECT p.id, p.name, a.itemid FROM person AS p "
                  "JOIN auction AS a ON p.id = a.seller "
                  "WINDOW TUMBLE(INTERVAL '1' SECOND)")
        env_s, tenv_s = sql_env(True)
        report = tenv_s.explain_sql(q8_sql)
        sink_s = tenv_s.sql_query(q8_sql).collect()
        runners_s, _ = build_runners(plan(env_s._sinks), env_s.config)
        sql_fused = [r for r in runners_s
                     if type(r).__name__ == "DeviceJoinRunner"]
        t0 = time.perf_counter()
        env_s.execute()
        sql_dt = max(time.perf_counter() - t0, 1e-9)

        env_i, tenv_i = sql_env(False)
        sink_i = tenv_i.sql_query(q8_sql).collect()
        env_i.execute()

        def norm(rows):
            return sorted(tuple(sorted(r.items())) for r in rows)

        full_report = tenv_s.explain_sql(
            "SELECT p.id, a.itemid FROM person AS p FULL OUTER JOIN "
            "auction AS a ON p.id = a.seller")
        sql = {
            "sql_fused_selected": bool(
                report.fused and sql_fused and sql_fused[0].sql_origin),
            "explain": report.describe()[:400],
            "parity": (norm(sink_s.results) == norm(sink_i.results)
                       and len(sink_s.results) > 0),
            "sql_join_tuples_per_sec": round(
                2 * min(n_parity, 4096) / sql_dt, 1),
            "fallback_attributed":
                full_report.reason == "join-full-outer",
        }
    except Exception as e:  # noqa: BLE001 — the block must survive
        sql = {"error": repr(e)[:300]}

    q8 = scenarios["nexmark_q8"]
    return {
        "devices": devices,
        "events": events,
        "num_keys": num_keys,
        "zipf_s": zipf_s,
        "scenarios": scenarios,
        "parity": bool(all_parity),
        "fused_selected": bool(fused_selected),
        "join_tuples_per_sec": q8["join_tuples_per_sec"],
        "host_join_tuples_per_sec": q8["host_join_tuples_per_sec"],
        "speedup_vs_host_join": q8["speedup_vs_host_join"],
        "sharded": sharded,
        "sql": sql,
        "workload": "nexmark_join_device_ring",
    }


def child_join() -> None:
    """Join child: CPU-pinned on the forced 8-device virtual mesh (the
    sharded leg needs devices; real multi-chip rides ICI)."""
    _emit({"event": "start", "device": "cpu-join", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": join_microbench()})


def run_join_child(timeout_s: float = 600.0) -> dict:
    """Join scenarios in a CPU-pinned child on the forced 8-device
    virtual mesh."""
    return _run_cpu_child('join', timeout_s, force_mesh=True)


def chaos_microbench(names: Optional[list] = None) -> dict:
    """Resilience gate (ISSUE-10): run the chaos scenario matrix
    (flink_tpu/chaos/scenarios.py — injected rpc flaps, dataplane blips,
    torn checkpoints, storage brownouts, device dispatch errors, TM crash
    mid-rescale, heartbeat partitions) and emit
    chaos.{scenarios_passed, scenarios_total, parity, recovery_time_ms_p50}
    so recovery behavior is tracked per PR exactly like throughput. Every
    scenario asserts exactly-once parity vs an undisturbed oracle run and
    the expected ExceptionHistory/recovery-timeline shape (injected
    attribution included)."""
    from flink_tpu.chaos import scenarios

    result = scenarios.run_matrix(names)
    # compact per-scenario view for the artifact (full detail on failure)
    result["scenarios"] = [
        {k: r.get(k) for k in ("name", "path", "passed", "parity",
                               "restarts", "recovery_ms", "injected_fired",
                               "attributed", "skipped", "detail")}
        for r in result["scenarios"]
    ]
    return result


def child_chaos() -> None:
    """Chaos-matrix child: CPU-pinned like child_checkpoint (scenarios run
    in-process mini/distributed clusters; the parent must never lose the
    TPU relay to a resilience drill)."""
    _emit({"event": "start", "device": "cpu-chaos", "pid": os.getpid()})
    try:
        import jax
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)
    except Exception:
        pass
    _emit({"event": "result", "result": chaos_microbench()})


def run_chaos_microbench_child(timeout_s: float = 420.0) -> dict:
    """Chaos matrix in a CPU-pinned child with a FORCED 8-device virtual
    mesh, so the chip-loss-sharded scenario exercises a real reduced-mesh
    recovery, not a skip."""
    return _run_cpu_child('chaos', timeout_s, force_mesh=True)


def parent_main() -> None:
    deadline = time.monotonic() + BUDGET_S - 15
    best = {
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": 0.0,
        "unit": "tuples/s/chip",
        "vs_baseline": 0.0,
        "error": "no measurement completed",
    }
    best_rank = -1
    lock = threading.Lock()

    # host-only, a few seconds: the exchange microbench never touches the
    # chip, so it runs up front and rides every outcome of the TPU attempts
    try:
        dataplane = dataplane_microbench()
    except Exception as e:  # noqa: BLE001 — the headline must survive
        dataplane = {"error": repr(e)[:300]}
    _emit({"event": "dataplane_microbench", "result": dataplane})

    # checkpoint-overhead microbench: also host-only, but it builds window
    # operators — run it in a CPU-pinned child so the parent never imports
    # a jax backend out from under the TPU attempts
    checkpoint = run_checkpoint_microbench_child()
    _emit({"event": "checkpoint_microbench", "result": checkpoint})

    # elastic-autoscaler adaptation speed: host-only 2x load-step scenario
    # in its own CPU-pinned child, so the trajectory tracks how fast the
    # scheduler turns a saturation signal into a completed rescale
    autoscaler = run_autoscaler_scenario_child()
    _emit({"event": "autoscaler_scenario", "result": autoscaler})

    # API-vs-kernel gap: the full DataStream program through the fused
    # device path vs the legacy ChainRunner path, CPU-pinned child (same
    # backend both sides — the ratio is the refactor's, not the chip's)
    api_path = run_api_path_microbench_child()
    _emit({"event": "api_path_microbench", "result": api_path})

    # SQL front door: the YSB sliding count as SQL through the planner's
    # fused lowering vs the interpreted table path vs the hand-built
    # DataStream-fused yardstick — three-way parity + the reroute gate,
    # CPU-pinned child like the api-path scenario
    sql_path = run_sql_path_microbench_child()
    _emit({"event": "sql_path_microbench", "result": sql_path})

    # device-plane observability: compile/recompile tracking, roofline +
    # phase attribution, key skew, and the measured overhead of the
    # enabled plane — CPU-pinned child like the api-path scenario
    device_plane = run_device_plane_child()
    _emit({"event": "device_plane_microbench", "result": device_plane})

    # chaos scenario matrix: injected compound faults against both
    # execution paths, exactly-once parity vs undisturbed oracles —
    # resilience tracked per-PR like throughput (CPU-pinned child)
    chaos = run_chaos_microbench_child()
    _emit({"event": "chaos_microbench", "result": chaos})

    # multichip SPMD: the fused DataStream YSB program sharded over the
    # (virtual 8-device) mesh vs single-chip — scaling efficiency, zipf
    # skewed variant, per-device telemetry, reroute + parity gates
    multichip = run_multichip_child()
    _emit({"event": "multichip_microbench", "result": multichip})

    # million-key state plane: YSB at a key cardinality orders of
    # magnitude past the resident HBM capacity — bounded residency,
    # cold-tier churn, incremental checkpoint ratio, host-oracle parity
    millikey = run_millikey_child()
    _emit({"event": "millikey_microbench", "result": millikey})

    # shared partials (Factor Windows): the 1m/5m/1h correlated-window job
    # through ONE shared-partial program vs three independent fused runs,
    # single-chip + mesh legs, parity + reroute gates (CPU-pinned child)
    correlated = run_correlated_child()
    _emit({"event": "correlated_windows_microbench", "result": correlated})

    # skew matrix (PDSP-Bench grid): parallelism x workload x skew cells
    # with exact parity, plus the skew-adaptive leg (local-combine +
    # key-group rebalance) — skewed/uniform ratio and post-rebalance
    # meshLoadSkew tracked per PR like throughput
    skew_matrix = run_skew_matrix_child()
    _emit({"event": "skew_matrix_microbench", "result": skew_matrix})

    # streaming joins (NEXMark q3/q8): the device bucket-ring join vs the
    # host join oracle — exact parity on uniform AND zipf legs, the SQL
    # JOIN lowering's reroute gate, and the sharded-mesh leg
    join_bench = run_join_child()
    _emit({"event": "join_microbench", "result": join_bench})

    # latency x throughput frontier: the fused YSB job under open-loop
    # arrival pacing at 25/50/100% of measured peak — p50/p99/p999
    # emission latency (event-time close -> host-visible) per load point,
    # stall attribution, and the emission plane's on/off overhead
    latency_frontier = run_latency_frontier_child()
    _emit({"event": "latency_frontier_microbench",
           "result": latency_frontier})

    # history/doctor plane: ring non-emptiness over the REST read path,
    # the doctor's verdict on an undisturbed run, and the sampler's
    # measured overhead — the health block every artifact now carries
    health = run_health_child()
    _emit({"event": "health_microbench", "result": health})

    # static-analysis plane (ISSUE-20 acceptance): the full 16-rule lint
    # run rides every artifact next to health — a PR that regresses the
    # analyzer's coverage or leaves active violations shows up in the
    # trajectory, not just in CI
    lint_info = lint_summary()
    _emit({"event": "lint_summary", "result": lint_info})

    def consider(res, rank):
        nonlocal best, best_rank
        if res is None:
            return
        with lock:
            if rank > best_rank and res.get("value", 0) > 0:
                best, best_rank = res, rank

    printed = threading.Event()

    def finish():
        if not printed.is_set():
            printed.set()
            best["dataplane"] = dataplane
            best["checkpoint"] = checkpoint
            best["autoscaler"] = autoscaler
            best["api_path"] = api_path
            best["sql_path"] = sql_path
            # top-level continuity key for the trajectory table: the SQL
            # front door's fused throughput, tracked per PR like the
            # api-path number
            sql_tps = sql_path.get("sql_tuples_per_sec")
            if sql_tps:
                best["sql_path_tuples_per_sec"] = sql_tps
            best["chaos"] = chaos
            best["multichip"] = multichip
            best["state_tier"] = millikey
            best["correlated_windows"] = correlated
            # top-level continuity keys: the shared-partial throughput and
            # the sharing speedup, tracked per PR like the api-path number
            if correlated.get("shared_tuples_per_sec"):
                best["correlated_windows_tuples_per_sec"] = \
                    correlated["shared_tuples_per_sec"]
                best["correlated_sharing_speedup"] = \
                    correlated.get("speedup_vs_independent")
            if millikey.get("tuples_per_sec"):
                best["millikey_tuples_per_sec"] = \
                    millikey["tuples_per_sec"]
                best["millikey_incremental_ratio"] = \
                    millikey.get("incremental_ratio")
            best["skew_matrix"] = skew_matrix
            best["join"] = join_bench
            # emission-latency frontier (ISSUE-17 acceptance): the block
            # with per-load-point tail latencies rides every artifact,
            # and the 100%-load p99 is a first-class trajectory key
            best["latency_frontier"] = latency_frontier.get(
                "latency_frontier", latency_frontier)
            if latency_frontier.get("p99_emission_latency_ms") is not None:
                best["p99_emission_latency_ms"] = \
                    latency_frontier["p99_emission_latency_ms"]
            # health block (ISSUE-19 acceptance): the doctor's verdict and
            # the sampler's measured overhead ride every artifact
            best["health"] = health
            # lint block (ISSUE-20 acceptance): the exactly-once contract
            # analyzer's verdict on the tree, timed
            best["lint"] = lint_info
            # first-class join keys (ISSUE-16 acceptance): the q8 device
            # throughput and its ratio to the host join oracle — the
            # >= 20x bar is judged where this lands on real TPU hardware
            if join_bench.get("join_tuples_per_sec"):
                best["join_tuples_per_sec"] = \
                    join_bench["join_tuples_per_sec"]
                best["join_speedup_vs_host"] = \
                    join_bench.get("speedup_vs_host_join")
            # first-class skew keys (ISSUE-15 acceptance): the adaptive
            # zipf/uniform throughput ratio and the post-rebalance device
            # skew, tracked per PR next to the static value they improve
            if skew_matrix.get("skewed_uniform_ratio") is not None:
                best["skewed_uniform_ratio"] = \
                    skew_matrix["skewed_uniform_ratio"]
            if skew_matrix.get("post_rebalance_mesh_load_skew") is not None:
                best["post_rebalance_mesh_load_skew"] = \
                    skew_matrix["post_rebalance_mesh_load_skew"]
            # top-level continuity keys for the trajectory table
            if multichip.get("tuples_per_sec"):
                best["multichip_tuples_per_sec"] = \
                    multichip["tuples_per_sec"]
                best["multichip_scaling_efficiency"] = \
                    multichip.get("scaling_efficiency")
            # device_plane, NOT "device": the top-level "device" key is the
            # backend marker ("tpu"/"cpu-jit") the bench driver parses —
            # clobbering it would misclassify the whole artifact
            best["device_plane"] = device_plane
            # top-level continuity keys (the r02 shape): the API-path
            # number and its ratio to the headline kernel, tracked per PR
            tps = api_path.get("api_path_tuples_per_sec")
            if tps:
                best["api_path_tuples_per_sec"] = tps
                if best.get("value"):
                    best["api_vs_fused"] = round(tps / best["value"], 4)
            print(json.dumps(best), flush=True)
            for c in _CHILDREN:
                # never orphan a TPU child: it would keep the single-client
                # relay claimed and wedge the NEXT bench run's backend init
                c.kill()
            os._exit(0)

    wd = threading.Timer(max(deadline - time.monotonic(), 1), finish)
    wd.daemon = True
    wd.start()

    # safety net: XLA superscan on the CPU backend, smaller scale
    cpu_child = Child(
        "cpu-jit", {"JAX_PLATFORMS": "cpu"},
        ["cpu-jit", os.environ.get("BENCH_CPU_SPAN_STEPS", "24"),
         os.environ.get("BENCH_CPU_LOG2_BATCH", "16"),
         os.environ.get("BENCH_CPU_SPANS", "3")],
    )
    _CHILDREN.append(cpu_child)

    # the prize: the real chip, with a bounded init window and one retry
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    for attempt in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining < 120:
            break
        tpu_child = Child(
            "tpu", {},
            ["tpu", str(SPAN_STEPS), str(LOG2_BATCH), str(SPANS)],
        )
        _CHILDREN.append(tpu_child)
        init_deadline = time.monotonic() + min(INIT_S, remaining - 60)
        aborted = False
        while tpu_child.alive():
            if tpu_child.result is not None:
                break
            now = time.monotonic()
            if "backend_ready" not in tpu_child.events and now > init_deadline:
                aborted = True  # backend init wedged; relay may free on retry
                break
            if now > deadline - 20:
                aborted = True
                break
            time.sleep(1.0)
        if not tpu_child.alive():
            tpu_child.join_output()  # drain a just-printed final result line
        if tpu_child.result is not None:
            # the headline is banked; give the secondary-config pass a
            # bounded window to enrich it, then take whichever is newest
            enrich_by = min(deadline - 20, time.monotonic() + 300)
            while (tpu_child.alive()
                   and "result_final" not in tpu_child.events
                   and time.monotonic() < enrich_by):
                time.sleep(1.0)
            if not tpu_child.alive():
                tpu_child.join_output()
            final = tpu_child.events.get("result_final")
            consider(final["result"] if final else tpu_child.result, rank=3)
            tpu_child.kill()
            break
        consider(tpu_child.best_partial, rank=2)
        tpu_child.kill()
        if not aborted:
            time.sleep(2)

    # bank the safety net (running concurrently all along) — unless a TPU
    # measurement already outranks anything it could produce
    if best_rank < 2:
        cpu_deadline = min(deadline - 10, time.monotonic() + 300)
        while cpu_child.alive() and cpu_child.result is None and time.monotonic() < cpu_deadline:
            time.sleep(1.0)
        if not cpu_child.alive():
            cpu_child.join_output()
        consider(cpu_child.result, rank=1)
    cpu_child.kill()
    wd.cancel()
    finish()


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        label = sys.argv[2]
        T = int(sys.argv[3])
        spans = int(sys.argv[5])
        if label == "tpu":
            child_tpu(T, 1 << int(sys.argv[4]), spans)
        elif label == "checkpoint":
            child_checkpoint()
        elif label == "autoscaler":
            child_autoscaler()
        elif label == "api-path":
            child_api_path()
        elif label == "sql-path":
            child_sql_path()
        elif label == "device-plane":
            child_device_plane()
        elif label == "chaos":
            child_chaos()
        elif label == "multichip":
            child_multichip()
        elif label == "millikey":
            child_millikey()
        elif label == "skew-matrix":
            child_skew_matrix()
        elif label == "join":
            child_join()
        elif label == "correlated":
            child_correlated()
        elif label == "latency-frontier":
            child_latency_frontier()
        elif label == "health":
            child_health()
        else:
            child_cpu(T, 1 << int(sys.argv[4]), spans)
    else:
        parent_main()


if __name__ == "__main__":
    main()
