"""Benchmark: Yahoo-Streaming-Benchmark-style keyed sliding-window count.

Workload (BASELINE.json config 2): events keyed by campaign (dense int
keys), 10s windows sliding by 1s, event-time, watermark advanced per batch.

Device path (round 3): the fused PALLAS superscan — the whole T-step window
dispatch (MXU one-hot ingest + fire + purge) as ONE kernel with the
slice-ring state resident in VMEM (flink_tpu/ops/pallas_superscan.py).
The record stream is synthesized ON DEVICE with jax threefry PRNG from a
fixed integer schedule; the host regenerates bit-identical records (threefry
is backend-deterministic) for the single-core numpy baseline and the
window-by-window parity check. Only kilobyte-sized plan arrays cross the
host link per dispatch, so the measurement reflects the operator, not the
relay's ~50 MB/s host<->device tunnel (staging-bandwidth numbers are still
reported for transparency).

CPU baseline: an optimized single-core numpy implementation of the same
slice-decomposed algorithm (np.bincount segment sums) — a deliberately
*stronger* baseline than a per-record port of the reference's JVM
WindowOperator (see BASELINE.md; hot path WindowOperator.java:293).

Robustness: the TPU is reached over a single-client relay whose backend
init can wedge for minutes. This file is a *supervisor*: it runs the
measurement in child processes that stream incremental JSON progress lines
and always prints one final JSON line picked from, in order of preference:

  1. completed full-scale TPU run        (device: "tpu", parity checked)
  2. partial / small-scale TPU run       (device: "tpu", partial: true) —
     the tiny first measurement is parity-checked within ~1 min of
     backend_ready; later partials carry parity "deferred"
  3. completed CPU-backend run of the XLA superscan ("cpu-jit")
  4. numpy-baseline-only sentinel (only if even the CPU child dies)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

NUM_KEYS = 8192
WINDOW_MS = 10_000
SLIDE_MS = 1_000
OOO_MS = 500                  # out-of-orderness jitter bound
WM_DELAY_MS = 1_000
STEP_MS = 655                 # event-time span of one step (int schedule)
NSB = 4
SEED = 42

# main (TPU) workload scale
LOG2_BATCH = int(os.environ.get("BENCH_LOG2_BATCH", "20"))
SPAN_STEPS = int(os.environ.get("BENCH_SPAN_STEPS", "48"))   # steps per dispatch
SPANS = int(os.environ.get("BENCH_SPANS", "8"))
PIPE_DEPTH = int(os.environ.get("BENCH_PIPE_DEPTH", "3"))

# total wall budget and init window for the TPU attempt
BUDGET_S = int(os.environ.get("BENCH_WATCHDOG_S", "1200"))
INIT_S = int(os.environ.get("BENCH_INIT_S", "420"))

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")


def _emit(obj):
    print(json.dumps(obj), flush=True)


# ---------------------------------------------------------------------------
# deterministic stream schedule (integer math, identical on host and device)
#
#   step t, record b (0-based):
#     base  = t*STEP_MS + ((b+1)*STEP_MS)//B
#     ts    = max(base - jitter, 0),  jitter = bits >> 13 mod (OOO_MS+1)
#     key   = bits & (NUM_KEYS-1)     bits = threefry(fold_in(seed, t))
#   watermark after step t: (t+1)*STEP_MS - WM_DELAY_MS
# ---------------------------------------------------------------------------

def step_bounds(t: int, B: int):
    """Inclusive (smin, smax) slice bounds of step t's records."""
    smin = max((t * STEP_MS + STEP_MS // B - OOO_MS) // SLIDE_MS, 0)
    smax = ((t + 1) * STEP_MS) // SLIDE_MS
    return smin, smax


def host_step(t: int, B: int, bits_fn):
    """Regenerate step t's (keys, ts) on host, bit-identical to the device."""
    bits = bits_fn(t)
    keys = (bits & (NUM_KEYS - 1)).astype(np.int64)
    jitter = ((bits >> 13) % (OOO_MS + 1)).astype(np.int64)
    base = t * STEP_MS + ((np.arange(1, B + 1, dtype=np.int64) * STEP_MS) // B)
    ts = np.maximum(base - jitter, 0)
    return keys, ts


def make_bits_fn(B: int):
    """Host-side threefry bit stream (jitted on the cpu backend)."""
    import jax

    cpu = jax.devices("cpu")[0]
    base = jax.random.PRNGKey(SEED)

    @jax.jit
    def _bits(t):
        return jax.random.bits(jax.random.fold_in(base, t), (B,), "uint32")

    def bits_fn(t: int) -> np.ndarray:
        with jax.default_device(cpu):
            return np.asarray(_bits(t))

    return bits_fn


def make_device_gen(T: int, B: int):
    """Jitted on-device generator: span of T steps -> flat idx [T*B] int32."""
    import jax
    import jax.numpy as jnp

    base = jax.random.PRNGKey(SEED)
    bb = jnp.arange(1, B + 1, dtype=jnp.int32)

    @jax.jit
    def gen(t0, smin_abs):
        def one(tr):
            t = t0 + tr
            bits = jax.random.bits(jax.random.fold_in(base, t), (B,), "uint32")
            kid = (bits & jnp.uint32(NUM_KEYS - 1)).astype(jnp.int32)
            jit_ = ((bits >> jnp.uint32(13)) % jnp.uint32(OOO_MS + 1)).astype(jnp.int32)
            ts = jnp.maximum(t * STEP_MS + (bb * STEP_MS) // B - jit_, 0)
            srel = ts // SLIDE_MS - smin_abs[tr]
            return kid * NSB + srel

        return jax.vmap(one)(jnp.arange(T, dtype=jnp.int32)).reshape(-1)

    return gen


# ---------------------------------------------------------------------------
# CPU baseline: same slice-decomposed algorithm, single core, numpy
# ---------------------------------------------------------------------------

class NumpyWindower:
    """Incremental single-core reference; alg_seconds excludes generation."""

    S = 64

    def __init__(self):
        self.counts = np.zeros((NUM_KEYS, self.S), dtype=np.int64)
        self.fired_upto = None
        self.fired = {}
        self.alg_seconds = 0.0
        self.events = 0

    def step(self, keys, ts, wm):
        S, spw = self.S, WINDOW_MS // SLIDE_MS
        t0 = time.perf_counter()
        s_abs = ts // SLIDE_MS
        flat = keys * S + (s_abs % S)
        self.counts += np.bincount(flat, minlength=NUM_KEYS * S).reshape(NUM_KEYS, S)
        self.events += len(keys)
        j_hi = (wm + 1 - WINDOW_MS) // SLIDE_MS
        j_lo = self.fired_upto + 1 if self.fired_upto is not None else j_hi
        for j in range(j_lo, j_hi + 1):
            # windows with negative start exist for early records, matching
            # the reference's getWindowStartWithOffset arithmetic
            pos = np.arange(j, j + spw) % S
            self.fired[j] = self.counts[:, pos].sum(axis=1)
            self.counts[:, j % S] = 0
        if self.fired_upto is None or j_hi > self.fired_upto:
            self.fired_upto = j_hi
        self.alg_seconds += time.perf_counter() - t0


def _parity(cpu_fired, dev_fired, require_all: bool = True):
    """Window-by-window equality; with require_all=False (partial runs) only
    the windows the device actually fired are compared."""
    mismatches = 0
    checked = 0
    for j, crow in cpu_fired.items():
        drow = dev_fired.get(j)
        if drow is None:
            if require_all and crow.any():
                mismatches += 1
            continue
        checked += 1
        if not np.array_equal(crow.astype(np.int64), np.asarray(drow).astype(np.int64)):
            mismatches += 1
    ok = mismatches == 0 and (checked > 0 or not require_all)
    if require_all:
        nonempty = len([j for j, c in cpu_fired.items() if c.any()])
        ok = ok and len(dev_fired) >= nonempty
    return ok, checked


# ---------------------------------------------------------------------------
# TPU child
# ---------------------------------------------------------------------------

def _new_pipe(chunk: int, backend: str = "auto"):
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.runtime.fused_window_pipeline import FusedWindowPipeline

    return FusedWindowPipeline(
        SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS),
        "count",
        key_capacity=NUM_KEYS,
        num_slices=32,
        nsb=NSB,
        fires_per_step=4,
        out_rows=64,
        chunk=chunk,
        backend=backend,
    )


def run_tpu_stream(T: int, B: int, spans: int, depth: int, t0_step: int = 0,
                   warmup: bool = True):
    """Pipelined on-device-generated stream; yields progress per resolve."""
    import jax
    import jax.numpy as jnp

    pipe = _new_pipe(chunk=8192)
    gen = make_device_gen(T, B)

    if warmup:
        # compile gen + superscan + staging shapes on a throwaway pipe (the
        # compiled executables are shared via module-level caches), so the
        # timed region below measures steady-state streaming only
        wpipe = _new_pipe(chunk=8192)
        bounds = [step_bounds(r, B) for r in range(T)]
        wms = [(r + 1) * STEP_MS - WM_DELAY_MS for r in range(T)]
        plan, smin_abs = wpipe.plan_superbatch(bounds, wms)
        widx = gen(jnp.int32(0), jnp.asarray(smin_abs))
        wpipe.process_superbatch(
            None, None, staged=(widx, jnp.zeros((T, 1), jnp.float32), plan),
        )
        del wpipe, widx

    def enqueue(i):
        lo = t0_step + i * T
        bounds = [step_bounds(lo + r, B) for r in range(T)]
        wms = [(lo + r + 1) * STEP_MS - WM_DELAY_MS for r in range(T)]
        plan, smin_abs = pipe.plan_superbatch(bounds, wms)
        idx = gen(jnp.int32(lo), jnp.asarray(smin_abs))
        d = pipe.process_superbatch(
            None, None,
            staged=(idx, jnp.zeros((T, 1), jnp.float32), plan), defer=True,
        )
        return d, time.perf_counter()

    fired = {}
    span_lat = []
    t_first = time.perf_counter()
    inflight = []
    for i in range(min(depth, spans)):
        inflight.append(enqueue(i))
    next_i = len(inflight)
    resolved = 0
    while inflight:
        d, t_enq = inflight.pop(0)
        for window, counts, _f in d.resolve():
            fired[window.start // SLIDE_MS] = counts
        span_lat.append((time.perf_counter() - t_enq) * 1000.0)
        resolved += 1
        if next_i < spans:
            inflight.append(enqueue(next_i))
            next_i += 1
        yield_partial = resolved < spans
        elapsed = time.perf_counter() - t_first
        yield {
            "events": resolved * T * B,
            "elapsed": elapsed,
            "fired": fired,
            "span_latency_ms": span_lat,
            "final": not yield_partial,
        }


def child_tpu(T: int, B: int, spans: int) -> None:
    import jax

    _emit({"event": "start", "device": "tpu", "pid": os.getpid()})
    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass
    t0 = time.perf_counter()
    devs = jax.devices()
    _emit({"event": "backend_ready", "platform": devs[0].platform,
           "init_s": round(time.perf_counter() - t0, 1)})

    def result_json(tps, vsb, parity, checked, lat_ms, events, extra):
        res = {
            "metric": "ysb_sliding_count_tuples_per_sec",
            "value": round(tps, 1),
            "unit": "tuples/s/chip",
            "vs_baseline": round(vsb, 3),
            "parity": parity,
            "windows_checked": checked,
            "p99_flush_latency_ms": round(
                float(np.percentile(lat_ms, 99)), 1) if lat_ms else 0.0,
            "events": events,
            "num_keys": NUM_KEYS,
            "window_ms": WINDOW_MS,
            "slide_ms": SLIDE_MS,
            "device": "tpu",
            "kernel": "pallas_superscan",
            "data_source": "on_device_threefry_generator",
        }
        res.update(extra)
        return res

    # ---- quick numpy-baseline estimate (for partial-result ratios) ----
    bits_small = make_bits_fn(1 << 18)
    est = NumpyWindower()
    for t in range(8):
        keys, ts = host_step(t, 1 << 18, bits_small)
        est.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    cpu_tps_est = est.events / max(est.alg_seconds, 1e-9)
    _emit({"event": "cpu_baseline_estimate", "tuples_per_sec": round(cpu_tps_est)})

    # ---- tiny first measurement: parity-checked TPU number, banked fast ----
    tiny_T, tiny_B, tiny_spans = 8, 1 << 18, 2
    t0 = time.perf_counter()
    last = None
    for prog in run_tpu_stream(tiny_T, tiny_B, tiny_spans, depth=2):
        last = prog
    ref = NumpyWindower()
    for t in range(tiny_T * tiny_spans):
        keys, ts = host_step(t, tiny_B, bits_small)
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    ok, checked = _parity(ref.fired, last["fired"], require_all=True)
    tiny_tps = last["events"] / last["elapsed"]
    _emit({"event": "span_done", "phase": "tiny",
           "partial_result": result_json(
               tiny_tps, tiny_tps / cpu_tps_est, bool(ok), checked,
               last["span_latency_ms"], last["events"],
               {"partial": True, "scale": "small",
                "wall_from_backend_ready_s": round(time.perf_counter() - t0, 1)})})

    # ---- main run ----
    t_compile = time.perf_counter()
    last = None
    for prog in run_tpu_stream(T, B, spans, depth=PIPE_DEPTH):
        last = prog
        if not prog["final"]:
            tps = prog["events"] / prog["elapsed"]
            _emit({"event": "span_done", "phase": "main",
                   "partial_result": result_json(
                       tps, tps / cpu_tps_est, "deferred", 0,
                       prog["span_latency_ms"], prog["events"],
                       {"partial": True})})
    tps = last["events"] / last["elapsed"]
    _emit({"event": "main_done", "tuples_per_sec": round(tps),
           "elapsed_s": round(last["elapsed"], 3),
           "incl_warmup_s": round(time.perf_counter() - t_compile, 1)})

    # ---- untimed: full host replay for parity + the real baseline ----
    bits_fn = make_bits_fn(B)
    ref = NumpyWindower()
    for t in range(T * spans):
        keys, ts = host_step(t, B, bits_fn)
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
        if t % 64 == 63:
            _emit({"event": "replay_progress", "steps": t + 1})
    cpu_tps = ref.events / max(ref.alg_seconds, 1e-9)
    ok, checked = _parity(ref.fired, last["fired"], require_all=True)
    res = result_json(
        tps, tps / cpu_tps, bool(ok), checked,
        last["span_latency_ms"], last["events"],
        {"cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
         "span_steps": T, "batch": B, "spans": spans,
         "pipeline_depth": PIPE_DEPTH,
         "late_dropped": 0},
    )
    _emit({"event": "result", "result": res})


# ---------------------------------------------------------------------------
# CPU safety-net child: XLA superscan on the cpu backend, host-staged
# ---------------------------------------------------------------------------

def child_cpu(T: int, B: int, spans: int) -> None:
    _emit({"event": "start", "device": "cpu-jit", "pid": os.getpid()})
    import jax

    # The TPU relay's sitecustomize hook force-sets jax_platforms="axon,cpu";
    # the relay is single-client and a probe from a second process wedges.
    # Drop the factory so the safety-net child can never touch the chip.
    from jax._src import xla_bridge as _xb

    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
    _xb._topology_factories.pop("axon", None)

    devs = jax.devices()
    _emit({"event": "backend_ready", "platform": devs[0].platform})

    bits_fn = make_bits_fn(B)
    ref = NumpyWindower()
    steps_data = []
    for t in range(T * spans):
        keys, ts = host_step(t, B, bits_fn)
        steps_data.append((keys.astype(np.int32), None, ts))
        ref.step(keys, ts, (t + 1) * STEP_MS - WM_DELAY_MS)
    cpu_tps = ref.events / max(ref.alg_seconds, 1e-9)
    _emit({"event": "cpu_baseline", "tuples_per_sec": round(cpu_tps)})

    pipe = _new_pipe(chunk=4096, backend="xla")
    wms = [(t + 1) * STEP_MS - WM_DELAY_MS for t in range(T * spans)]
    # warmup compile on the first span shape
    warm = _new_pipe(chunk=4096, backend="xla")
    warm.process_superbatch(steps_data[:T], wms[:T])

    fired = {}
    lat = []
    t0 = time.perf_counter()
    prev = None
    n = 0
    for i in range(spans):
        lo, hi = i * T, (i + 1) * T
        t_enq = time.perf_counter()
        d = pipe.process_superbatch(steps_data[lo:hi], wms[lo:hi], defer=True)
        if prev is not None:
            pd, pt, pn = prev
            for w, c, _f in pd.resolve():
                fired[w.start // SLIDE_MS] = c
            lat.append((time.perf_counter() - pt) * 1000.0)
            n += pn
        prev = (d, t_enq, sum(len(b[2]) for b in steps_data[lo:hi]))
    pd, pt, pn = prev
    for w, c, _f in pd.resolve():
        fired[w.start // SLIDE_MS] = c
    lat.append((time.perf_counter() - pt) * 1000.0)
    n += pn
    elapsed = time.perf_counter() - t0
    ok, checked = _parity(ref.fired, fired, require_all=True)
    tps = n / elapsed
    _emit({"event": "result", "result": {
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": round(tps, 1),
        "unit": "tuples/s/chip",
        "vs_baseline": round(tps / cpu_tps, 3),
        "cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
        "parity": bool(ok),
        "windows_checked": checked,
        "p99_flush_latency_ms": round(float(np.percentile(lat, 99)), 1),
        "events": n,
        "device": "cpu-jit",
        "kernel": "xla_superscan",
    }})


# ---------------------------------------------------------------------------
# parent: supervisor
# ---------------------------------------------------------------------------

class Child:
    def __init__(self, name: str, env: dict, argv_extra: list):
        self.name = name
        self.best_partial = None
        self.result = None
        full_env = dict(os.environ)
        full_env.update(env)
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"] + argv_extra,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=full_env, text=True,
        )
        self.events = {}
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            ev = obj.get("event")
            if ev:
                self.events[ev] = obj
            if ev == "span_done" and obj.get("partial_result"):
                pr = obj["partial_result"]
                # prefer parity-checked partials; otherwise latest/biggest
                if (self.best_partial is None
                        or pr.get("parity") is True
                        or (self.best_partial.get("parity") is not True
                            and pr.get("events", 0) >= self.best_partial.get("events", 0))):
                    self.best_partial = pr
            if ev == "result":
                self.result = obj["result"]

    def alive(self):
        return self.proc.poll() is None

    def join_output(self, timeout: float = 5.0):
        self._t.join(timeout)

    def kill(self):
        try:
            self.proc.send_signal(signal.SIGKILL)
        except Exception:
            pass


_CHILDREN: list = []


def parent_main() -> None:
    deadline = time.monotonic() + BUDGET_S - 15
    best = {
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": 0.0,
        "unit": "tuples/s/chip",
        "vs_baseline": 0.0,
        "error": "no measurement completed",
    }
    best_rank = -1
    lock = threading.Lock()

    def consider(res, rank):
        nonlocal best, best_rank
        if res is None:
            return
        with lock:
            if rank > best_rank and res.get("value", 0) > 0:
                best, best_rank = res, rank

    printed = threading.Event()

    def finish():
        if not printed.is_set():
            printed.set()
            print(json.dumps(best), flush=True)
            for c in _CHILDREN:
                # never orphan a TPU child: it would keep the single-client
                # relay claimed and wedge the NEXT bench run's backend init
                c.kill()
            os._exit(0)

    wd = threading.Timer(max(deadline - time.monotonic(), 1), finish)
    wd.daemon = True
    wd.start()

    # safety net: XLA superscan on the CPU backend, smaller scale
    cpu_child = Child(
        "cpu-jit", {"JAX_PLATFORMS": "cpu"},
        ["cpu-jit", os.environ.get("BENCH_CPU_SPAN_STEPS", "24"),
         os.environ.get("BENCH_CPU_LOG2_BATCH", "16"),
         os.environ.get("BENCH_CPU_SPANS", "3")],
    )
    _CHILDREN.append(cpu_child)

    # the prize: the real chip, with a bounded init window and one retry
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    for attempt in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining < 120:
            break
        tpu_child = Child(
            "tpu", {},
            ["tpu", str(SPAN_STEPS), str(LOG2_BATCH), str(SPANS)],
        )
        _CHILDREN.append(tpu_child)
        init_deadline = time.monotonic() + min(INIT_S, remaining - 60)
        aborted = False
        while tpu_child.alive():
            if tpu_child.result is not None:
                break
            now = time.monotonic()
            if "backend_ready" not in tpu_child.events and now > init_deadline:
                aborted = True  # backend init wedged; relay may free on retry
                break
            if now > deadline - 20:
                aborted = True
                break
            time.sleep(1.0)
        if not tpu_child.alive():
            tpu_child.join_output()  # drain a just-printed final result line
        if tpu_child.result is not None:
            consider(tpu_child.result, rank=3)
            break
        consider(tpu_child.best_partial, rank=2)
        tpu_child.kill()
        if not aborted:
            time.sleep(2)

    # bank the safety net (running concurrently all along) — unless a TPU
    # measurement already outranks anything it could produce
    if best_rank < 2:
        cpu_deadline = min(deadline - 10, time.monotonic() + 300)
        while cpu_child.alive() and cpu_child.result is None and time.monotonic() < cpu_deadline:
            time.sleep(1.0)
        if not cpu_child.alive():
            cpu_child.join_output()
        consider(cpu_child.result, rank=1)
    cpu_child.kill()
    wd.cancel()
    finish()


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        label = sys.argv[2]
        T = int(sys.argv[3])
        spans = int(sys.argv[5])
        if label == "tpu":
            child_tpu(T, 1 << int(sys.argv[4]), spans)
        else:
            child_cpu(T, 1 << int(sys.argv[4]), spans)
    else:
        parent_main()


if __name__ == "__main__":
    main()
