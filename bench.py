"""Benchmark: Yahoo-Streaming-Benchmark-style keyed sliding-window count.

Workload (BASELINE.json config 2): events keyed by campaign (dense int
keys), 10s windows sliding by 1s, event-time, watermark advanced per batch.

Device path: FusedWindowPipeline — the whole stream compiled as lax.scan
superbatches (MXU matmul-histogram ingest + fused fire/purge, one dispatch
and one bulk async readback per superbatch). CPU baseline: an optimized
single-core numpy implementation of the same slice-decomposed algorithm
(np.bincount segment sums) — a deliberately *stronger* baseline than a
per-record port of the reference's JVM WindowOperator (see BASELINE.md).

Both paths consume identical pre-generated batches; the device path's
host->device staging runs before the timed region (its analogue of the
baseline reading RAM-resident arrays; this chip is reached over a ~130 MB/s
single-client relay, two orders of magnitude below a production PCIe/host
link — `h2d_staging_s` reports the cost transparently). Result parity is
asserted window-by-window before the JSON line is printed.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

NUM_KEYS = 8192
WINDOW_MS = 10_000
SLIDE_MS = 1_000
BATCH = 1 << int(os.environ.get("BENCH_LOG2_BATCH", "18"))
STEPS = int(os.environ.get("BENCH_STEPS", "192"))
SUPERBATCH = int(os.environ.get("BENCH_SUPERBATCH", "96"))   # steps per dispatch
EVENTS_PER_SEC_SIM = 400_000  # event-time density of the simulated stream
OOO_MS = 500                # out-of-orderness jitter
WM_DELAY_MS = 1_000


def _watchdog(seconds):
    """The axon TPU relay is single-client; if backend init wedges, emit a
    sentinel result instead of hanging the driver forever."""
    def fire():
        print(json.dumps({
            "metric": "ysb_sliding_count_tuples_per_sec",
            "value": 0.0,
            "unit": "tuples/s/chip",
            "vs_baseline": 0.0,
            "error": "device run timed out",
        }), flush=True)
        os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def make_batches(num_batches: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    batches, wms = [], []
    t_cursor = 0.0
    ms_per_batch = BATCH / EVENTS_PER_SEC_SIM * 1000.0
    for _ in range(num_batches):
        keys = rng.integers(0, NUM_KEYS, size=BATCH).astype(np.int32)
        base = t_cursor + np.sort(rng.random(BATCH)) * ms_per_batch
        jitter = rng.integers(-OOO_MS, 1, size=BATCH)
        ts = np.maximum(base.astype(np.int64) + jitter, 0)
        batches.append((keys, None, ts))
        wms.append(int(base[-1]) - WM_DELAY_MS)
        t_cursor += ms_per_batch
    return batches, wms


# ---------------------------------------------------------------------------
# CPU baseline: same slice-decomposed algorithm, single core, numpy
# ---------------------------------------------------------------------------

def run_cpu(batches, wms):
    S = 32
    spw = WINDOW_MS // SLIDE_MS
    counts = np.zeros((NUM_KEYS, S), dtype=np.int64)
    fired_upto = None
    fired = {}

    t0 = time.perf_counter()
    n = 0
    for (keys, _vals, ts), wm in zip(batches, wms):
        s_abs = ts // SLIDE_MS
        flat = keys.astype(np.int64) * S + (s_abs % S)
        counts += np.bincount(flat, minlength=NUM_KEYS * S).reshape(NUM_KEYS, S)
        n += len(keys)
        j_hi = (wm + 1 - WINDOW_MS) // SLIDE_MS
        j_lo = fired_upto + 1 if fired_upto is not None else j_hi
        for j in range(j_lo, j_hi + 1):
            pos = np.arange(j, j + spw) % S
            fired[j] = counts[:, pos].sum(axis=1)
            counts[:, j % S] = 0
        if fired_upto is None or j_hi > fired_upto:
            fired_upto = j_hi
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired


# ---------------------------------------------------------------------------
# device: fused superbatch pipeline
# ---------------------------------------------------------------------------

def run_device(batches, wms):
    import jax
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.runtime.fused_window_pipeline import FusedWindowPipeline

    def new_pipe():
        return FusedWindowPipeline(
            SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS),
            "count",
            key_capacity=NUM_KEYS,
            num_slices=32,
            nsb=int(os.environ.get("BENCH_NSB", "4")),
            fires_per_step=2,
            out_rows=256,
            chunk=int(os.environ.get("BENCH_CHUNK", "4096")),
        )

    spans = [(lo, min(lo + SUPERBATCH, len(batches))) for lo in range(0, len(batches), SUPERBATCH)]

    # warmup: compile the superscan on a throwaway pipeline (first span shape)
    warm = new_pipe()
    lo, hi = spans[0]
    warm.process_superbatch(batches[lo:hi], wms[lo:hi])

    pipe = new_pipe()
    t_stage0 = time.perf_counter()
    staged = []
    for lo, hi in spans:
        staged.append(pipe.stage_superbatch(batches[lo:hi], wms[lo:hi]))
    jax.block_until_ready([s[0] for s in staged])
    stage_s = time.perf_counter() - t_stage0
    # reset host cursors: staging already advanced them; re-staging is not
    # allowed, so hand the pre-staged plans back in execution order only.
    late_dropped = pipe.num_late_records_dropped

    t0 = time.perf_counter()
    n = 0
    deferred = []
    dispatch_t0 = []
    for (lo, hi), st in zip(spans, staged):
        dispatch_t0.append(time.perf_counter())
        d = pipe.process_superbatch(batches[lo:hi], wms[lo:hi], staged=st, defer=True)
        deferred.append(d)
        n += (hi - lo) * BATCH
    fired = {}
    flush_ms = []
    for t_disp, d in zip(dispatch_t0, deferred):
        for window, counts, _fields in d.resolve():
            fired[window.start // SLIDE_MS] = counts
        flush_ms.append((time.perf_counter() - t_disp) * 1000.0)
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired, stage_s, flush_ms, late_dropped


def main():
    wd = _watchdog(int(os.environ.get("BENCH_WATCHDOG_S", "1200")))
    batches, wms = make_batches(STEPS)

    cpu_tps, cpu_fired = run_cpu(batches, wms)
    dev_tps, dev_fired, stage_s, flush_ms, late = run_device(batches, wms)
    wd.cancel()

    # result parity, window by window (count>0 keys must match exactly)
    mismatches = 0
    for j, crow in cpu_fired.items():
        drow = dev_fired.get(j)
        if drow is None:
            if crow.any():
                mismatches += 1
            continue
        if not np.array_equal(crow.astype(np.int64), drow.astype(np.int64)):
            mismatches += 1
    parity = mismatches == 0 and len(dev_fired) >= len([j for j, c in cpu_fired.items() if c.any()])

    print(json.dumps({
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": round(dev_tps, 1),
        "unit": "tuples/s/chip",
        "vs_baseline": round(dev_tps / cpu_tps, 3),
        "cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
        "parity": bool(parity),
        "windows_checked": len(cpu_fired),
        "p99_flush_latency_ms": round(float(np.percentile(flush_ms, 99)), 1) if flush_ms else 0.0,
        "h2d_staging_s": round(stage_s, 2),
        "late_dropped": int(late),
        "events": STEPS * BATCH,
        "num_keys": NUM_KEYS,
        "window_ms": WINDOW_MS,
        "slide_ms": SLIDE_MS,
        "superbatch_steps": SUPERBATCH,
    }), flush=True)


if __name__ == "__main__":
    main()
