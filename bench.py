"""Benchmark: Yahoo-Streaming-Benchmark-style keyed sliding-window count.

Workload (BASELINE.json config 2): events keyed by campaign (dense int
keys), 10s windows sliding by 1s, event-time, watermark advanced per batch.

Device path: FusedWindowPipeline — the whole stream compiled as lax.scan
superbatches (MXU matmul-histogram ingest + fused fire/purge, one dispatch
and one bulk async readback per superbatch). CPU baseline: an optimized
single-core numpy implementation of the same slice-decomposed algorithm
(np.bincount segment sums) — a deliberately *stronger* baseline than a
per-record port of the reference's JVM WindowOperator (see BASELINE.md).

Robustness (round 2): the TPU behind this machine is reached over a
single-client relay whose backend init can wedge for minutes (round 1
recorded 0.0 because a bare `jax.devices()` hung past the watchdog). This
file is therefore a *supervisor*: it runs the measurement in child
processes that stream incremental JSON progress lines, and always prints
one final JSON result line picked from, in order of preference:

  1. completed TPU run            (device: "tpu")
  2. partial TPU run              (device: "tpu", partial: true) — the
     throughput over the superbatches that DID complete, parity checked
     over the windows fired so far
  3. completed CPU-backend run of the same fused pipeline
     (device: "cpu-jit") — a real measured number, never 0.0
  4. numpy-baseline-only sentinel (only if even the CPU child dies)

The CPU-jit safety-net child runs concurrently with the TPU child so the
fallback is already banked while the TPU attempt is still initializing.
TPU init gets a bounded window (BENCH_INIT_S) and one retry; the JAX
persistent compilation cache is enabled so retries and later rounds skip
recompiles. Result parity is asserted window-by-window in every mode.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

NUM_KEYS = 8192
WINDOW_MS = 10_000
SLIDE_MS = 1_000
EVENTS_PER_SEC_SIM = 400_000  # event-time density of the simulated stream
OOO_MS = 500                  # out-of-orderness jitter
WM_DELAY_MS = 1_000

# main (TPU) workload scale
BATCH = 1 << int(os.environ.get("BENCH_LOG2_BATCH", "18"))
STEPS = int(os.environ.get("BENCH_STEPS", "192"))
SUPERBATCH = int(os.environ.get("BENCH_SUPERBATCH", "48"))   # steps per dispatch

# total wall budget and init window for the TPU attempt
BUDGET_S = int(os.environ.get("BENCH_WATCHDOG_S", "1200"))
INIT_S = int(os.environ.get("BENCH_INIT_S", "420"))

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")


def make_batches(num_batches: int, batch: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    batches, wms = [], []
    t_cursor = 0.0
    # event-time span per batch is batch-size-invariant (~0.66 s) so the
    # same number of windows fires at every measurement scale
    ms_per_batch = (1 << 18) / EVENTS_PER_SEC_SIM * 1000.0
    for _ in range(num_batches):
        keys = rng.integers(0, NUM_KEYS, size=batch).astype(np.int32)
        base = t_cursor + np.sort(rng.random(batch)) * ms_per_batch
        jitter = rng.integers(-OOO_MS, 1, size=batch)
        ts = np.maximum(base.astype(np.int64) + jitter, 0)
        batches.append((keys, None, ts))
        wms.append(int(base[-1]) - WM_DELAY_MS)
        t_cursor += ms_per_batch
    return batches, wms


# ---------------------------------------------------------------------------
# CPU baseline: same slice-decomposed algorithm, single core, numpy
# ---------------------------------------------------------------------------

def run_cpu(batches, wms):
    S = 32
    spw = WINDOW_MS // SLIDE_MS
    counts = np.zeros((NUM_KEYS, S), dtype=np.int64)
    fired_upto = None
    fired = {}

    t0 = time.perf_counter()
    n = 0
    for (keys, _vals, ts), wm in zip(batches, wms):
        s_abs = ts // SLIDE_MS
        flat = keys.astype(np.int64) * S + (s_abs % S)
        counts += np.bincount(flat, minlength=NUM_KEYS * S).reshape(NUM_KEYS, S)
        n += len(keys)
        j_hi = (wm + 1 - WINDOW_MS) // SLIDE_MS
        j_lo = fired_upto + 1 if fired_upto is not None else j_hi
        for j in range(j_lo, j_hi + 1):
            pos = np.arange(j, j + spw) % S
            fired[j] = counts[:, pos].sum(axis=1)
            counts[:, j % S] = 0
        if fired_upto is None or j_hi > fired_upto:
            fired_upto = j_hi
    elapsed = time.perf_counter() - t0
    return n / elapsed, fired


def _parity(cpu_fired, dev_fired, require_all: bool = True):
    """Window-by-window equality; with require_all=False (partial runs) only
    the windows the device actually fired are compared."""
    mismatches = 0
    checked = 0
    for j, crow in cpu_fired.items():
        drow = dev_fired.get(j)
        if drow is None:
            if require_all and crow.any():
                mismatches += 1
            continue
        checked += 1
        if not np.array_equal(crow.astype(np.int64), np.asarray(drow).astype(np.int64)):
            mismatches += 1
    ok = mismatches == 0 and (checked > 0 or not require_all)
    if require_all:
        nonempty = len([j for j, c in cpu_fired.items() if c.any()])
        ok = ok and len(dev_fired) >= nonempty
    return ok, checked


# ---------------------------------------------------------------------------
# child: runs entirely in a subprocess, streams JSON lines on stdout
# ---------------------------------------------------------------------------

def _emit(obj):
    print(json.dumps(obj), flush=True)


def child_main(device_label: str, steps: int, batch: int, superbatch: int) -> None:
    _emit({"event": "start", "device": device_label, "pid": os.getpid()})
    batches, wms = make_batches(steps, batch)
    cpu_tps, cpu_fired = run_cpu(batches, wms)
    _emit({"event": "cpu_baseline", "tuples_per_sec": cpu_tps})

    import jax

    if device_label != "tpu":
        # The TPU relay's sitecustomize hook force-sets
        # jax_platforms="axon,cpu" at interpreter start, overriding
        # JAX_PLATFORMS from the environment; the relay is single-client
        # and a probe from a second process wedges. Drop the factory so
        # the safety-net child can never touch the chip.
        from jax._src import xla_bridge as _xb

        jax.config.update("jax_platforms", "cpu")
        _xb._backend_factories.pop("axon", None)
        _xb._topology_factories.pop("axon", None)

    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    t0 = time.perf_counter()
    devs = jax.devices()
    _emit({"event": "backend_ready", "platform": devs[0].platform,
           "init_s": round(time.perf_counter() - t0, 1)})

    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.runtime.fused_window_pipeline import FusedWindowPipeline

    def new_pipe():
        return FusedWindowPipeline(
            SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS),
            "count",
            key_capacity=NUM_KEYS,
            num_slices=32,
            nsb=int(os.environ.get("BENCH_NSB", "4")),
            fires_per_step=4,
            out_rows=256,
            chunk=int(os.environ.get("BENCH_CHUNK", "4096")),
        )

    spans = [(lo, min(lo + superbatch, len(batches)))
             for lo in range(0, len(batches), superbatch)]

    # warmup: compile the superscan on a throwaway pipeline (first span shape)
    t0 = time.perf_counter()
    warm = new_pipe()
    lo, hi = spans[0]
    warm.process_superbatch(batches[lo:hi], wms[lo:hi])
    _emit({"event": "warmup_done", "compile_s": round(time.perf_counter() - t0, 1)})

    pipe = new_pipe()
    t_stage0 = time.perf_counter()
    staged = [pipe.stage_superbatch(batches[lo:hi], wms[lo:hi]) for lo, hi in spans]
    jax.block_until_ready([s[0] for s in staged])
    stage_s = time.perf_counter() - t_stage0
    _emit({"event": "staged", "h2d_staging_s": round(stage_s, 2)})
    late_dropped = pipe.num_late_records_dropped

    def partial_result(n_events, elapsed, fired, flush_ms, complete):
        tps = n_events / max(elapsed, 1e-9)
        ok, checked = _parity(cpu_fired, fired, require_all=complete)
        res = {
            "metric": "ysb_sliding_count_tuples_per_sec",
            "value": round(tps, 1),
            "unit": "tuples/s/chip",
            "vs_baseline": round(tps / cpu_tps, 3),
            "cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
            "parity": bool(ok),
            "windows_checked": checked if not complete else len(cpu_fired),
            "p99_flush_latency_ms": round(float(np.percentile(flush_ms, 99)), 1) if flush_ms else 0.0,
            "h2d_staging_s": round(stage_s, 2),
            "late_dropped": int(late_dropped),
            "events": n_events,
            "num_keys": NUM_KEYS,
            "window_ms": WINDOW_MS,
            "slide_ms": SLIDE_MS,
            "superbatch_steps": superbatch,
            "device": device_label,
        }
        if not complete:
            res["partial"] = True
        return res

    # timed region: dispatch span i+1 before resolving span i so one
    # dispatch is always in flight; emit a bankable partial after each
    # resolve so a wedged relay still leaves a measured result on record.
    fired = {}
    flush_ms = []
    t_run0 = time.perf_counter()
    n_done = 0
    prev = None  # (deferred, t_dispatch, n_events_of_span)
    for i, ((lo, hi), st) in enumerate(zip(spans, staged)):
        t_disp = time.perf_counter()
        d = pipe.process_superbatch(batches[lo:hi], wms[lo:hi], staged=st, defer=True)
        if prev is not None:
            pd, pt, pn = prev
            for window, counts, _fields in pd.resolve():
                fired[window.start // SLIDE_MS] = counts
            flush_ms.append((time.perf_counter() - pt) * 1000.0)
            n_done += pn
            _emit({"event": "span_done", "spans_done": i,
                   "partial_result": partial_result(
                       n_done, time.perf_counter() - t_run0, fired, flush_ms, False)})
        prev = (d, t_disp, (hi - lo) * batch)
    pd, pt, pn = prev
    for window, counts, _fields in pd.resolve():
        fired[window.start // SLIDE_MS] = counts
    flush_ms.append((time.perf_counter() - pt) * 1000.0)
    n_done += pn
    elapsed = time.perf_counter() - t_run0

    res = partial_result(n_done, elapsed, fired, flush_ms, True)
    if os.environ.get("BENCH_API", "1") == "1":
        try:
            api_tps = run_api_path(batch, steps, superbatch)
            res["api_path_tuples_per_sec"] = round(api_tps, 1)
            res["api_vs_fused"] = round(api_tps / max(res["value"], 1e-9), 3)
        except Exception as e:  # the headline number must survive an API-path bug
            res["api_path_error"] = repr(e)[:200]
    _emit({"event": "result", "result": res})


def run_api_path(batch: int, steps: int, superbatch: int) -> float:
    """The same YSB workload driven through the public DataStream API —
    vectorized filter + projection chain, vectorized keyBy, fused window
    operator, columnar emission. This measures the FRAMEWORK (source loop,
    chain kernels, key dictionary, operator selection, emission), not just
    the superscan kernel; the api_vs_fused ratio in the result JSON is the
    framework overhead the round-1 verdict asked to close."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.config import Configuration, ExecutionOptions
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy

    rng = np.random.default_rng(11)
    n_total = steps * batch
    ms_per_batch = (1 << 18) / EVENTS_PER_SEC_SIM * 1000.0

    def gen(idx: np.ndarray) -> Batch:
        # YSB shape: (campaign key, event type); ~1/3 of events survive the
        # view filter. Columns are derived deterministically from idx.
        lo = int(idx[0])
        r = np.random.default_rng(lo)
        keys = r.integers(0, NUM_KEYS, size=len(idx), dtype=np.int64)
        etype = r.integers(0, 3, size=len(idx), dtype=np.int64)
        base = lo / batch * ms_per_batch + np.sort(r.random(len(idx))) * (
            ms_per_batch * len(idx) / batch
        )
        ts = np.maximum(base.astype(np.int64) - r.integers(0, OOO_MS, len(idx)), 0)
        return Batch(np.stack([keys, etype], axis=1), ts)

    conf = Configuration()
    conf.set(ExecutionOptions.BATCH_SIZE, batch)
    conf.set(ExecutionOptions.KEY_CAPACITY, NUM_KEYS)
    conf.set(ExecutionOptions.SUPERBATCH_STEPS, superbatch)
    conf.set(ExecutionOptions.COLUMNAR_OUTPUT, True)
    env = StreamExecutionEnvironment.get_execution_environment(conf)
    sink = (
        env.from_source(
            DataGeneratorSource(gen, count=n_total, num_splits=1),
            watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(WM_DELAY_MS),
        )
        .filter(lambda col: col[:, 1] == 0, vectorized=True)
        .key_by(lambda col: col[:, 0], vectorized=True)
        .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
        .count()
        .collect()
    )
    t0 = time.perf_counter()
    result = env.execute("ysb-api")
    elapsed = time.perf_counter() - t0
    _emit({"event": "api_done", "windows_emitted": len(sink.results),
           "records": result.records_in, "elapsed_s": round(elapsed, 2)})
    return result.records_in / elapsed


# ---------------------------------------------------------------------------
# parent: supervisor
# ---------------------------------------------------------------------------

class Child:
    def __init__(self, name: str, env: dict, argv_extra: list):
        self.name = name
        self.lines: list = []
        self.best_partial = None
        self.result = None
        full_env = dict(os.environ)
        full_env.update(env)
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"] + argv_extra,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=full_env, text=True,
        )
        self.events = {}
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        for line in self.proc.stdout:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            self.lines.append(obj)
            ev = obj.get("event")
            if ev:
                self.events[ev] = obj
            if ev == "span_done" and obj.get("partial_result"):
                self.best_partial = obj["partial_result"]
            if ev == "result":
                self.result = obj["result"]

    def alive(self):
        return self.proc.poll() is None

    def join_output(self, timeout: float = 5.0):
        """Wait for the stdout pump to finish parsing (call after the child
        exited, so a just-printed final result is not missed)."""
        self._t.join(timeout)

    def kill(self):
        try:
            self.proc.send_signal(signal.SIGKILL)
        except Exception:
            pass


_CHILDREN: list = []


def parent_main() -> None:
    deadline = time.monotonic() + BUDGET_S - 15
    best = {
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": 0.0,
        "unit": "tuples/s/chip",
        "vs_baseline": 0.0,
        "error": "no measurement completed",
    }
    best_rank = -1
    lock = threading.Lock()

    def consider(res, rank):
        nonlocal best, best_rank
        if res is None:
            return
        with lock:
            if rank > best_rank and res.get("value", 0) > 0:
                best, best_rank = res, rank

    printed = threading.Event()

    def finish():
        if not printed.is_set():
            printed.set()
            print(json.dumps(best), flush=True)
            for c in _CHILDREN:
                # never orphan a TPU child: it would keep the single-client
                # relay claimed and wedge the NEXT bench run's backend init
                c.kill()
            os._exit(0)

    wd = threading.Timer(max(deadline - time.monotonic(), 1), finish)
    wd.daemon = True
    wd.start()

    # safety net: same fused pipeline on the CPU backend, smaller scale
    cpu_child = Child(
        "cpu-jit",
        {"JAX_PLATFORMS": "cpu"},
        ["cpu-jit", os.environ.get("BENCH_CPU_STEPS", "48"),
         os.environ.get("BENCH_CPU_LOG2_BATCH", "16"), "24"],
    )
    _CHILDREN.append(cpu_child)

    # the prize: the real chip, with a bounded init window and one retry
    attempts = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
    tpu_res = None
    for attempt in range(attempts):
        remaining = deadline - time.monotonic()
        if remaining < 120:
            break
        tpu_child = Child(
            "tpu", {},
            ["tpu", str(STEPS), str(int(np.log2(BATCH))), str(SUPERBATCH)],
        )
        _CHILDREN.append(tpu_child)
        init_deadline = time.monotonic() + min(INIT_S, remaining - 60)
        aborted = False
        while tpu_child.alive():
            if tpu_child.result is not None:
                break
            now = time.monotonic()
            if "backend_ready" not in tpu_child.events and now > init_deadline:
                aborted = True  # backend init wedged; relay may free up on retry
                break
            if now > deadline - 20:
                aborted = True
                break
            time.sleep(1.0)
        if not tpu_child.alive():
            tpu_child.join_output()  # drain a just-printed final result line
        if tpu_child.result is not None:
            tpu_res = tpu_child.result
            consider(tpu_res, rank=3)
            break
        consider(tpu_child.best_partial, rank=2)
        tpu_child.kill()
        if not aborted:  # child crashed on its own; look at next attempt
            time.sleep(2)

    # bank the safety net (it has been running concurrently all along) —
    # unless a TPU measurement already outranks anything it could produce
    if best_rank < 2:
        cpu_deadline = min(deadline - 10, time.monotonic() + 300)
        while cpu_child.alive() and cpu_child.result is None and time.monotonic() < cpu_deadline:
            time.sleep(1.0)
        if not cpu_child.alive():
            cpu_child.join_output()
        consider(cpu_child.result, rank=1)
    cpu_child.kill()
    wd.cancel()
    finish()


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        label = sys.argv[2]
        steps = int(sys.argv[3])
        batch = 1 << int(sys.argv[4])
        superbatch = int(sys.argv[5])
        child_main(label, steps, batch, superbatch)
    else:
        parent_main()


if __name__ == "__main__":
    main()
