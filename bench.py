"""Benchmark: Yahoo-Streaming-Benchmark-style keyed sliding-window count.

Workload (BASELINE.json config 2): events keyed by campaign (dense int
keys), 10s windows sliding by 1s, event-time with bounded out-of-orderness,
watermark advanced per step batch. The device path runs the columnar
TpuWindowOperator (scatter-combine ingest + segment-reduce fire,
flink_tpu/runtime/tpu_window_operator.py); the baseline is an optimized
single-core CPU implementation of the same slice-decomposed algorithm
(np.bincount segment sums — a *stronger* baseline than the per-record
oracle, standing in for the reference's JVM WindowOperator which cannot be
built in this offline image; see BASELINE.md protocol note).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

# Watchdog: the axon TPU relay is single-client; if backend init wedges,
# emit a sentinel result instead of hanging the driver forever.
def _watchdog(seconds=900):
    def fire():
        print(json.dumps({
            "metric": "ysb_sliding_count_tuples_per_sec",
            "value": 0.0,
            "unit": "tuples/s/chip",
            "vs_baseline": 0.0,
            "error": "device backend init timed out",
        }), flush=True)
        os._exit(0)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


NUM_KEYS = 8192
WINDOW_MS = 10_000
SLIDE_MS = 1_000
BATCH = 1 << 17            # 131072 events per step
EVENTS_PER_SEC_SIM = 400_000  # simulated event-time density: events/sec of stream time
OOO_MS = 500               # out-of-orderness jitter
WM_DELAY_MS = 1_000


def make_batches(num_batches: int, seed: int = 7):
    """Pre-generate the whole workload (host memory) so generation cost is
    excluded from both measurements equally."""
    rng = np.random.default_rng(seed)
    batches = []
    t_cursor = 0.0
    ms_per_batch = BATCH / EVENTS_PER_SEC_SIM * 1000.0
    for _ in range(num_batches):
        keys = rng.integers(0, NUM_KEYS, size=BATCH).astype(np.int64)
        base = t_cursor + np.sort(rng.random(BATCH)) * ms_per_batch
        jitter = rng.integers(-OOO_MS, 1, size=BATCH)
        ts = np.maximum(base.astype(np.int64) + jitter, 0)
        vals = np.ones(BATCH, dtype=np.float32)
        wm = int(base[-1]) - WM_DELAY_MS
        batches.append((keys, vals, ts, wm))
        t_cursor += ms_per_batch
    return batches


# ---------------------------------------------------------------------------
# device run
# ---------------------------------------------------------------------------

def run_device(batches, warmup: int = 2):
    from flink_tpu.api.windowing.assigners import SlidingEventTimeWindows
    from flink_tpu.runtime.tpu_window_operator import TpuWindowOperator
    import jax

    def new_op():
        return TpuWindowOperator(
            SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS),
            "count",
            key_capacity=NUM_KEYS,
            num_slices=32,
            dense_int_keys=True,
            columnar_output=True,
            batch_pad=BATCH,
        )

    # warmup/compile on a throwaway operator
    op = new_op()
    for keys, vals, ts, wm in batches[:warmup]:
        op.process_batch(keys, vals, ts)
        op.process_watermark(wm)
    jax.block_until_ready(op.state.count)

    op = new_op()
    fire_times = []
    orig_emit = op._emit_window

    def timed_emit(j, *, touch_mask):
        t0 = time.perf_counter()
        orig_emit(j, touch_mask=touch_mask)
        fire_times.append(time.perf_counter() - t0)

    op._emit_window = timed_emit

    t0 = time.perf_counter()
    n = 0
    for keys, vals, ts, wm in batches:
        op.process_batch(keys, vals, ts)
        op.process_watermark(wm)
        n += len(keys)
    jax.block_until_ready(op.state.count)
    elapsed = time.perf_counter() - t0
    p99_fire_ms = (
        float(np.percentile(np.asarray(fire_times) * 1000, 99)) if fire_times else 0.0
    )
    total_emitted = sum(len(np.flatnonzero(m)) if hasattr(m, "any") else 0
                        for _, _, (m, _r), _ in op.output) if op.output else 0
    return n / elapsed, p99_fire_ms, total_emitted


# ---------------------------------------------------------------------------
# CPU baseline: same slice-decomposed algorithm, single core, numpy
# ---------------------------------------------------------------------------

def run_cpu(batches):
    S = 32
    spw = WINDOW_MS // SLIDE_MS
    counts = np.zeros((NUM_KEYS, S), dtype=np.int64)
    fired_upto = None
    emitted = 0

    t0 = time.perf_counter()
    n = 0
    for keys, vals, ts, wm in batches:
        s_abs = ts // SLIDE_MS
        flat = keys * S + (s_abs % S)
        counts += np.bincount(flat, minlength=NUM_KEYS * S).reshape(NUM_KEYS, S)
        n += len(keys)
        # fire windows whose end-1 <= wm
        j_hi = (wm + 1 - WINDOW_MS) // SLIDE_MS
        j_lo = fired_upto + 1 if fired_upto is not None else j_hi - 1
        for j in range(j_lo, j_hi + 1):
            pos = np.arange(j, j + spw) % S
            win = counts[:, pos].sum(axis=1)
            emitted += int((win > 0).sum())
            # purge the slice leaving the live range (ring reuse)
            counts[:, j % S] = 0
        fired_upto = max(j_hi, fired_upto) if fired_upto is not None else j_hi
    elapsed = time.perf_counter() - t0
    return n / elapsed, emitted


def main():
    num_batches = int(os.environ.get("BENCH_BATCHES", "24"))
    wd = _watchdog(int(os.environ.get("BENCH_WATCHDOG_S", "900")))
    batches = make_batches(num_batches)

    cpu_tps, _ = run_cpu(batches)
    dev_tps, p99_fire_ms, _ = run_device(batches)
    wd.cancel()

    print(json.dumps({
        "metric": "ysb_sliding_count_tuples_per_sec",
        "value": round(dev_tps, 1),
        "unit": "tuples/s/chip",
        "vs_baseline": round(dev_tps / cpu_tps, 3),
        "cpu_baseline_tuples_per_sec": round(cpu_tps, 1),
        "p99_window_fire_ms": round(p99_fire_ms, 3),
        "events": num_batches * BATCH,
        "num_keys": NUM_KEYS,
        "window_ms": WINDOW_MS,
        "slide_ms": SLIDE_MS,
    }), flush=True)


if __name__ == "__main__":
    main()
