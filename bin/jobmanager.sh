#!/usr/bin/env bash
# Start the JobManager (Dispatcher + JobMaster + blob server) — the analogue
# of the reference's bin/jobmanager.sh.
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m flink_tpu.runtime.cluster jobmanager "$@"
