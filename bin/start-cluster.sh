#!/usr/bin/env bash
# Local cluster: one JobManager + N TaskManagers (default 2) on this host —
# the analogue of the reference's bin/start-cluster.sh.
set -euo pipefail
cd "$(dirname "$0")/.."
N_TM="${1:-2}"
PORT="${FLINK_TPU_JM_PORT:-6123}"
LOGDIR="${FLINK_TPU_LOG_DIR:-/tmp/flink_tpu_logs}"
mkdir -p "$LOGDIR"
python -m flink_tpu.runtime.cluster jobmanager --port "$PORT" \
  --checkpoint-dir "${FLINK_TPU_CHECKPOINT_DIR:-/tmp/flink_tpu_checkpoints}" \
  --checkpoint-interval "${FLINK_TPU_CHECKPOINT_INTERVAL:-10}" \
  > "$LOGDIR/jobmanager.log" 2>&1 &
echo $! > "$LOGDIR/jobmanager.pid"
sleep 1
for i in $(seq 1 "$N_TM"); do
  python -m flink_tpu.runtime.cluster taskmanager --jobmanager "127.0.0.1:$PORT" \
    > "$LOGDIR/taskmanager-$i.log" 2>&1 &
  echo $! >> "$LOGDIR/taskmanagers.pid"
done
echo "cluster up: jobmanager 127.0.0.1:$PORT, $N_TM taskmanagers (logs in $LOGDIR)"
