#!/usr/bin/env bash
set -euo pipefail
LOGDIR="${FLINK_TPU_LOG_DIR:-/tmp/flink_tpu_logs}"
for f in "$LOGDIR"/taskmanagers.pid "$LOGDIR"/jobmanager.pid; do
  [ -f "$f" ] && while read -r pid; do kill "$pid" 2>/dev/null || true; done < "$f" && rm -f "$f"
done
echo "cluster stopped"
