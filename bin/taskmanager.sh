#!/usr/bin/env bash
# Start a TaskManager worker and register it with the JobManager.
# Usage: taskmanager.sh --jobmanager host:6123 [--slots N]
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m flink_tpu.runtime.cluster taskmanager "$@"
