"""flink_tpu — a TPU-native stream-processing framework.

A brand-new framework with the capabilities of Apache Flink (event-time
streaming, keyed partitioned state over a fixed key-group space,
tumbling/sliding/session/global windows with triggers and allowed lateness,
exactly-once fault tolerance via consistent snapshots, pluggable
sources/sinks), re-architected for TPUs:

- The keyed windowed-aggregation hot path (``key_by().window().aggregate()``)
  runs as batched XLA segment-reduces over HBM-resident columnar per-key
  state instead of per-record hash-map mutation
  (reference: flink-runtime .../windowing/WindowOperator.java:293).
- keyBy shuffles become device all-to-alls inside ``shard_map`` programs over
  a ``jax.sharding.Mesh``; global-window merges are ``psum`` collectives
  (reference: Netty credit-based shuffle, io/network/netty/).
- Execution is a host-driven stepped dataflow: records are ingested and
  batched on host, each step is one compiled XLA program
  (reference: mailbox-driven StreamTask, streaming/runtime/tasks/StreamTask.java:205).

Layering mirrors the reference's semantic contracts (SURVEY.md §1) without
transplanting its thread/actor/Netty architecture.
"""

__version__ = "0.1.0"

from flink_tpu.config import ConfigOption, Configuration
from flink_tpu.core.time import TimeWindow, window_start_with_offset, MAX_WATERMARK, MIN_TIMESTAMP
from flink_tpu.core.keygroups import (
    KeyGroupRange,
    assign_to_key_group,
    compute_key_group_for_key_hash,
    key_group_range_for_operator,
    operator_index_for_key_group,
)

__all__ = [
    "ConfigOption",
    "Configuration",
    "TimeWindow",
    "window_start_with_offset",
    "MAX_WATERMARK",
    "MIN_TIMESTAMP",
    "KeyGroupRange",
    "assign_to_key_group",
    "compute_key_group_for_key_hash",
    "key_group_range_for_operator",
    "operator_index_for_key_group",
]
