"""User-facing DataStream-style API."""
