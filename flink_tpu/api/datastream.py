"""DataStream-style fluent API.

Capability parity with the reference's DataStream V1 surface
(flink-runtime .../streaming/api/datastream/DataStream.java:111,
KeyedStream.java:94 window() :705, WindowedStream.java reduce :181 /
aggregate :310, StreamExecutionEnvironment.java:1823 execute()): fluent
map/flatMap/filter/keyBy/window/aggregate/sink chains recording a
Transformation DAG, executed by the stepped local executor (and, sharded,
by the parallel executor).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

from flink_tpu.api.functions import (
    AggregateFunction,
    as_key_selector,
)
from flink_tpu.api.windowing.assigners import WindowAssigner
from flink_tpu.api.windowing.triggers import Trigger
from flink_tpu.api.windowing.evictors import Evictor
from flink_tpu.config import Configuration, PipelineOptions
from flink_tpu.core.watermarks import WatermarkStrategy
from flink_tpu.graph.transformation import Transformation, plan
from flink_tpu.connectors.source import CollectionSource, Source
from flink_tpu.connectors.sink import CollectSink, Sink


class StreamExecutionEnvironment:
    """Entry point (StreamExecutionEnvironment.java). Holds config and the
    set of sink transformations; execute() plans and runs."""

    def __init__(self, config: Optional[Configuration] = None):
        self.config = config or Configuration()
        self._sinks: List[Transformation] = []
        # non-sink plan roots (iteration tails): reachable only through
        # close_with, so they must be planned explicitly
        self._roots: List[Transformation] = []

    @staticmethod
    def get_execution_environment(config: Optional[Configuration] = None) -> "StreamExecutionEnvironment":
        return StreamExecutionEnvironment(config)

    # -- config -----------------------------------------------------------
    def set_parallelism(self, parallelism: int) -> "StreamExecutionEnvironment":
        self.config.set(PipelineOptions.PARALLELISM, parallelism)
        return self

    def set_max_parallelism(self, max_parallelism: int) -> "StreamExecutionEnvironment":
        self.config.set(PipelineOptions.MAX_PARALLELISM, max_parallelism)
        return self

    @property
    def parallelism(self) -> int:
        return self.config.get(PipelineOptions.PARALLELISM)

    @property
    def max_parallelism(self) -> int:
        return self.config.get(PipelineOptions.MAX_PARALLELISM)

    # -- sources ----------------------------------------------------------
    def from_source(
        self,
        source: Source,
        watermark_strategy: Optional[WatermarkStrategy] = None,
        name: str = "source",
    ) -> "DataStream":
        t = Transformation(
            "source", name, [], {"source": source, "watermark_strategy": watermark_strategy}
        )
        return DataStream(self, t)

    def from_collection(
        self,
        items: Sequence,
        timestamp_fn: Optional[Callable] = None,
        watermark_strategy: Optional[WatermarkStrategy] = None,
    ) -> "DataStream":
        return self.from_source(
            CollectionSource(items, timestamp_fn), watermark_strategy, name="collection"
        )

    # -- execution --------------------------------------------------------
    def execute(self, job_name: Optional[str] = None):
        from flink_tpu.runtime.executor import LocalPipelineExecutor

        if not self._sinks:
            raise RuntimeError("No sinks defined; nothing to execute")
        graph = plan(self._sinks + self._roots)
        executor = LocalPipelineExecutor(self.config)
        return executor.execute(graph, job_name or self.config.get(PipelineOptions.NAME))

    def execute_async(self, job_name: Optional[str] = None):
        """Submit to the in-process mini-cluster (Dispatcher analogue)."""
        from flink_tpu.runtime.minicluster import MiniCluster

        if len(self._sinks) != 1:
            raise RuntimeError("exactly one sink required")
        graph = plan([self._sinks[0]] + self._roots)
        return MiniCluster.get_shared().submit(graph, self.config, job_name)


class DataStream:
    def __init__(self, env: StreamExecutionEnvironment, transform: Transformation):
        self.env = env
        self.transform = transform

    def _derive(self, kind: str, name: str, config: dict) -> "DataStream":
        return DataStream(self.env, Transformation(kind, name, [self.transform], config))

    # -- record-local ops --------------------------------------------------
    def map(self, fn: Callable, name: str = "map", vectorized: bool = False,
            traceable: bool = False) -> "DataStream":
        """Per-record transform. With vectorized=True, fn receives the whole
        value column (numpy array) and must return an equal-length column —
        the chain then executes as array ops instead of a Python loop (the
        TPU-native form of operator chaining: the reference fuses chained
        operators into direct calls, StreamingJobGraphGenerator.java:1730;
        here a chain fuses into columnar kernels).

        traceable=True (implies vectorized) additionally declares fn to be a
        pure jax-traceable column function (array ufunc ops only, no data-
        dependent shapes or host calls): the chain then qualifies for
        whole-graph fusion, compiling together with a downstream keyed
        window aggregate into ONE jitted device program (docs/fusion.md)."""
        fn = fn.map if hasattr(fn, "map") else fn
        return self._derive("map", name, {
            "fn": fn, "vectorized": vectorized or traceable,
            "traceable": traceable,
        })

    def map_batch(self, fn: Callable, name: str = "map_batch") -> "DataStream":
        """1:1 transform over the whole step batch at once (list -> list of
        equal length) — the amortization point for device inference."""
        t = Transformation("map_batch", name, [self.transform], {"fn": fn})
        return DataStream(self.env, t)

    def map_with_timestamp(self, fn: Callable, name: str = "map_ts",
                           vectorized: bool = False,
                           traceable: bool = False) -> "DataStream":
        """map over (value, event_timestamp_ms) pairs. Vectorized form:
        fn(values_column, timestamps_column) -> values_column. traceable=True
        declares a jax-traceable column fn eligible for whole-graph fusion
        (see map())."""
        return self._derive("map_ts", name, {
            "fn": fn, "vectorized": vectorized or traceable,
            "traceable": traceable,
        })

    def flat_map(self, fn: Callable, name: str = "flat_map",
                 vectorized: bool = False) -> "DataStream":
        """1:N transform. Vectorized form: fn(values_column) returns
        (out_values, source_index) where source_index[i] is the input row
        out_values[i] came from (used to propagate timestamps)."""
        fn = fn.flat_map if hasattr(fn, "flat_map") else fn
        return self._derive("flat_map", name, {"fn": fn, "vectorized": vectorized})

    def filter(self, fn: Callable, name: str = "filter",
               vectorized: bool = False, traceable: bool = False) -> "DataStream":
        """Predicate filter. Vectorized form: fn(values_column) returns a
        boolean mask over the column. traceable=True declares a
        jax-traceable mask fn eligible for whole-graph fusion (see map())."""
        fn = fn.filter if hasattr(fn, "filter") else fn
        return self._derive("filter", name, {
            "fn": fn, "vectorized": vectorized or traceable,
            "traceable": traceable,
        })

    def async_map(
        self,
        fn: Callable,
        *,
        capacity: int = 100,
        timeout_ms: Optional[float] = None,
        ordered: bool = True,
        retry=None,
        name: str = "async_map",
    ) -> "DataStream":
        """Async I/O with bounded concurrency (AsyncDataStream.orderedWait /
        unorderedWait semantics; AsyncWaitOperator analogue)."""
        from flink_tpu.runtime.async_io import NO_RETRY

        return self._derive(
            "async_map",
            name,
            {
                "fn": fn,
                "capacity": capacity,
                "timeout_ms": timeout_ms,
                "ordered": ordered,
                "retry": retry or NO_RETRY,
            },
        )

    def get_side_output(self, tag) -> "DataStream":
        """The stream of this operator's side output for `tag`
        (SingleOutputStreamOperator.getSideOutput / OutputTag). Works on the
        result of process()-style operators that call ctx.output(tag, v) and
        on windowed streams with side_output_late_data()."""
        from flink_tpu.api.functions import OutputTag

        if not isinstance(tag, OutputTag):
            tag = OutputTag(str(tag))
        t = Transformation("side_output", f"side:{tag.tag_id}",
                           [self.transform], {"tag": tag})
        return DataStream(self.env, t)

    # -- multi-input topologies (DataStream.java:111) ----------------------
    def union(self, *others: "DataStream") -> "DataStream":
        """Merge streams of the same type; watermarks min-combine across the
        inputs (DataStream.union / UnionTransformation)."""
        if not others:
            return self
        t = Transformation(
            "union", "union",
            [self.transform] + [o.transform for o in others], {},
        )
        return DataStream(self.env, t)

    def connect(self, other: "DataStream") -> "ConnectedStreams":
        """Pair two streams for co-processing with shared state
        (DataStream.connect / ConnectedStreams)."""
        return ConnectedStreams(self.env, self, other)

    def join(self, other: "DataStream") -> "JoinBuilder":
        """Keyed windowed join (JoinedStreams.java:101):
        a.join(b).where(ks_a).equal_to(ks_b).window(assigner).apply(fn)."""
        return JoinBuilder(self.env, self, other, cogroup=False)

    def co_group(self, other: "DataStream") -> "JoinBuilder":
        """Keyed windowed coGroup (CoGroupedStreams.java): apply(fn) receives
        (left_elements, right_elements) once per key x window."""
        return JoinBuilder(self.env, self, other, cogroup=True)

    # -- partitioning ------------------------------------------------------
    def _partition_hint(self, kind: str) -> "DataStream":
        """Explicit repartitioning (DataStream.rebalance/broadcast/...).

        Locally these are pass-through views (one parallel instance); the
        distributed scheduler reads the hint to choose the exchange pattern,
        and key_by remains the only data-moving partitioner on the stepped
        executor (records route by key group)."""
        return DataStream(
            self.env, Transformation(kind, kind, [self.transform], {})
        )

    def rebalance(self) -> "DataStream":
        """Round-robin redistribution (RebalancePartitioner)."""
        return self._partition_hint("rebalance")

    def rescale(self) -> "DataStream":
        """Local-group round-robin (RescalePartitioner)."""
        return self._partition_hint("rescale")

    def shuffle(self) -> "DataStream":
        """Uniform-random redistribution (ShufflePartitioner)."""
        return self._partition_hint("shuffle")

    def broadcast(self) -> "DataStream":
        """Every downstream instance sees every record (BroadcastPartitioner)."""
        return self._partition_hint("broadcast")

    def forward(self) -> "DataStream":
        """Pin to the local downstream instance (ForwardPartitioner)."""
        return self._partition_hint("forward")

    def global_(self) -> "DataStream":
        """Route everything to instance 0 (GlobalPartitioner)."""
        return self._partition_hint("global")

    def slot_sharing_group(self, name: str) -> "DataStream":
        """Put the operator that produced this stream into slot-sharing
        group `name` (DataStream.slotSharingGroup). Downstream operators
        inherit the group unless they declare their own. On the distributed
        cluster, each named group deploys as its own pipeline stage in its
        own slot, connected by credit-controlled exchanges — isolating
        heavyweight operators AND running the stages concurrently; locally
        (one process) groups are a no-op, like the reference's local
        environments."""
        self.transform.config["slot_sharing_group"] = name
        return self

    def iterate(self, max_rounds: int = 10000) -> "IterativeStream":
        """Open an iteration (DataStream.iterate / IterativeStream.java):
        the returned stream carries this stream's records plus every record
        later fed back via close_with(). Watermarks do not cross the
        feedback edge (reference semantics); with bounded inputs the job
        finishes when the loop body stops emitting feedback records, and
        `max_rounds` bounds non-converging loop bodies.

            it = stream.iterate()
            body = it.map(step_fn)
            it.close_with(body.filter(still_going))   # feedback edge
            body.filter(done).sink_to(...)            # loop exit
        """
        t = Transformation(
            "iteration_head", "iterate", [self.transform],
            {"max_rounds": max_rounds},
        )
        return IterativeStream(self.env, t)

    def key_by(self, key_selector: Callable, name: str = "key_by",
               vectorized: bool = False, traceable: bool = False) -> "KeyedStream":
        """Partition by key. Vectorized form: key_selector(values_column)
        returns the whole key column — keeps the hot ingest path columnar.

        traceable=True (implies vectorized) declares the selector to be a
        pure jax-traceable column function returning NON-NEGATIVE INTEGER
        keys below `execution.state.key-capacity`: the key column is then
        computed on device and a downstream eligible window aggregate fuses
        with this step's chain into one device program (docs/fusion.md)."""
        vectorized = vectorized or traceable
        sel = as_key_selector(key_selector) if not vectorized else key_selector
        t = Transformation(
            "key_by", name, [self.transform],
            {"key_selector": sel, "vectorized": vectorized,
             "traceable": traceable},
        )
        return KeyedStream(self.env, t)

    # -- sinks -------------------------------------------------------------
    def sink_to(self, sink: Sink, name: str = "sink") -> "DataStreamSink":
        t = Transformation("sink", name, [self.transform], {"sink": sink})
        self.env._sinks.append(t)
        return DataStreamSink(self.env, t)

    def print(self) -> "DataStreamSink":
        from flink_tpu.connectors.sink import PrintSink

        return self.sink_to(PrintSink(), name="print")

    def collect(self) -> CollectSink:
        """Convenience: attach a CollectSink and return it (results after
        env.execute())."""
        sink = CollectSink()
        self.sink_to(sink, name="collect")
        return sink


class IterativeStream(DataStream):
    """The head of an iteration (IterativeStream.java analogue); close_with
    wires the feedback edge back to this head."""

    def close_with(self, feedback: DataStream) -> DataStream:
        """Feed `feedback`'s records back into the iteration head
        (IterativeStream.closeWith). Returns the feedback stream."""
        tail = Transformation(
            "iteration_tail", "iteration_tail", [feedback.transform],
            {"head": self.transform},
        )
        self.env._roots.append(tail)
        return feedback


class DataStreamSink:
    def __init__(self, env, transform):
        self.env = env
        self.transform = transform

    def uid(self, uid: str) -> "DataStreamSink":
        self.transform.uid = uid
        return self


class ConnectedStreams:
    """Two paired streams (ConnectedStreams.java): co-transforms see both
    inputs; keyed variants share per-key state across the two inputs."""

    def __init__(self, env: StreamExecutionEnvironment,
                 first: DataStream, second: DataStream):
        self.env = env
        self.first = first
        self.second = second

    def map(self, fn1: Callable, fn2: Callable, name: str = "co_map") -> DataStream:
        t = Transformation(
            "co_map", name, [self.first.transform, self.second.transform],
            {"fn1": fn1, "fn2": fn2},
        )
        return DataStream(self.env, t)

    def flat_map(self, fn1: Callable, fn2: Callable,
                 name: str = "co_flat_map") -> DataStream:
        t = Transformation(
            "co_flat_map", name, [self.first.transform, self.second.transform],
            {"fn1": fn1, "fn2": fn2},
        )
        return DataStream(self.env, t)

    def key_by(self, key_selector1: Callable, key_selector2: Callable) -> "ConnectedStreams":
        """Key both inputs; a subsequent process() shares keyed state/timers
        across the two inputs (the point of connect over union)."""
        cs = ConnectedStreams(self.env, self.first, self.second)
        cs._ks = (as_key_selector(key_selector1), as_key_selector(key_selector2))
        return cs

    def process(self, co_process_fn, name: str = "co_process") -> DataStream:
        """Keyed: KeyedCoProcessFunction (process_element1/process_element2 +
        optional on_timer) with shared per-key state — requires
        key_by(ks1, ks2). Broadcast: when the second stream is
        .broadcast(), a BroadcastProcessFunction
        (process_element(value, state_view) / process_broadcast_element
        (value, state)) with operator-wide broadcast state — the reference's
        broadcast state pattern (BroadcastConnectedStream.process)."""
        ks = getattr(self, "_ks", None)
        if ks is not None:
            t = Transformation(
                "co_process", name,
                [self.first.transform, self.second.transform],
                {"process_fn": co_process_fn,
                 "key_selector1": ks[0], "key_selector2": ks[1]},
            )
            return DataStream(self.env, t)
        if self.second.transform.kind == "broadcast":
            t = Transformation(
                "broadcast_process", name,
                [self.first.transform, self.second.transform],
                {"process_fn": co_process_fn},
            )
            return DataStream(self.env, t)
        raise ValueError(
            "connect(...).process requires key_by(ks1, ks2), or a "
            ".broadcast() second stream for the broadcast state pattern"
        )


class JoinBuilder:
    """where/equalTo/window/apply builder for joins and coGroups
    (JoinedStreams.java:101, CoGroupedStreams.java)."""

    def __init__(self, env, first: DataStream, second: DataStream, cogroup: bool):
        self.env = env
        self.first = first
        self.second = second
        self.cogroup = cogroup
        self._ks1: Optional[Callable] = None
        self._ks2: Optional[Callable] = None
        self._assigner: Optional[WindowAssigner] = None

    def where(self, key_selector: Callable) -> "JoinBuilder":
        self._ks1 = as_key_selector(key_selector)
        return self

    def equal_to(self, key_selector: Callable) -> "JoinBuilder":
        self._ks2 = as_key_selector(key_selector)
        return self

    def window(self, assigner: WindowAssigner) -> "JoinBuilder":
        self._assigner = assigner
        return self

    def apply(self, fn: Callable, name: Optional[str] = None) -> DataStream:
        """Join: fn(left, right) per matching pair. CoGroup: fn(lefts,
        rights) once per key x window."""
        if self._ks1 is None or self._ks2 is None:
            raise ValueError("join requires where(...) and equal_to(...)")
        if self._assigner is None:
            raise ValueError("join requires a window(...) assigner")
        kind = "co_group" if self.cogroup else "window_join"
        t = Transformation(
            kind, name or kind,
            [self.first.transform, self.second.transform],
            {"key_selector1": self._ks1, "key_selector2": self._ks2,
             "assigner": self._assigner, "join_fn": fn},
        )
        return DataStream(self.env, t)


class KeyedStream(DataStream):
    """Keyed partitioned stream (KeyedStream.java:94)."""

    @property
    def key_selector(self) -> Callable:
        return self.transform.config["key_selector"]

    def window(self, assigner: WindowAssigner) -> "WindowedStream":
        return WindowedStream(self, assigner)

    def _scalar_key_selector(self) -> Callable:
        """Per-record view of the key selector (vectorized selectors are
        adapted for the per-record oracle/CPU operators)."""
        sel = self.key_selector
        if self.transform.config.get("vectorized"):
            import numpy as np

            return lambda v: sel(np.asarray(v)[None, ...])[0]
        return sel

    # rolling (non-windowed) keyed reduce — oracle/CPU path
    def reduce(self, fn: Callable, name: str = "keyed_reduce") -> "DataStream":
        t = Transformation(
            "reduce", name, [self.transform],
            {"reduce_fn": fn, "key_selector": self._scalar_key_selector()},
        )
        return DataStream(self.env, t)

    def process(self, process_fn, name: str = "keyed_process") -> "DataStream":
        """Low-level keyed ProcessFunction with timers (oracle/CPU path)."""
        t = Transformation(
            "process_keyed",
            name,
            [self.transform],
            {"process_fn": process_fn, "key_selector": self._scalar_key_selector()},
        )
        return DataStream(self.env, t)

    def continuous_aggregate(
        self,
        specs,
        key_fields,
        out_names,
        mini_batch: Optional[bool] = None,
        generate_update_before: bool = True,
        device: Optional[bool] = None,
        name: str = "group_agg",
    ) -> "DataStream":
        """Continuous (non-windowed) group aggregation emitting a retract
        changelog — the reference's GroupAggFunction
        (flink-table-runtime .../aggregate/GroupAggFunction.java:33).

        `specs` is a list of (func, col) with func in COUNT/SUM/AVG/MIN/MAX
        (col ignored for COUNT); `key_fields` name the key parts and
        `out_names` the aggregate outputs in emitted rows. Input rows may
        themselves carry changelog kinds (table/changelog.py), so cascaded
        aggregations compose. `mini_batch=True` emits one transition per
        distinct key per batch (MiniBatchGroupAggFunction analogue);
        False gives the exact per-record reference emission order.
        `device=True` keeps linear accumulators in HBM with one scatter-add
        dispatch per batch."""
        t = Transformation(
            "group_agg", name, [self.transform],
            {
                "key_selector": self._scalar_key_selector(),
                "specs": list(specs),
                "key_fields": list(key_fields),
                "out_names": list(out_names),
                "mini_batch": mini_batch,
                "generate_update_before": generate_update_before,
                "device": device,
            },
        )
        return DataStream(self.env, t)


class WindowedStream:
    """Builder for windowed aggregations (WindowedStream.java;
    the builder decides oracle vs device operator the same way
    WindowOperatorBuilder.java:79 selects sync vs async operators)."""

    def __init__(self, keyed: KeyedStream, assigner: WindowAssigner):
        self._keyed = keyed
        self._assigner = assigner
        self._trigger: Optional[Trigger] = None
        self._evictor: Optional[Evictor] = None
        self._allowed_lateness = 0
        self._side_output_late = False

    def trigger(self, trigger: Trigger) -> "WindowedStream":
        self._trigger = trigger
        return self

    def evictor(self, evictor: Evictor) -> "WindowedStream":
        self._evictor = evictor
        return self

    def allowed_lateness(self, lateness_ms: int) -> "WindowedStream":
        self._allowed_lateness = lateness_ms
        return self

    def side_output_late_data(self) -> "WindowedStream":
        self._side_output_late = True
        return self

    def _agg_transform(self, aggregate, value_fn, window_fn, name,
                       value_vectorized: bool = False,
                       value_traceable: bool = False) -> DataStream:
        t = Transformation(
            "window_aggregate",
            name,
            [self._keyed.transform],
            {
                "assigner": self._assigner,
                "aggregate": aggregate,
                "value_fn": value_fn,
                "value_vectorized": value_vectorized or value_traceable,
                "value_traceable": value_traceable,
                "window_fn": window_fn,
                "trigger": self._trigger,
                "evictor": self._evictor,
                "allowed_lateness": self._allowed_lateness,
                "side_output_late": self._side_output_late,
                "key_selector": self._keyed.key_selector,
                "key_vectorized": self._keyed.transform.config.get("vectorized", False),
                "key_traceable": self._keyed.transform.config.get("traceable", False),
            },
        )
        return DataStream(self._keyed.env, t)

    def aggregate(
        self,
        aggregate: Union[str, AggregateFunction, Any],
        value_fn: Optional[Callable] = None,
        window_fn=None,
        name: str = "window_aggregate",
        value_vectorized: bool = False,
        value_traceable: bool = False,
    ) -> DataStream:
        """`aggregate` is a builtin name ('sum'/'count'/'min'/'max'/'mean'),
        a DeviceAggregator (device path), or an AggregateFunction (oracle).
        `value_fn` extracts the numeric column for device aggregation; with
        value_vectorized=True it maps the whole values column at once, and
        value_traceable=True additionally declares it jax-traceable so the
        extraction runs inside the fused device program (docs/fusion.md)."""
        return self._agg_transform(aggregate, value_fn, window_fn, name,
                                   value_vectorized=value_vectorized,
                                   value_traceable=value_traceable)

    def reduce(self, fn: Callable, name: str = "window_reduce") -> DataStream:
        from flink_tpu.api.functions import ReduceAggregate

        return self._agg_transform(ReduceAggregate(fn), None, None, name)

    def sum(self, value_fn: Optional[Callable] = None) -> DataStream:
        return self.aggregate("sum", value_fn, name="window_sum")

    def count(self) -> DataStream:
        return self.aggregate("count", name="window_count")

    def max(self, value_fn: Optional[Callable] = None) -> DataStream:
        return self.aggregate("max", value_fn, name="window_max")

    def min(self, value_fn: Optional[Callable] = None) -> DataStream:
        return self.aggregate("min", value_fn, name="window_min")

    def process(self, window_fn, name: str = "window_process") -> DataStream:
        """Buffered window with ProcessWindowFunction (no pre-aggregation)."""
        return self._agg_transform(None, None, window_fn, name)
