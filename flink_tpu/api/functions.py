"""User function interfaces — re-exported from flink_tpu.core.functions.

The definitions live in core (matching the reference, which places these
in flink-core .../api/common/functions/, not in the streaming API layer);
this module keeps the API-namespace import path working.
"""

from flink_tpu.core.functions import (  # noqa: F401
    ACC,
    IN,
    KEY,
    LATE_DATA_TAG,
    OUT,
    AggregateFunction,
    FilterFunction,
    FlatMapFunction,
    KeySelector,
    MapFunction,
    OutputTag,
    PassThroughWindowFunction,
    ProcessFunction,
    ProcessWindowFunction,
    ReduceAggregate,
    ReduceFunction,
    as_key_selector,
    as_reduce_function,
)
