"""DataStream API V2: ProcessFunction-centric streams (C9).

The reference's next-gen API (flink-datastream-api:
datastream/api/ExecutionEnvironment.java, stream/KeyedPartitionStream.java,
function/OneInputStreamProcessFunction.java) reduces the operator zoo to a
single `process()` primitive over explicit partitionings; its impl module
(flink-datastream) translates onto the V1 runtime. Same structure here: V2
streams wrap the V1 DataStream plan, so both APIs share the executor,
state, windowing and device paths.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

from flink_tpu.api.datastream import DataStream, StreamExecutionEnvironment


class Collector:
    """Receives the elements a ProcessFunction emits."""

    def __init__(self):
        self._out: List[Any] = []

    def collect(self, value: Any) -> None:
        self._out.append(value)


class RuntimeContext:
    """Visible execution context of one invocation."""

    def __init__(self, timestamp: Optional[int] = None, key: Any = None):
        self.timestamp = timestamp
        self.key = key


class OneInputStreamProcessFunction:
    """V2's single user primitive (OneInputStreamProcessFunction.java):
    override process_record; open/close bracket the lifetime."""

    def open(self) -> None:
        pass

    def process_record(self, record: Any, output: Collector, ctx: RuntimeContext) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


def _as_process_fn(fn) -> OneInputStreamProcessFunction:
    if isinstance(fn, OneInputStreamProcessFunction):
        return fn

    class _Wrapped(OneInputStreamProcessFunction):
        def process_record(self, record, output, ctx):
            for v in fn(record):
                output.collect(v)

    return _Wrapped()


class NonKeyedPartitionStream:
    """V2 stream over the V1 plan."""

    def __init__(self, inner: DataStream):
        self._inner = inner

    def process(self, fn, name: str = "process") -> "NonKeyedPartitionStream":
        pf = _as_process_fn(fn)
        pf.open()

        def flat(record):
            out = Collector()
            pf.process_record(record, out, RuntimeContext())
            return out._out

        return NonKeyedPartitionStream(self._inner.flat_map(flat, name=name))

    def key_by(self, key_selector: Callable) -> "KeyedPartitionStream":
        return KeyedPartitionStream(self._inner.key_by(key_selector), key_selector)

    def to_sink(self, sink) -> None:
        self._inner.sink_to(sink)

    def collect_to_list(self):
        return self._inner.collect()


class KeyedPartitionStream:
    def __init__(self, inner, key_selector: Callable):
        self._inner = inner
        self._key_selector = key_selector

    def process(self, fn, name: str = "keyed_process") -> NonKeyedPartitionStream:
        from flink_tpu.api.functions import ProcessFunction

        pf = _as_process_fn(fn)
        pf.open()
        selector = self._key_selector

        class _Adapter(ProcessFunction):
            def process_element(self, value, ctx):
                out = Collector()
                pf.process_record(
                    value, out,
                    RuntimeContext(timestamp=ctx.timestamp, key=selector(value)),
                )
                return iter(out._out)

        return NonKeyedPartitionStream(self._inner.process(_Adapter(), name=name))

    def window(self, assigner):
        return self._inner.window(assigner)


class ExecutionEnvironment:
    """V2 entry point (ExecutionEnvironment.java)."""

    def __init__(self, v1_env: Optional[StreamExecutionEnvironment] = None):
        self.v1 = v1_env or StreamExecutionEnvironment.get_execution_environment()

    @staticmethod
    def get_instance() -> "ExecutionEnvironment":
        return ExecutionEnvironment()

    def from_source(self, source, watermark_strategy=None,
                    name: str = "v2-source") -> NonKeyedPartitionStream:
        return NonKeyedPartitionStream(self.v1.from_source(source, watermark_strategy, name))

    def from_collection(self, items: Iterable, timestamp_fn=None,
                        watermark_strategy=None) -> NonKeyedPartitionStream:
        return NonKeyedPartitionStream(
            self.v1.from_collection(list(items), timestamp_fn=timestamp_fn,
                                    watermark_strategy=watermark_strategy)
        )

    def execute(self, job_name: str = "v2-job"):
        return self.v1.execute(job_name)
