"""Windowing API: assigners, triggers, evictors (reference:
flink-runtime .../streaming/api/windowing/, flink-streaming-java session
assigners)."""

from flink_tpu.api.windowing.assigners import (
    WindowAssigner,
    TumblingEventTimeWindows,
    SlidingEventTimeWindows,
    EventTimeSessionWindows,
    GlobalWindows,
    GlobalWindow,
)
from flink_tpu.api.windowing.triggers import (
    Trigger,
    TriggerResult,
    EventTimeTrigger,
    CountTrigger,
    PurgingTrigger,
    NeverTrigger,
)
from flink_tpu.api.windowing.evictors import Evictor, CountEvictor, TimeEvictor
