"""Window assigners.

Reference semantics:
- TumblingEventTimeWindows / SlidingEventTimeWindows
  (flink-runtime .../api/windowing/assigners/): grid windows via
  TimeWindow.getWindowStartWithOffset.
- EventTimeSessionWindows (flink-streaming-java .../assigners/
  EventTimeSessionWindows.java): per-element window [ts, ts+gap), merged by
  the operator's MergingWindowSet.
- GlobalWindows (.../assigners/GlobalWindows.java): single window, default
  NeverTrigger.

TPU note: grid assigners also expose the *slice decomposition* used by the
device operator (slice = gcd-granule of (size, slide, offset); a window is a
contiguous run of slices) — the same pane/slice trick as the reference SQL
runtime's tvf/slicing/ assigners, which is what makes sliding windows a
segment-reduce instead of size/slide redundant state copies.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional

import numpy as np

from flink_tpu.api.windowing.triggers import (
    EventTimeTrigger,
    NeverTrigger,
    ProcessingTimeTrigger,
    Trigger,
)
from flink_tpu.core.time import (
    MIN_TIMESTAMP,
    TimeWindow,
    assign_sliding,
    assign_tumbling,
    window_start_with_offset,
)


@dataclasses.dataclass(frozen=True)
class GlobalWindow:
    """The singleton namespace of GlobalWindows (GlobalWindow.java)."""

    def max_timestamp(self) -> int:
        from flink_tpu.core.time import MAX_WATERMARK
        return MAX_WATERMARK

    def __repr__(self) -> str:
        return "GlobalWindow"


class WindowAssigner:
    """Base contract (WindowAssigner.java): assign windows per element,
    provide the default trigger, and declare event-time-ness."""

    is_event_time: bool = True
    is_merging: bool = False

    def assign_windows(self, element, timestamp: int) -> List:
        raise NotImplementedError

    def get_default_trigger(self) -> Trigger:
        raise NotImplementedError

    # -- slice decomposition (device path; None = not sliceable) ----------
    @property
    def slice_ms(self) -> Optional[int]:
        return None

    @property
    def slices_per_window(self) -> Optional[int]:
        return None

    @property
    def slide_slices(self) -> Optional[int]:
        """Slices between consecutive window starts."""
        return None

    @property
    def offset_ms(self) -> int:
        return 0

    def slices_on(self, granule_ms: int):
        """EXACT decomposition of this assigner's windows onto an arbitrary
        slice granule: (slices_per_window, slide_slices) such that window j
        covers exactly the half-open slice run [j*slide_slices,
        j*slide_slices + slices_per_window) on the `granule_ms` grid.

        This is the shared-partials contract (graph/window_sharing.py): a
        group of correlated windows computes ONE ring at the gcd granule
        and every member derives its windows from those partials, so the
        decomposition must be exact — including the degenerate shapes a
        naive `size // slide` computation gets wrong (a slide that does
        not divide the size, and the size == slide tumbling collapse,
        where the only valid granule is gcd(size, slide), not slide).

        Raises ValueError when the granule does not divide both size and
        slide (the decomposition would not be exact: a window edge would
        fall inside a slice) or when the assigner is not sliceable."""
        if self.slice_ms is None:
            raise ValueError(f"{self!r} is not sliceable")
        size = self.slices_per_window * self.slice_ms
        slide = self.slide_slices * self.slice_ms
        if granule_ms <= 0 or size % granule_ms or slide % granule_ms:
            raise ValueError(
                f"granule {granule_ms}ms does not divide size={size}ms / "
                f"slide={slide}ms exactly — a window edge would fall inside "
                f"a slice; use a divisor of gcd(size, slide) = "
                f"{math.gcd(size, slide)}ms"
            )
        return size // granule_ms, slide // granule_ms


class TumblingEventTimeWindows(WindowAssigner):
    def __init__(self, size_ms: int, offset_ms: int = 0):
        if abs(offset_ms) >= size_ms or size_ms <= 0:
            raise ValueError(
                f"TumblingEventTimeWindows requires size > 0 and |offset| < size, got size={size_ms} offset={offset_ms}"
            )
        self.size = size_ms
        self.offset = offset_ms

    @staticmethod
    def of(size_ms: int, offset_ms: int = 0) -> "TumblingEventTimeWindows":
        return TumblingEventTimeWindows(size_ms, offset_ms)

    def assign_windows(self, element, timestamp: int) -> List[TimeWindow]:
        return assign_tumbling(timestamp, self.size, self.offset)

    def get_default_trigger(self) -> Trigger:
        return EventTimeTrigger()

    @property
    def slice_ms(self) -> int:
        return self.size

    @property
    def slices_per_window(self) -> int:
        return 1

    @property
    def slide_slices(self) -> int:
        return 1

    @property
    def offset_ms(self) -> int:
        return self.offset

    def __repr__(self) -> str:
        return f"TumblingEventTimeWindows(size={self.size}, offset={self.offset})"


class SlidingEventTimeWindows(WindowAssigner):
    def __init__(self, size_ms: int, slide_ms: int, offset_ms: int = 0):
        if abs(offset_ms) >= slide_ms or size_ms <= 0:
            raise ValueError(
                f"SlidingEventTimeWindows requires size > 0 and |offset| < slide, got size={size_ms} slide={slide_ms} offset={offset_ms}"
            )
        self.size = size_ms
        self.slide = slide_ms
        self.offset = offset_ms

    @staticmethod
    def of(size_ms: int, slide_ms: int, offset_ms: int = 0) -> "SlidingEventTimeWindows":
        return SlidingEventTimeWindows(size_ms, slide_ms, offset_ms)

    def assign_windows(self, element, timestamp: int) -> List[TimeWindow]:
        return assign_sliding(timestamp, self.size, self.slide, self.offset)

    def get_default_trigger(self) -> Trigger:
        return EventTimeTrigger()

    # Slice decomposition: slice granule = gcd(size, slide). A window of
    # `size` covers size/g consecutive slices; windows start every slide/g
    # slices. When slide divides size this is exactly the reference's
    # tvf/slicing SliceAssigners.sliding decomposition.
    @property
    def slice_ms(self) -> int:
        return math.gcd(self.size, self.slide)

    @property
    def slices_per_window(self) -> int:
        return self.size // self.slice_ms

    @property
    def slide_slices(self) -> int:
        return self.slide // self.slice_ms

    @property
    def offset_ms(self) -> int:
        return self.offset

    def __repr__(self) -> str:
        return f"SlidingEventTimeWindows(size={self.size}, slide={self.slide}, offset={self.offset})"


class EventTimeSessionWindows(WindowAssigner):
    """Each element gets [ts, ts + gap); overlapping windows merge
    (EventTimeSessionWindows.java + MergingWindowSet)."""

    is_merging = True

    def __init__(self, gap_ms: int):
        if gap_ms <= 0:
            raise ValueError("Session gap must be positive")
        self.gap = gap_ms

    @staticmethod
    def with_gap(gap_ms: int) -> "EventTimeSessionWindows":
        return EventTimeSessionWindows(gap_ms)

    def assign_windows(self, element, timestamp: int) -> List[TimeWindow]:
        return [TimeWindow(timestamp, timestamp + self.gap)]

    def get_default_trigger(self) -> Trigger:
        return EventTimeTrigger()

    def merge_windows(self, windows: List[TimeWindow]):
        """Returns list of (merged_window, [source_windows]) for overlapping
        runs (MergingWindowAssigner.mergeWindows semantics)."""
        if not windows:
            return []
        sorted_ws = sorted(windows, key=lambda w: (w.start, w.end))
        merged = []
        cur_cover = sorted_ws[0]
        cur_members = [sorted_ws[0]]
        for w in sorted_ws[1:]:
            # session merge: touching windows ([a,b) and [b,c)) DO merge
            if w.start <= cur_cover.end:
                cur_cover = cur_cover.cover(w)
                cur_members.append(w)
            else:
                merged.append((cur_cover, cur_members))
                cur_cover, cur_members = w, [w]
        merged.append((cur_cover, cur_members))
        return merged

    def __repr__(self) -> str:
        return f"EventTimeSessionWindows(gap={self.gap})"


class ProcessingTimeSessionWindows(EventTimeSessionWindows):
    is_event_time = False

    def get_default_trigger(self) -> Trigger:
        return ProcessingTimeTrigger()


class GlobalWindows(WindowAssigner):
    """All elements into one global window; never fires unless a custom
    trigger (e.g. CountTrigger) is set (GlobalWindows.java:95 NeverTrigger)."""

    _WINDOW = GlobalWindow()

    def assign_windows(self, element, timestamp: int) -> List[GlobalWindow]:
        return [self._WINDOW]

    @staticmethod
    def create() -> "GlobalWindows":
        return GlobalWindows()

    def get_default_trigger(self) -> Trigger:
        return NeverTrigger()

    def __repr__(self) -> str:
        return "GlobalWindows()"


class TumblingProcessingTimeWindows(TumblingEventTimeWindows):
    is_event_time = False

    def get_default_trigger(self) -> Trigger:
        return ProcessingTimeTrigger()


class SlidingProcessingTimeWindows(SlidingEventTimeWindows):
    is_event_time = False

    def get_default_trigger(self) -> Trigger:
        return ProcessingTimeTrigger()
