"""Evictors: remove elements from the window buffer before/after the window
function (flink-runtime .../api/windowing/evictors/Evictor.java,
CountEvictor.java, TimeEvictor.java).

Evicting windows buffer the full element list per (key, window) — the
EvictingWindowOperator path (EvictingWindowOperator.java:63) — which is
incompatible with pre-aggregation; the device operator falls back to the
oracle operator when an evictor is present (same as the reference, where
evicting windows use ListState instead of a single ACC).
"""

from __future__ import annotations

from typing import List, Tuple


class Evictor:
    """Elements are (timestamp, value) pairs in insertion order."""

    def evict_before(self, elements: List[Tuple[int, object]], size: int, window) -> List[Tuple[int, object]]:
        return elements

    def evict_after(self, elements: List[Tuple[int, object]], size: int, window) -> List[Tuple[int, object]]:
        return elements


class CountEvictor(Evictor):
    """Keeps only the last max_count elements (CountEvictor.java)."""

    def __init__(self, max_count: int, do_evict_after: bool = False):
        self.max_count = max_count
        self.do_evict_after = do_evict_after

    @staticmethod
    def of(max_count: int, do_evict_after: bool = False) -> "CountEvictor":
        return CountEvictor(max_count, do_evict_after)

    def _evict(self, elements, size, window):
        if size <= self.max_count:
            return elements
        return elements[size - self.max_count:]

    def evict_before(self, elements, size, window):
        return elements if self.do_evict_after else self._evict(elements, size, window)

    def evict_after(self, elements, size, window):
        return self._evict(elements, size, window) if self.do_evict_after else elements


class TimeEvictor(Evictor):
    """Evicts elements older than max_ts - window_size_ms (TimeEvictor.java)."""

    def __init__(self, window_size_ms: int, do_evict_after: bool = False):
        self.window_size = window_size_ms
        self.do_evict_after = do_evict_after

    @staticmethod
    def of(window_size_ms: int, do_evict_after: bool = False) -> "TimeEvictor":
        return TimeEvictor(window_size_ms, do_evict_after)

    def _evict(self, elements, size, window):
        if not elements:
            return elements
        has_ts = any(ts is not None for ts, _ in elements)
        if not has_ts:
            return elements
        max_ts = max(ts for ts, _ in elements if ts is not None)
        cutoff = max_ts - self.window_size
        return [(ts, v) for ts, v in elements if ts is None or ts >= cutoff]

    def evict_before(self, elements, size, window):
        return elements if self.do_evict_after else self._evict(elements, size, window)

    def evict_after(self, elements, size, window):
        return self._evict(elements, size, window) if self.do_evict_after else elements
