"""Triggers: decide when a window's contents are emitted.

Reference semantics (flink-runtime .../api/windowing/triggers/):
- TriggerResult ∈ {CONTINUE, FIRE, PURGE, FIRE_AND_PURGE}
- EventTimeTrigger.onElement: FIRE immediately if window.maxTimestamp() <=
  currentWatermark (late-but-allowed element), else register an event-time
  timer at maxTimestamp() and CONTINUE; onEventTime: FIRE iff time ==
  window.maxTimestamp().
- CountTrigger: per-(key, window) ReducingState count; FIRE_AND... no — FIRE
  when count >= maxCount, resetting the count (CountTrigger.java clears via
  state.clear() only in clear(); onElement adds 1 and fires + clears count).
- PurgingTrigger wraps any trigger, turning FIRE into FIRE_AND_PURGE.

The TriggerContext gives triggers per-(key, window) partitioned state and
timer registration — same contract as Trigger.TriggerContext.
"""

from __future__ import annotations

import enum
from typing import Any, Optional


class TriggerResult(enum.Flag):
    CONTINUE = 0
    FIRE = enum.auto()
    PURGE = enum.auto()
    FIRE_AND_PURGE = FIRE | PURGE

    @property
    def is_fire(self) -> bool:
        return bool(self & TriggerResult.FIRE)

    @property
    def is_purge(self) -> bool:
        return bool(self & TriggerResult.PURGE)


class TriggerContext:
    """Per-invocation context: current key/window fixed by the operator."""

    def get_current_watermark(self) -> int:
        raise NotImplementedError

    def register_event_time_timer(self, time: int) -> None:
        raise NotImplementedError

    def delete_event_time_timer(self, time: int) -> None:
        raise NotImplementedError

    def register_processing_time_timer(self, time: int) -> None:
        raise NotImplementedError

    def delete_processing_time_timer(self, time: int) -> None:
        raise NotImplementedError

    def get_trigger_state(self, name: str, default=None) -> Any:
        """Partitioned per-(key, window) trigger state (ValueState analogue)."""
        raise NotImplementedError

    def set_trigger_state(self, name: str, value) -> None:
        raise NotImplementedError

    def clear_trigger_state(self, name: str) -> None:
        raise NotImplementedError


class Trigger:
    def on_element(self, element, timestamp: int, window, ctx: TriggerContext) -> TriggerResult:
        raise NotImplementedError

    def on_event_time(self, time: int, window, ctx: TriggerContext) -> TriggerResult:
        raise NotImplementedError

    def on_processing_time(self, time: int, window, ctx: TriggerContext) -> TriggerResult:
        raise NotImplementedError

    def can_merge(self) -> bool:
        return False

    def on_merge(self, window, ctx: TriggerContext) -> None:
        raise NotImplementedError(f"{type(self).__name__} cannot merge")

    def clear(self, window, ctx: TriggerContext) -> None:
        pass


class EventTimeTrigger(Trigger):
    """EventTimeTrigger.java exact semantics."""

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        if window.max_timestamp() <= ctx.get_current_watermark():
            return TriggerResult.FIRE
        ctx.register_event_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.FIRE if time == window.max_timestamp() else TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        # only re-register if the merged window's timer is still in the future
        if window.max_timestamp() > ctx.get_current_watermark():
            ctx.register_event_time_timer(window.max_timestamp())

    def clear(self, window, ctx) -> None:
        ctx.delete_event_time_timer(window.max_timestamp())

    def __repr__(self):
        return "EventTimeTrigger()"


class ProcessingTimeTrigger(Trigger):
    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        ctx.register_processing_time_timer(window.max_timestamp())
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.FIRE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        ctx.register_processing_time_timer(window.max_timestamp())

    def clear(self, window, ctx) -> None:
        ctx.delete_processing_time_timer(window.max_timestamp())


class CountTrigger(Trigger):
    """Fires once the per-(key, window) element count reaches max_count
    (CountTrigger.java: ReducingState sum; fire clears the count)."""

    def __init__(self, max_count: int):
        self.max_count = max_count

    @staticmethod
    def of(max_count: int) -> "CountTrigger":
        return CountTrigger(max_count)

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        count = (ctx.get_trigger_state("count") or 0) + 1
        if count >= self.max_count:
            ctx.clear_trigger_state("count")
            return TriggerResult.FIRE
        ctx.set_trigger_state("count", count)
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        pass  # counts were merged by state merge

    def clear(self, window, ctx) -> None:
        ctx.clear_trigger_state("count")


class PurgingTrigger(Trigger):
    """Wraps a trigger, upgrading FIRE to FIRE_AND_PURGE (PurgingTrigger.java)."""

    def __init__(self, inner: Trigger):
        self.inner = inner

    @staticmethod
    def of(inner: Trigger) -> "PurgingTrigger":
        return PurgingTrigger(inner)

    def _wrap(self, result: TriggerResult) -> TriggerResult:
        return TriggerResult.FIRE_AND_PURGE if result.is_fire else result

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        return self._wrap(self.inner.on_element(element, timestamp, window, ctx))

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return self._wrap(self.inner.on_event_time(time, window, ctx))

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return self._wrap(self.inner.on_processing_time(time, window, ctx))

    def can_merge(self) -> bool:
        return self.inner.can_merge()

    def on_merge(self, window, ctx) -> None:
        self.inner.on_merge(window, ctx)

    def clear(self, window, ctx) -> None:
        self.inner.clear(window, ctx)


class NeverTrigger(Trigger):
    """GlobalWindows' default: never fires (GlobalWindows.java NeverTrigger)."""

    def on_element(self, element, timestamp, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_event_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def on_processing_time(self, time, window, ctx) -> TriggerResult:
        return TriggerResult.CONTINUE

    def can_merge(self) -> bool:
        return True

    def on_merge(self, window, ctx) -> None:
        pass
