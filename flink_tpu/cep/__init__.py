"""CEP: complex event processing on keyed streams (reference:
flink-libraries/flink-cep — CepOperator.java:83, nfa/NFA.java, Pattern API)."""

from flink_tpu.cep.pattern import Pattern
from flink_tpu.cep.nfa import NFA
from flink_tpu.cep.operator import CepOperator


def pattern_stream(keyed_stream, pattern: Pattern, select_fn=None, name: str = "cep"):
    """CEP.pattern(stream, pattern).select(fn) analogue: returns a DataStream
    of select_fn(match) records."""
    from flink_tpu.api.datastream import DataStream
    from flink_tpu.graph.transformation import Transformation

    t = Transformation(
        "cep",
        name,
        [keyed_stream.transform],
        {
            "pattern": pattern,
            "select_fn": select_fn,
            "key_selector": keyed_stream.key_selector,
        },
    )
    return DataStream(keyed_stream.env, t)
