"""NFA over pattern stages (reference: flink-cep nfa/NFA.java — states with
TAKE/IGNORE/PROCEED transitions over shared-buffer runs).

A *run* is a partial match: (stage_index, taken_in_stage, events, start_ts).
On each event, every run branches per the reference's transition semantics:

  TAKE    — event matches the current stage: extend the run
  PROCEED — stage satisfied (>= min_times): also advance to the next stage
            and re-evaluate (epsilon transition)
  IGNORE  — relaxed contiguity: keep the run alive without consuming;
            strict contiguity kills the run on a non-matching event

Completed runs (all stages satisfied) emit {stage_name: [events]}.
`within` prunes runs whose span exceeds the window (timed-out runs die,
matching the reference's timeout pruning; partial-timeout side output is a
later addition).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.cep.pattern import Pattern, PatternStage


@dataclasses.dataclass(frozen=True)
class Run:
    stage: int                        # current stage index
    taken: int                        # events taken in the current stage
    events: Tuple[Tuple[str, int, Any], ...]  # (stage_name, event_seq, event)
    start_ts: int

    def to_match(self, pattern: Pattern) -> Dict[str, List[Any]]:
        out: Dict[str, List[Any]] = {s.name: [] for s in pattern.stages}
        for name, _seq, ev in self.events:
            out[name].append(ev)
        return out


class NFA:
    def __init__(self, pattern: Pattern):
        self.pattern = pattern
        self.stages = pattern.stages
        self._auto_seq = 0

    def initial_runs(self) -> List[Run]:
        return []

    def _stage_satisfied(self, stage: PatternStage, taken: int) -> bool:
        return taken >= stage.min_times

    def _stage_can_take(self, stage: PatternStage, taken: int) -> bool:
        return stage.max_times == -1 or taken < stage.max_times

    def advance(
        self, runs: List[Run], event: Any, timestamp: int, seq: int = None
    ) -> Tuple[List[Run], List[Dict[str, List[Any]]]]:
        """Process one event (per key, in timestamp order). `seq` is a
        unique event ordinal distinguishing equal-valued events (match dedup
        is per event *instance*). Returns (surviving runs, matches)."""
        if seq is None:
            seq = self._auto_seq
            self._auto_seq += 1
        self._event_seq = seq
        within = self.pattern.within_ms
        new_runs: List[Run] = []
        matches: List[Dict[str, List[Any]]] = []
        seen: set = set()
        seen_matches: set = set()

        def add_run(run: Run) -> None:
            key = (run.stage, run.taken, run.events)
            if key not in seen:
                seen.add(key)
                new_runs.append(run)

        def emit(run: Run) -> None:
            key = tuple((n, s) for n, s, _ in run.events)
            if key not in seen_matches:
                seen_matches.add(key)
                matches.append(run.to_match(self.pattern))

        # start a fresh run at every event (every event may begin a match)
        candidates = list(runs) + [Run(0, 0, (), timestamp)]

        for run in candidates:
            if within is not None and run.events and timestamp - run.start_ts > within:
                continue  # timed out
            self._branch(run, event, timestamp, add_run, emit)
        return new_runs, matches

    def _after_take(self, run: Run, add_run, emit) -> None:
        """Post-TAKE bookkeeping: emit complete matches, keep loops open,
        eagerly advance satisfied stages (so the NEXT stage's contiguity
        policy governs subsequent events — strict `next` dies on a gap)."""
        stage = self.stages[run.stage]
        last = len(self.stages) - 1
        if run.stage == last:
            if self._stage_satisfied(stage, run.taken):
                emit(run)
                if stage.max_times == -1:
                    add_run(run)  # looping final stage may still grow
            else:
                add_run(run)  # e.g. times(n) not yet reached
            return
        if self._stage_can_take(stage, run.taken):
            add_run(run)  # looping stage stays open
        if self._stage_satisfied(stage, run.taken):
            add_run(Run(run.stage + 1, 0, run.events, run.start_ts))

    def _branch(self, run: Run, event, timestamp, add_run, emit) -> None:
        stage = self.stages[run.stage]
        matched = stage.accepts(event)

        # TAKE in current stage
        if matched and self._stage_can_take(stage, run.taken):
            taken_run = Run(
                run.stage,
                run.taken + 1,
                run.events + ((stage.name, self._event_seq, event),),
                run.start_ts if run.events else timestamp,
            )
            self._after_take(taken_run, add_run, emit)
            return  # took: the run's successors were registered

        # PROCEED (epsilon): satisfied without taking (optional stages) ->
        # evaluate the same event against the next stage
        if run.stage < len(self.stages) - 1 and self._stage_satisfied(stage, run.taken):
            self._branch(
                Run(run.stage + 1, 0, run.events, run.start_ts),
                event, timestamp, add_run, emit,
            )

        # IGNORE: survive a non-matching event?
        if run.taken == 0 and run.stage > 0 and stage.contiguity == "strict":
            return  # strict `next`: a gap kills the run
        if run.events:  # started runs survive under relaxed contiguity
            add_run(run)
