"""CepOperator: keyed NFA evaluation with event-time ordering.

Reference semantics (flink-cep .../operator/CepOperator.java:83): in event
time, elements are buffered per key in a priority queue and fed to the NFA
in timestamp order when the watermark passes them (:processElement buffers,
:onEventTime drains up to the watermark); per-key NFA state lives in keyed
state and is part of snapshots.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Dict, List, Optional, Tuple

from flink_tpu.cep.nfa import NFA, Run
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.time import MIN_WATERMARK


class CepOperator:
    """Duck-types the window-operator runner interface (process_record /
    process_watermark / drain_output / snapshot / restore)."""

    def __init__(self, pattern: Pattern, select_fn: Optional[Callable] = None):
        self.pattern = pattern
        self.nfa = NFA(pattern)
        self.select_fn = select_fn or (lambda match: match)
        self._buffers: Dict[Any, List[Tuple[int, int, Any]]] = {}  # key -> heap
        self._runs: Dict[Any, List[Run]] = {}
        self._seq = 0
        self.current_watermark = MIN_WATERMARK
        self.output: List[Tuple[Any, Any, Any, int]] = []
        self.side_output: Dict[str, List] = {}
        self.num_late_records_dropped = 0

    def process_record(self, key, value, timestamp: int) -> None:
        if timestamp <= self.current_watermark:
            self.num_late_records_dropped += 1  # late events are dropped (ref)
            return
        heapq.heappush(self._buffers.setdefault(key, []), (timestamp, self._seq, value))
        self._seq += 1

    def process_watermark(self, watermark: int) -> None:
        if watermark <= self.current_watermark:
            return
        for key, heap in self._buffers.items():
            runs = self._runs.get(key, [])
            while heap and heap[0][0] <= watermark:
                ts, _, event = heapq.heappop(heap)
                runs, matches = self.nfa.advance(runs, event, ts)
                for m in matches:
                    self.output.append((key, None, self.select_fn(m), ts))
            self._runs[key] = runs
        self.current_watermark = watermark

    def advance_processing_time(self, time: int) -> None:
        pass

    def drain_output(self):
        out = self.output
        self.output = []
        return out

    # -- snapshot ---------------------------------------------------------
    def snapshot(self) -> dict:
        return {
            "buffers": {k: list(v) for k, v in self._buffers.items()},
            "runs": {
                k: [(r.stage, r.taken, list(r.events), r.start_ts) for r in v]
                for k, v in self._runs.items()
            },
            "watermark": self.current_watermark,
            "seq": self._seq,
            "late": self.num_late_records_dropped,
        }

    def restore(self, snap: dict) -> None:
        self._buffers = {k: list(map(tuple, v)) for k, v in snap["buffers"].items()}
        for h in self._buffers.values():
            heapq.heapify(h)
        self._runs = {
            k: [Run(s, t, tuple(map(tuple, ev)), st) for (s, t, ev, st) in v]
            for k, v in snap["runs"].items()
        }
        self.current_watermark = snap["watermark"]
        self._seq = snap["seq"]
        self.num_late_records_dropped = snap["late"]
        self.output = []
