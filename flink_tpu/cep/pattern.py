"""Pattern API (reference: flink-cep .../pattern/Pattern.java).

Supported surface: begin/next (strict contiguity) / followed_by (relaxed
contiguity, skips non-matching events) / where (predicates, ANDed) /
times(n) / one_or_more() (greedy, relaxed-internal) / optional() /
within(ms) — the core of the reference's quantifier model.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional


@dataclasses.dataclass
class PatternStage:
    name: str
    contiguity: str              # 'strict' | 'relaxed' (first stage: 'relaxed')
    conditions: List[Callable]   # ANDed predicates event -> bool
    min_times: int = 1
    max_times: int = 1           # -1 = unbounded (one_or_more)
    optional: bool = False

    def accepts(self, event) -> bool:
        return all(c(event) for c in self.conditions)


class Pattern:
    def __init__(self, stages: List[PatternStage], within_ms: Optional[int] = None):
        self.stages = stages
        self.within_ms = within_ms

    # -- construction -----------------------------------------------------
    @staticmethod
    def begin(name: str) -> "Pattern":
        return Pattern([PatternStage(name, "relaxed", [])])

    def next(self, name: str) -> "Pattern":
        """Strict contiguity: the very next event must match (Pattern.next)."""
        return Pattern(self.stages + [PatternStage(name, "strict", [])], self.within_ms)

    def followed_by(self, name: str) -> "Pattern":
        """Relaxed contiguity: non-matching events in between are skipped
        (Pattern.followedBy)."""
        return Pattern(self.stages + [PatternStage(name, "relaxed", [])], self.within_ms)

    def where(self, condition: Callable) -> "Pattern":
        last = self.stages[-1]
        new_last = dataclasses.replace(last, conditions=last.conditions + [condition])
        return Pattern(self.stages[:-1] + [new_last], self.within_ms)

    def times(self, n: int) -> "Pattern":
        last = dataclasses.replace(self.stages[-1], min_times=n, max_times=n)
        return Pattern(self.stages[:-1] + [last], self.within_ms)

    def one_or_more(self) -> "Pattern":
        last = dataclasses.replace(self.stages[-1], min_times=1, max_times=-1)
        return Pattern(self.stages[:-1] + [last], self.within_ms)

    def optional(self) -> "Pattern":
        last = dataclasses.replace(self.stages[-1], optional=True, min_times=0)
        return Pattern(self.stages[:-1] + [last], self.within_ms)

    def within(self, ms: int) -> "Pattern":
        return Pattern(list(self.stages), ms)

    def __repr__(self) -> str:
        return "Pattern(" + " -> ".join(s.name for s in self.stages) + ")"
