"""flink_tpu.chaos — deterministic fault injection + the scenario gate.

Two halves:

- :mod:`flink_tpu.chaos.plan` (re-exported here): the seeded FaultPlan /
  FaultRule model and the module-level HOOK the runtime's seams check.
  Stdlib-only — security/, checkpoint/ and runtime/ all import it, so it
  must sit below every layer it instruments.
- :mod:`flink_tpu.chaos.scenarios` (import explicitly, NOT re-exported):
  the named chaos scenario matrix (rpc-flap, dataplane-blip,
  torn-checkpoint, ...) that runs real jobs under injected compound
  faults and asserts exactly-once parity vs an undisturbed oracle. It
  imports the runtime, so pulling it in here would drag the whole
  runtime into every `import flink_tpu.security` — keep this package
  __init__ leaf-light.

See docs/robustness.md for the fault model and the scenario catalog.
"""

from flink_tpu.chaos.plan import (  # noqa: F401
    INJECTED_MARKER,
    FaultPlan,
    FaultRule,
    InjectedCrash,
    InjectedFault,
    active_plan,
    install_plan,
    uninstall_plan,
)
