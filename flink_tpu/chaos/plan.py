"""Deterministic fault injection: the chaos plane's core.

PDSP-Bench (PAPERS.md) argues a distributed SPE's resilience claims are
only as good as the fault matrix they survive. This module is the seeded,
declarative half of that matrix: a :class:`FaultPlan` holds rules
(scope + fault kind + trigger) and is installed as ONE module-level
callable (:data:`HOOK`). The runtime's five seams check it with a single
``is None`` comparison per call — the exact pattern of
``observability.device.enabled`` — so a disabled chaos plane costs one
attribute load on the hot path and nothing else.

Seams (each passes its scope + a site label):

=============  =========================================================
scope          where the hook fires
=============  =========================================================
``transport``  security/transport.py send_obj / recv_msg (any plane)
``rpc``        runtime/rpc.py gateway calls (site ``endpoint.method``)
               and server handlers (site ``server:endpoint.method``)
``dataplane``  runtime/dataplane.py OutputChannel.send (site channel id)
``storage``    checkpoint/storage.py save/load (site ``save:<id>`` /
               ``load:<handle>``)
``device``     runtime/executor.py window-step dispatch (site op uid)
``heartbeat``  runtime/cluster.py JM heartbeat intake (site tm id)
=============  =========================================================

Faults: ``error`` raises :class:`InjectedFault` (a ``ConnectionError``,
so every transient-fault path treats it like a real peer failure);
``crash`` raises :class:`InjectedCrash` (same, but hardening layers must
NOT absorb it — it models a process death, not a blip); ``delay`` sleeps;
``drop`` and ``torn`` return a directive the seam implements (drop a
frame/heartbeat, tear a checkpoint artifact); ``partition`` is ``drop``
that defaults to unlimited fires (pair it with ``nth``/``window_s`` to
bound the outage).

Every injected fault is labeled with :data:`INJECTED_MARKER` so failures
it causes are attributed ``injected: true`` in the PR-4 ExceptionHistory
(metrics/checkpoint_stats.py) on BOTH execution paths — the marker
survives the distributed path's repr()-over-RPC shipping.

This module imports nothing from the package (it is imported by
security/, checkpoint/ and runtime/ alike); configuration parsing
(`chaos.*`) imports flink_tpu.config lazily.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

#: substring stamped into every injected fault's message: ExceptionHistory
#: derives its `injected: true` attribution from it (the distributed path
#: ships failures as strings, so the label must survive repr()).
INJECTED_MARKER = "[chaos-injected"

_VALID_SCOPES = ("transport", "rpc", "dataplane", "storage", "device",
                 "heartbeat")
_VALID_FAULTS = ("error", "crash", "delay", "drop", "torn", "partition")

#: sentinel distinguishing "max_fires omitted" from an explicit value: the
#: partition default widens to unlimited fires, but an operator's explicit
#: max_fires=1 must stay exactly one dropped call
_UNSET_MAX_FIRES = object()


class InjectedFault(ConnectionError):
    """A chaos-injected transient fault. Subclasses ConnectionError so the
    seams' existing `except OSError` transient-fault paths (rpc retry,
    dataplane reconnect) treat it exactly like a real peer failure."""

    def __init__(self, label: str):
        super().__init__(f"{INJECTED_MARKER}:{label}] injected fault")
        self.label = label


class InjectedCrash(InjectedFault):
    """A chaos-injected hard failure (process-death model): hardening
    layers re-raise it instead of absorbing it, so it always reaches the
    failure-detection/restart machinery."""


@dataclasses.dataclass
class FaultRule:
    """One declarative injection rule.

    Trigger semantics: a call at (scope, site) matches when the rule's
    scope equals the call's scope and `match` is a substring of the site
    ("" matches everything). The rule fires on matching calls number
    `nth`, `nth`+1, ... (1-based; 0 = from the first), each with
    `probability`, inside `window_s` (seconds since plan install; None =
    always), at most `max_fires` times (None = unlimited)."""

    scope: str
    fault: str
    match: str = ""
    nth: int = 0
    probability: float = 1.0
    # default: 1 fire — except partition, which models an outage and
    # defaults to unlimited; an EXPLICIT max_fires always wins
    max_fires: Any = _UNSET_MAX_FIRES
    delay_s: float = 0.0
    window_s: Optional[Tuple[float, float]] = None
    label: str = ""

    def __post_init__(self):
        if self.scope not in _VALID_SCOPES:
            raise ValueError(f"unknown chaos scope {self.scope!r} "
                             f"(valid: {', '.join(_VALID_SCOPES)})")
        if self.fault not in _VALID_FAULTS:
            raise ValueError(f"unknown chaos fault {self.fault!r} "
                             f"(valid: {', '.join(_VALID_FAULTS)})")
        if self.max_fires is _UNSET_MAX_FIRES:
            self.max_fires = None if self.fault == "partition" else 1
        if not self.label:
            self.label = f"{self.scope}:{self.fault}:{self.match or '*'}"


class FaultPlan:
    """A seeded set of FaultRules with thread-safe trigger accounting.

    `act(scope, site)` is the single entry point the seams call (via
    :data:`HOOK`): it returns None (no fault — the overwhelmingly common
    case), returns a directive string ("drop" / "torn") the seam
    implements, sleeps for delay faults, or raises InjectedFault/
    InjectedCrash for error/crash faults. All randomness comes from the
    seeded RNG, so a plan over a deterministic workload replays the same
    fault sequence run after run."""

    def __init__(self, rules: List[FaultRule], seed: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.rules = list(rules)
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._matches = [0] * len(self.rules)
        self._fires = [0] * len(self.rules)
        self.fired: List[Tuple[str, str, str]] = []   # (label, scope, site)

    # -- the seam entry point ---------------------------------------------
    def act(self, scope: str, site: str) -> Optional[str]:
        directive = None
        delay = 0.0
        error: Optional[InjectedFault] = None
        with self._lock:
            now = self._clock() - self._t0
            for i, rule in enumerate(self.rules):
                if rule.scope != scope or rule.match not in site:
                    continue
                self._matches[i] += 1
                if rule.nth and self._matches[i] < rule.nth:
                    continue
                if rule.max_fires is not None and \
                        self._fires[i] >= rule.max_fires:
                    continue
                if rule.window_s is not None and not (
                        rule.window_s[0] <= now <= rule.window_s[1]):
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                self._fires[i] += 1
                self.fired.append((rule.label, scope, site))
                if rule.fault == "delay":
                    delay = max(delay, rule.delay_s)
                elif rule.fault == "crash":
                    error = InjectedCrash(rule.label)
                elif rule.fault == "error":
                    if error is None:       # crash outranks error
                        error = InjectedFault(rule.label)
                elif rule.fault == "torn":
                    directive = "torn"
                else:                       # drop / partition
                    directive = "drop"
        # side effects OUTSIDE the lock: a sleeping/raising rule must not
        # serialize every other seam's no-fault check behind it
        if delay > 0.0:
            time.sleep(delay)
        if error is not None:
            raise error
        return directive

    # -- accounting --------------------------------------------------------
    @property
    def total_fired(self) -> int:
        with self._lock:
            return len(self.fired)

    def report(self) -> Dict[str, Any]:
        """Per-rule match/fire counts + the fired-event log (label, scope,
        site) — what a scenario asserts its injection actually happened."""
        with self._lock:
            return {
                "seed": self.seed,
                "rules": [
                    {"label": r.label, "scope": r.scope, "fault": r.fault,
                     "matches": self._matches[i], "fires": self._fires[i]}
                    for i, r in enumerate(self.rules)
                ],
                "fired": list(self.fired),
            }

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_rules(rule_dicts: List[dict], seed: int = 0) -> "FaultPlan":
        rules = []
        for d in rule_dicts:
            d = dict(d)
            if "window_s" in d and d["window_s"] is not None:
                d["window_s"] = tuple(d["window_s"])
            rules.append(FaultRule(**d))
        return FaultPlan(rules, seed=seed)

    @staticmethod
    def from_config(config) -> Optional["FaultPlan"]:
        """Build from the `chaos.*` config group (None when disabled or no
        rules). `chaos.rules` is a JSON list of FaultRule field dicts."""
        from flink_tpu.config import ChaosOptions

        if not config.get(ChaosOptions.ENABLED):
            return None
        raw = config.get(ChaosOptions.RULES) or ""
        rule_dicts = json.loads(raw) if raw.strip() else []
        return FaultPlan.from_rules(rule_dicts,
                                    seed=config.get(ChaosOptions.SEED))


# ---------------------------------------------------------------------------
# the module-level hook the seams check (None = chaos off, zero work)
# ---------------------------------------------------------------------------

#: the installed plan's `act`, or None. Seams read this ONCE per call:
#: `hook = plan_module.HOOK; if hook is not None: hook(scope, site)`.
HOOK: Optional[Callable[[str, str], Optional[str]]] = None

_installed: Optional[FaultPlan] = None
_install_lock = threading.Lock()


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Install `plan` process-wide (exactly one plan at a time: stacked
    plans would make nth-counting meaningless)."""
    global HOOK, _installed
    with _install_lock:
        if _installed is not None:
            raise RuntimeError("a FaultPlan is already installed — "
                               "uninstall_plan() first")
        _installed = plan
        HOOK = plan.act
    return plan


def uninstall_plan() -> Optional[FaultPlan]:
    global HOOK, _installed
    with _install_lock:
        plan, _installed = _installed, None
        HOOK = None
    return plan


def active_plan() -> Optional[FaultPlan]:
    return _installed
