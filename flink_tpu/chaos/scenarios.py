"""Chaos scenario matrix: real jobs under injected compound faults.

Each named scenario runs a deterministic keyed-window job twice — once
undisturbed (the oracle) and once with a seeded FaultPlan installed — and
asserts (1) exactly-once result parity between the two runs, (2) the
expected recovery shape (restarts / rescales / reconnects / tolerated
checkpoint failures), and (3) that injected faults which caused failures
are attributed ``injected: true`` in the PR-4 ExceptionHistory. The matrix
covers BOTH execution paths: MiniCluster (torn-checkpoint,
storage-brownout, device-dispatch-error, chip-loss-sharded — the multichip
mesh losing a device mid-job and restarting at reduced mesh size,
cold-tier-read-error on the tiered state path, and
chip-loss-during-rebalance — a device dying while the job runs on a
skew-rebalanced key-group routing table) and the distributed JM+TM
cluster (rpc-flap, dataplane-blip, tm-crash-during-rescale,
heartbeat-partition).

`bench.py chaos_microbench` runs :func:`run_matrix` and emits
``chaos.{scenarios_passed, recovery_time_ms_p50, parity}`` into the bench
artifact; ``tests/test_bench_chaos.py`` is the tier-1 smoke gate over the
same matrix. See docs/robustness.md for the catalog and the config to
reproduce each scenario locally.

This module imports the runtime — import it explicitly
(``flink_tpu.chaos.scenarios``), never from ``flink_tpu.chaos``'s
package ``__init__`` (which must stay a stdlib-only leaf for the seams).
"""

from __future__ import annotations

import contextlib
import os
import shutil
import statistics
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.chaos.plan import FaultPlan
from flink_tpu.testing.harness import fault_injection


# ---------------------------------------------------------------------------
# shared workload: partition-consistent paced keyed source + oracle fold
# ---------------------------------------------------------------------------

class PacedKeyedSource:
    """Partition-consistent arrival-paced source for the distributed path:
    step s of shard i is the i-th slice of a seeded GLOBAL batch, so every
    parallelism (and every rescale) sees the same record set per step —
    replay after a checkpoint rewind stays exactly-once. `interval_s`
    paces steps in wall time so control-plane events (checkpoints,
    rescales, partitions) have room to land mid-job."""

    def __init__(self, steps: int, batch: int, n_keys: int,
                 interval_s: float, seed: int = 7):
        self.steps = steps
        self.batch = batch
        self.n_keys = n_keys
        self.interval_s = interval_s
        self.seed = seed

    def global_step(self, s: int):
        rng = np.random.default_rng(self.seed * 100_003 + s)
        keys = rng.integers(0, self.n_keys, self.batch).astype(np.int64)
        vals = np.ones(self.batch, dtype=np.float64)
        ts = (s * 500 + rng.integers(0, 500, self.batch)).astype(np.int64)
        return keys, vals, ts, s * 500 + 250

    def __call__(self, shard: int, num_shards: int):
        outer = self

        class _Paced(list):
            def __init__(self):
                super().__init__(range(outer.steps))
                self._anchor = None

            def __getitem__(self, s):
                if outer.interval_s > 0:
                    now = time.monotonic()
                    if self._anchor is None:
                        self._anchor = (now, s)
                    due = self._anchor[0] + (s - self._anchor[1]) * outer.interval_s
                    if due > now:
                        time.sleep(due - now)
                k, v, t, wm = outer.global_step(s)
                sl = slice(shard, None, num_shards)
                return k[sl], v[sl], t[sl], wm

        return _Paced()


def _dist_spec(source: PacedKeyedSource, name: str):
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.runtime.cluster import DistributedJobSpec

    return DistributedJobSpec(
        name=name, source_factory=source,
        assigner=TumblingEventTimeWindows.of(1000), aggregate="sum",
        max_parallelism=16,
    )


def _dist_expected(source: PacedKeyedSource) -> Dict[Tuple[int, int], float]:
    """Oracle: the global stream through one OracleWindowOperator."""
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.core.time import MAX_WATERMARK
    from flink_tpu.ops.aggregators import resolve
    from flink_tpu.runtime.oracle_window_operator import OracleWindowOperator

    op = OracleWindowOperator(
        TumblingEventTimeWindows.of(1000),
        resolve("sum").python_equivalent(), max_parallelism=16)
    for s in range(source.steps):
        keys, vals, ts, wm = source.global_step(s)
        for i in range(len(keys)):
            op.process_record(keys[i], float(vals[i]), int(ts[i]))
        op.process_watermark(wm)
    op.process_watermark(MAX_WATERMARK)
    return {(int(k), int(w.start)): float(r)
            for k, w, r, _ in op.drain_output()}


def _collect_dist(result: Optional[list]) -> Dict[Tuple[int, int], float]:
    return {(int(k), int(w[0])): float(r) for k, w, r, _ in (result or [])}


@contextlib.contextmanager
def _cluster(num_tms: int = 2, slots: int = 1,
             tm_ids: Optional[List[str]] = None, **jm_kwargs):
    from flink_tpu.runtime.cluster import (
        JobManagerEndpoint,
        TaskExecutorEndpoint,
    )
    from flink_tpu.runtime.rpc import RpcService

    chk = tempfile.mkdtemp(prefix="flink-tpu-chaos-")
    jm_defaults = dict(checkpoint_dir=chk, checkpoint_interval=0.2,
                       heartbeat_interval=0.2, heartbeat_timeout=10.0,
                       restart_delay=0.1)
    jm_defaults.update(jm_kwargs)
    svc_jm = RpcService()
    jm = JobManagerEndpoint(svc_jm, **jm_defaults)
    svcs = [svc_jm]
    tes = []
    for i in range(num_tms):
        svc = RpcService()
        svcs.append(svc)
        te = TaskExecutorEndpoint(
            svc, slots=slots, shipping_interval_ms=50,
            tm_id=tm_ids[i] if tm_ids else None)
        te.connect(svc_jm.address)
        tes.append(te)
    client = svc_jm.gateway(svc_jm.address, "jobmanager")
    try:
        yield client, jm, tes
    finally:
        for te in tes:
            te.stop()
        jm.stop()
        for svc in svcs:
            svc.stop()
        shutil.rmtree(chk, ignore_errors=True)


def _await_job(client, job_id: str, timeout_s: float = 90.0) -> dict:
    deadline = time.monotonic() + timeout_s
    st: dict = {}
    while time.monotonic() < deadline:
        st = client.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED", "CANCELED"):
            return st
        time.sleep(0.05)
    return st


def _await(predicate: Callable[[], bool], timeout_s: float) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------------------
# shared workload: MiniCluster keyed tumbling count job
# ---------------------------------------------------------------------------

def _run_mini_count_job(name: str, *, records: int = 2600, batch: int = 200,
                        chk_dir: Optional[str] = None, interval_ms: int = 1,
                        tolerable: int = 0, max_retained: int = 50,
                        fail_at_ts: Optional[int] = None,
                        timeout_s: float = 120.0,
                        extra_config: Optional[dict] = None):
    """One keyed tumbling-count DataStream job on the in-process path.
    Returns (client, sorted sink rows). `fail_at_ts` installs a one-shot
    REAL failure (a map raising at an event-time threshold — deterministic
    in event time, used to force the restart that exercises restore)."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import (
        CheckpointingOptions,
        Configuration,
        ExecutionOptions,
        RestartOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.utils.arrays import obj_array

    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, batch)
    # distinctive ring capacity (the PR-8 bench-gate pattern): superscan
    # executables are cached module-level by geometry, so sharing the
    # device-stats tests' K=1024 shape would pre-compile THEIR geometry
    # and hide the compile/recompile events those tests assert on
    config.set(ExecutionOptions.KEY_CAPACITY, 768)
    config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)
    if chk_dir is not None:
        config.set(CheckpointingOptions.INTERVAL_MS, interval_ms)
        config.set(CheckpointingOptions.DIRECTORY, chk_dir)
        config.set(CheckpointingOptions.MAX_RETAINED, max_retained)
        config.set(CheckpointingOptions.TOLERABLE_FAILED_CHECKPOINTS,
                   tolerable)
    for opt, val in (extra_config or {}).items():
        config.set(opt, val)

    state = {"failed": False}

    def maybe_fail(x):
        if fail_at_ts is not None and not state["failed"] \
                and x[2] >= fail_at_ts:
            state["failed"] = True
            raise RuntimeError(f"forced failure at ts {x[2]}")
        return x

    def gen(idx: np.ndarray) -> Batch:
        values = [(int(i % 7), 1.0, int(i * 10)) for i in idx]
        return Batch(obj_array(values), (idx * 10).astype(np.int64))

    env = StreamExecutionEnvironment(config)
    stream = env.from_source(
        DataGeneratorSource(gen, count=records, num_splits=8),
        watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
    )
    sink = CollectSink()
    (stream.map(maybe_fail)
           .key_by(lambda x: x[0])
           .window(TumblingEventTimeWindows.of(1000)).count()
           .sink_to(sink))
    client = env.execute_async(name)
    client.wait(timeout_s)
    return client, sorted((int(k), int(n)) for k, n in sink.results)


# ---------------------------------------------------------------------------
# the scenarios
# ---------------------------------------------------------------------------

def _result(name: str, path: str, plan: Optional[FaultPlan],
            problems: List[str], *, parity: Optional[bool] = None,
            restarts: int = 0, recovery_ms: Optional[float] = None,
            attributed: Optional[bool] = None,
            skipped: bool = False,
            doctor: Optional[str] = None) -> Dict[str, Any]:
    return {
        "name": name,
        "path": path,
        "passed": not problems,
        "detail": "; ".join(problems),
        "parity": bool(parity) if parity is not None else False,
        "restarts": int(restarts),
        "recovery_ms": recovery_ms,
        "injected_fired": plan.total_fired if plan is not None else 0,
        "attributed": attributed,
        # a scenario whose precondition the backend cannot meet (e.g. a
        # single-device host for the mesh scenario) — consumers must be
        # able to tell this from a pass, and the zero-injected-fires gate
        # must not read it as a seam losing its hook
        "skipped": bool(skipped),
        # the job doctor's post-run verdict (ISSUE-19), for the scenarios
        # that assert live diagnosis of the injected fault family
        "doctor": doctor,
    }


def _doctor_checks(problems: List[str], client, t0_ms: float,
                   expected_family: str = "recovery-restart") -> str:
    """Shared ISSUE-19 chaos assertions: the doctor's TOP diagnosis names
    the injected fault family, and at least one watchdog ``health.*``
    span landed inside the fault window [t0_ms, now]. Returns the
    verdict for the result dict."""
    doc = client.doctor_report()
    fams = [d["family"] for d in doc.get("diagnoses", [])]
    _check(problems, bool(fams) and fams[0] == expected_family,
           f"doctor top diagnosis {fams[:3]} != {expected_family}")
    _check(problems, doc.get("verdict") == expected_family,
           f"doctor verdict {doc.get('verdict')!r} != {expected_family}")
    log = getattr(client, "span_log", None)
    health = [s for s in (log.spans if log is not None else [])
              if s.scope == "health" and s.start_ts_ms >= t0_ms]
    _check(problems, bool(health),
           "no health.* watchdog span landed in the fault window")
    return str(doc.get("verdict"))


def _check(problems: List[str], ok: bool, what: str) -> bool:
    if not ok:
        problems.append(what)
    return ok


def scenario_torn_checkpoint() -> Dict[str, Any]:
    """Every checkpoint save from the 3rd onward writes a torn `_metadata`
    (the artifact fsync-before-rename exists to prevent); a later real
    failure forces a restore, which must SKIP the torn checkpoints and
    rewind to the last complete one instead of crash-looping. Pre-chaos
    runtime: the restart loop dies on a bare UnpicklingError and the job
    hangs RESTARTING forever."""
    problems: List[str] = []
    _oracle_client, expected = _run_mini_count_job("torn-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-torn-")
    try:
        with fault_injection(rules=[
            {"scope": "storage", "fault": "torn", "match": "save",
             "nth": 3, "max_fires": None},
        ]) as plan:
            client, results = _run_mini_count_job(
                "torn-checkpoint", chk_dir=chk,
                fail_at_ts=int(2600 * 10 * 0.7))
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "result parity broken")
    _check(problems, client.num_restarts == 1,
           f"expected 1 restart, saw {client.num_restarts}")
    _check(problems, plan.total_fired >= 1, "no torn save was injected")
    restored = (client.checkpoint_stats.last_restore or {}).get("checkpoint_id")
    _check(problems, restored == 2,
           f"restore did not skip the torn checkpoints (restored {restored}, "
           "expected 2 — the last complete one)")
    recs = client.exceptions.payload()["recoveries"]
    recovery_ms = recs[0]["downtime_ms"] if recs else None
    return _result("torn-checkpoint", "mini", plan, problems, parity=parity,
                   restarts=client.num_restarts, recovery_ms=recovery_ms)


def scenario_storage_brownout() -> Dict[str, Any]:
    """Three consecutive checkpoint saves fail (storage brownout). With
    execution.checkpointing.tolerable-failed-checkpoints=5 the job rides
    it out: FAILED stats records (with injected attribution in the cause),
    zero restarts, and the consecutive-failures gauge resets once storage
    heals. Pre-chaos runtime: the first failed save restarts the job."""
    problems: List[str] = []
    _oracle_client, expected = _run_mini_count_job("brownout-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-brownout-")
    try:
        with fault_injection(rules=[
            {"scope": "storage", "fault": "error", "match": "save",
             "nth": 2, "max_fires": 3},
        ]) as plan:
            client, results = _run_mini_count_job(
                "storage-brownout", chk_dir=chk, tolerable=5)
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "result parity broken")
    _check(problems, client.num_restarts == 0,
           f"brownout was not tolerated: {client.num_restarts} restart(s)")
    _check(problems, plan.total_fired == 3,
           f"expected 3 injected save failures, fired {plan.total_fired}")
    gauges = client.checkpoint_stats.gauge_values()
    _check(problems, gauges["numberOfFailedCheckpoints"] >= 3,
           "tolerated failures did not land FAILED stats records")
    _check(problems, gauges["consecutiveFailedCheckpoints"] == 0,
           "consecutive-failures gauge did not reset after storage healed")
    _check(problems, gauges["numberOfCompletedCheckpoints"] >= 1,
           "no checkpoint completed after the brownout")
    failed = client.checkpoint_stats.payload()["latest"]["failed"]
    attributed = bool(failed and "[chaos-injected" in
                      (failed.get("failure_cause") or ""))
    _check(problems, attributed,
           "FAILED record lost the injected-fault attribution")
    return _result("storage-brownout", "mini", plan, problems, parity=parity,
                   restarts=client.num_restarts, attributed=attributed)


def scenario_device_dispatch_error() -> Dict[str, Any]:
    """One injected error at the device dispatch boundary (the 6th window
    dispatch). The job must restart through the normal strategy, restore
    from the latest checkpoint, finish with exact results — and the
    ExceptionHistory entry must carry `injected: true` attribution."""
    problems: List[str] = []
    _oracle_client, expected = _run_mini_count_job("device-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-device-")
    try:
        with fault_injection(rules=[
            {"scope": "device", "fault": "error", "nth": 6},
        ]) as plan:
            client, results = _run_mini_count_job(
                "device-dispatch-error", chk_dir=chk)
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "result parity broken")
    _check(problems, client.num_restarts == 1,
           f"expected 1 restart, saw {client.num_restarts}")
    _check(problems, plan.total_fired == 1,
           f"expected 1 injected dispatch error, fired {plan.total_fired}")
    exc = client.exceptions.payload()
    entry = exc["entries"][0] if exc["entries"] else {}
    attributed = bool(entry.get("injected"))
    _check(problems, attributed,
           "injected dispatch error not attributed injected:true")
    recs = exc["recoveries"]
    recovery_ms = recs[0]["downtime_ms"] if recs else None
    _check(problems, bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
           "recovery timeline missing the rewound checkpoint")
    return _result("device-dispatch-error", "mini", plan, problems,
                   parity=parity, restarts=client.num_restarts,
                   recovery_ms=recovery_ms, attributed=attributed)


def scenario_latency_mode_restore() -> Dict[str, Any]:
    """Device dispatch error with LATENCY MODE ON (small superbatch rungs,
    in-flight ring depth 2): the fault lands while the ring can legally
    hold an unresolved dispatch. Checkpoint barriers must drain the ring
    before capture (exactly-once capture points unchanged), the restart
    must reset ring + controller, and the recovered job must finish at
    exact parity with a plain throughput-mode oracle — proving deep async
    dispatch never double-emits or drops a fired window across restore."""
    from flink_tpu.config import LatencyOptions, ObservabilityOptions

    problems: List[str] = []
    _oracle_client, expected = _run_mini_count_job("latency-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-latmode-")
    t0_ms = time.time() * 1000.0
    try:
        with fault_injection(rules=[
            {"scope": "device", "fault": "error", "nth": 6},
        ]) as plan:
            client, results = _run_mini_count_job(
                "latency-mode-restore", chk_dir=chk,
                extra_config={
                    # aggressive target so the controller leaves the full
                    # span and actually exercises small rungs + the ring
                    LatencyOptions.TARGET_MS: 1,
                    LatencyOptions.MAX_INFLIGHT: 2,
                    # history/doctor plane (ISSUE-19): tick fast enough
                    # that the short chaos job fills its rings, and opt
                    # the watchdog's p99 breach in at a floor every fired
                    # window crosses — the deterministic health.* span
                    ObservabilityOptions.HISTORY_INTERVAL_MS: 25,
                    ObservabilityOptions.DOCTOR_P99_BREACH_MS: 0.001,
                })
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "latency-mode parity vs throughput oracle broken")
    _check(problems, client.num_restarts == 1,
           f"expected 1 restart, saw {client.num_restarts}")
    _check(problems, plan.total_fired == 1,
           f"expected 1 injected dispatch error, fired {plan.total_fired}")
    exc = client.exceptions.payload()
    entry = exc["entries"][0] if exc["entries"] else {}
    attributed = bool(entry.get("injected"))
    _check(problems, attributed,
           "injected dispatch error not attributed injected:true")
    recs = exc["recoveries"]
    recovery_ms = recs[0]["downtime_ms"] if recs else None
    _check(problems, bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
           "recovery timeline missing the rewound checkpoint")
    # ISSUE-19: the doctor must attribute the run to the injected fault
    # family (the restart dominates) and the watchdog must have fired
    verdict = _doctor_checks(problems, client, t0_ms)
    return _result("latency-mode-restore", "mini", plan, problems,
                   parity=parity, restarts=client.num_restarts,
                   recovery_ms=recovery_ms, attributed=attributed,
                   doctor=verdict)


def _run_mini_join_job(name: str, *, records: int = 1200, batch: int = 100,
                       chk_dir: Optional[str] = None, interval_ms: int = 1,
                       timeout_s: float = 120.0):
    """One two-input keyed windowed JOIN job on the in-process path (the
    DeviceJoinRunner seam): two generator sources, tumbling event-time
    inner equi-join, rows collected. Returns (client, sorted rows)."""
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
    from flink_tpu.config import (
        CheckpointingOptions,
        Configuration,
        ExecutionOptions,
        RestartOptions,
    )
    from flink_tpu.connectors.sink import CollectSink
    from flink_tpu.connectors.source import Batch, DataGeneratorSource
    from flink_tpu.core.watermarks import WatermarkStrategy
    from flink_tpu.utils.arrays import obj_array

    config = Configuration()
    config.set(ExecutionOptions.BATCH_SIZE, batch)
    # distinctive ring capacity (the bench-gate pattern: never share
    # another test family's cached superscan geometry)
    config.set(ExecutionOptions.KEY_CAPACITY, 768)
    config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)
    # emission-latency plane, capture-eligible from the FIRST recorded
    # fire: the restart rebuilds the join runner with a fresh tracker, so
    # the first post-restore fire's stall interval starts at the tracker's
    # mid-restart birth — the EmissionStall span it emits must overlap the
    # recovery span for scenario_join_restore's stall-attribution check
    from flink_tpu.config import ObservabilityOptions

    config.set(ObservabilityOptions.EMISSION_LATENCY_OUTLIER_MIN_SAMPLES, 1)
    if chk_dir is not None:
        config.set(CheckpointingOptions.INTERVAL_MS, interval_ms)
        config.set(CheckpointingOptions.DIRECTORY, chk_dir)
        config.set(CheckpointingOptions.MAX_RETAINED, 50)

    def gen(side: str):
        def _gen(idx: np.ndarray) -> Batch:
            values = [(int(i % 7), f"{side}{int(i)}") for i in idx]
            return Batch(obj_array(values), (idx * 10).astype(np.int64))
        return _gen

    env = StreamExecutionEnvironment(config)
    wm = WatermarkStrategy.for_monotonous_timestamps()
    left = env.from_source(
        DataGeneratorSource(gen("l"), count=records), watermark_strategy=wm)
    right = env.from_source(
        DataGeneratorSource(gen("r"), count=records), watermark_strategy=wm)
    sink = CollectSink()
    (left.join(right)
         .where(lambda v: v[0]).equal_to(lambda v: v[0])
         .window(TumblingEventTimeWindows.of(1000))
         .apply(lambda a, b: (a[0], a[1], b[1]))
         .sink_to(sink))
    client = env.execute_async(name)
    client.wait(timeout_s)
    return client, sorted((k, l, r) for k, l, r in sink.results)


def scenario_join_restore() -> Dict[str, Any]:
    """One injected error at the device JOIN ingest boundary (the 6th ring
    ingest), mid-stream — while both sides hold live ring state inside
    unfired windows. The job must restart through the normal strategy,
    restore the bucket rings from the latest checkpoint (geometry first,
    then re-ingest), and finish with EXACT results vs an undisturbed run:
    no pair lost from the rings, none double-emitted from already-fired
    windows. The ExceptionHistory entry must carry `injected: true`."""
    problems: List[str] = []
    _oracle_client, expected = _run_mini_join_job("join-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-join-")
    try:
        with fault_injection(rules=[
            {"scope": "device", "fault": "error", "nth": 6},
        ]) as plan:
            client, results = _run_mini_join_job("join-restore",
                                                 chk_dir=chk)
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected and len(expected) > 0
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "join result parity broken")
    _check(problems, client.num_restarts == 1,
           f"expected 1 restart, saw {client.num_restarts}")
    _check(problems, plan.total_fired == 1,
           f"expected 1 injected ingest error, fired {plan.total_fired}")
    exc = client.exceptions.payload()
    entry = exc["entries"][0] if exc["entries"] else {}
    attributed = bool(entry.get("injected"))
    _check(problems, attributed,
           "injected join-ingest error not attributed injected:true")
    recs = exc["recoveries"]
    recovery_ms = recs[0]["downtime_ms"] if recs else None
    _check(problems,
           bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
           "recovery timeline missing the rewound checkpoint")
    # stall attribution (emission-latency plane): the post-restore fires
    # resolve late, and the tail outlier's stall interval — opened at the
    # rebuilt tracker's mid-restart birth — must be attributed to the
    # injected recovery span, not to checkpoints or compiles
    stalls = client.latency_report()["attribution"]
    stall_owners = stalls.get("attributed", {})
    _check(problems, stalls.get("outliers", 0) > 0,
           "no EmissionStall outlier captured across the restart")
    _check(problems,
           stall_owners.get("recovery.JobRestart", {}).get("count", 0) >= 1,
           "post-restore latency spike not attributed to recovery.JobRestart"
           f" (owners: {sorted(stall_owners)})")
    out = _result("join-restore", "mini", plan, problems,
                  parity=parity, restarts=client.num_restarts,
                  recovery_ms=recovery_ms, attributed=attributed)
    out["stall_owners"] = sorted(stall_owners)
    return out


def scenario_chip_loss_sharded() -> Dict[str, Any]:
    """Chip/host loss mid-job on the MULTICHIP sharded path: the same
    keyed job runs SPMD over the device mesh (parallel.mesh.enabled), and
    one injected error at the sharded dispatch boundary models a lost
    chip. The job must recover through the normal attributed restart path
    AT A REDUCED MESH SIZE (parallel.mesh.degrade-on-device-loss): the
    canonical [K, S] checkpoint re-shards over the surviving devices, and
    results stay exactly-once vs the single-chip oracle."""
    problems: List[str] = []
    import jax

    from flink_tpu.config import ParallelOptions

    n_devices = len(jax.devices())
    if n_devices < 2:
        # single-device backend: there is no mesh to lose a chip from.
        # Reported as a skip, not a silent pass of vacuous assertions.
        return _result("chip-loss-sharded", "mini", None, [],
                       parity=True, restarts=0, skipped=True)
    _oracle_client, expected = _run_mini_count_job("chip-loss-oracle")
    chk = tempfile.mkdtemp(prefix="flink-tpu-chiploss-")
    try:
        with fault_injection(rules=[
            {"scope": "device", "fault": "error", "nth": 6},
        ]) as plan:
            client, results = _run_mini_count_job(
                "chip-loss-sharded", chk_dir=chk,
                extra_config={ParallelOptions.MESH_ENABLED: True})
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    parity = results == expected
    _check(problems, client.status().value == "FINISHED",
           f"job ended {client.status().value}")
    _check(problems, parity, "result parity broken vs the single-chip oracle")
    _check(problems, client.num_restarts == 1,
           f"expected 1 restart, saw {client.num_restarts}")
    _check(problems, plan.total_fired == 1,
           f"expected 1 injected chip loss, fired {plan.total_fired}")
    # the mini job runs at KEY_CAPACITY=768, so the initial mesh is the
    # SAME clamp runner construction applies (single-sourced); the degrade
    # policy halves it on the attributed device loss
    from flink_tpu.parallel.mesh import usable_mesh_size

    initial = usable_mesh_size(0, n_devices, 768)
    final = client._runtime.mesh_devices()
    _check(problems, initial > 1,
           f"no usable mesh on this backend ({n_devices} devices)")
    _check(problems, final == max(1, initial // 2),
           f"restart did not reduce the mesh: {initial} -> {final} "
           f"(expected {max(1, initial // 2)})")
    exc = client.exceptions.payload()
    entry = exc["entries"][0] if exc["entries"] else {}
    attributed = bool(entry.get("injected"))
    _check(problems, attributed,
           "injected chip loss not attributed injected:true")
    recs = exc["recoveries"]
    recovery_ms = recs[0]["downtime_ms"] if recs else None
    _check(problems,
           bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
           "recovery timeline missing the rewound checkpoint")
    return _result("chip-loss-sharded", "mini", plan, problems,
                   parity=parity, restarts=client.num_restarts,
                   recovery_ms=recovery_ms, attributed=attributed)


def scenario_cold_tier_read_error() -> Dict[str, Any]:
    """One injected read error on the COLD TIER during a promotion
    (million-key state plane, state/tier_manager.py): the keyed job runs
    with a hot capacity far below its key cardinality, so every batch
    demotes/promotes rows through the cold store — the storage-scope rule
    errors the 60th promotion read. The job must restart through the
    normal attributed path, restore from the latest INCREMENTAL
    (changelog) checkpoint, and finish at exact parity with the untired
    oracle; the tier keeps evicting after recovery (resident keys stay
    bounded)."""
    problems: List[str] = []
    from flink_tpu.config import StateTierOptions

    def gen_rotating(num_keys: int, batch: int):
        # rotate each batch's key order so the batch-pinned working set
        # shifts: the vocabulary must evict the previous batch's
        # residents and re-admit (promote) them when they cycle back —
        # a fixed key order would pin one resident set forever and the
        # promotion seam under test would never fire
        def key_of(i: int) -> int:
            return int((i + (i // batch) * 17) % num_keys)
        return key_of

    key_of = gen_rotating(64, 200)

    def run(name: str, *, tiered: bool, chk: Optional[str] = None):
        from flink_tpu.api.datastream import StreamExecutionEnvironment
        from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.config import (
            CheckpointingOptions,
            Configuration,
            ExecutionOptions,
            RestartOptions,
        )
        from flink_tpu.connectors.sink import CollectSink
        from flink_tpu.connectors.source import Batch, DataGeneratorSource
        from flink_tpu.core.watermarks import WatermarkStrategy
        from flink_tpu.utils.arrays import obj_array

        config = Configuration()
        config.set(ExecutionOptions.BATCH_SIZE, 200)
        config.set(ExecutionOptions.KEY_CAPACITY, 768)
        config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)
        if tiered:
            config.set(StateTierOptions.TIER_ENABLED, True)
            config.set(StateTierOptions.HOT_KEY_CAPACITY, 16)
            config.set(StateTierOptions.CHANGELOG_ENABLED, True)
            # dirs under the checkpoint dir: every attempt of the job
            # shares one changelog/cold store, like a real deployment
            config.set(StateTierOptions.CHANGELOG_DIR,
                       os.path.join(chk, "changelog"))
            config.set(StateTierOptions.COLD_DIR,
                       os.path.join(chk, "cold"))
        if chk is not None:
            config.set(CheckpointingOptions.INTERVAL_MS, 1)
            config.set(CheckpointingOptions.DIRECTORY, chk)
            config.set(CheckpointingOptions.MAX_RETAINED, 50)

        def gen(idx: np.ndarray) -> Batch:
            values = [(key_of(int(i)), 1.0, int(i * 10)) for i in idx]
            return Batch(obj_array(values), (idx * 10).astype(np.int64))

        env = StreamExecutionEnvironment(config)
        stream = env.from_source(
            DataGeneratorSource(gen, count=2600, num_splits=1),
            watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
        )
        sink = CollectSink()
        (stream.key_by(lambda x: x[0])
               .window(TumblingEventTimeWindows.of(1000)).count()
               .sink_to(sink))
        client = env.execute_async(name)
        client.wait(120)
        return client, sorted((int(k), int(n)) for k, n in sink.results)

    _oracle_client, expected = run("cold-tier-oracle", tiered=False)
    chk = tempfile.mkdtemp(prefix="flink-tpu-coldtier-")
    try:
        with fault_injection(rules=[
            {"scope": "storage", "fault": "error",
             "match": "cold-tier:get", "nth": 60},
        ]) as plan:
            client, results = run("cold-tier-read-error", tiered=True,
                                  chk=chk)
        parity = results == expected
        _check(problems, client.status().value == "FINISHED",
               f"job ended {client.status().value}")
        _check(problems, parity, "result parity broken vs untired oracle")
        _check(problems, client.num_restarts == 1,
               f"expected 1 restart, saw {client.num_restarts}")
        _check(problems, plan.total_fired == 1,
               f"expected 1 injected cold read error, fired "
               f"{plan.total_fired}")
        exc = client.exceptions.payload()
        entry = exc["entries"][0] if exc["entries"] else {}
        attributed = bool(entry.get("injected"))
        _check(problems, attributed,
               "injected cold-tier error not attributed injected:true")
        recs = exc["recoveries"]
        recovery_ms = recs[0]["downtime_ms"] if recs else None
        _check(problems,
               bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
               "recovery timeline missing the rewound checkpoint")
        # the restored checkpoint must be the INCREMENTAL kind, and the
        # tier must still be bounded + churning after recovery
        tier = None
        for e in client._runtime.device_snapshot()["operators"].values():
            if e.get("tier"):
                tier = e["tier"]
        _check(problems, tier is not None, "tier payload missing")
        if tier is not None:
            _check(problems, bool(tier["changelogEnabled"]),
                   "checkpoints were not incremental (changelog off)")
            _check(problems, tier["residentKeys"] <= 16,
                   f"resident keys {tier['residentKeys']} exceed the cap")
            _check(problems, tier["evictions"] > 0 and tier["promotions"] > 0,
                   "no eviction/promotion churn — the seam under test "
                   "never exercised")
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    return _result("cold-tier-read-error", "mini", plan, problems,
                   parity=parity, restarts=client.num_restarts,
                   recovery_ms=recovery_ms, attributed=attributed)


def scenario_chip_loss_during_rebalance() -> Dict[str, Any]:
    """Chip loss against the SKEW-REBALANCED mesh (parallel.mesh.
    skew-rebalance): a zipf-shaped keyed job piles its hot key-groups
    onto device 0, the rebalancer remaps them across the mesh at a
    step-aligned boundary, and an injected device error then kills a chip
    while the job is running on the remapped routing table. The job must
    recover through the normal attributed restart path at a REDUCED mesh
    size, with the routing table rebuilt consistently with the rewound
    CANONICAL checkpoint (checkpoints are routing-independent [K, S] by
    construction — restore + a fresh table is exact for ANY placement),
    at parity with the undisturbed single-chip oracle."""
    problems: List[str] = []
    import jax

    from flink_tpu.config import ParallelOptions

    n_devices = len(jax.devices())
    if n_devices < 2:
        return _result("chip-loss-during-rebalance", "mini", None, [],
                       parity=True, restarts=0, skipped=True)

    NUM_KEYS = 512

    def keys_of(idx: np.ndarray) -> np.ndarray:
        # ~70% of the mass on 64 hot keys. The host-keyed path assigns
        # DENSE ids in arrival order, so the hot keys (seen first and
        # constantly) take the low dense ids — all in device 0's
        # contiguous range under the identity table, exactly the shape
        # the rebalancer exists to fix — while 64 of them spread over
        # enough key-groups that a balanced replan CAN fix it (a single
        # hot group is unsplittable by design)
        u = ((idx * 2654435761) % 1000) / 1000.0
        hot = (idx % 64) * 8
        cold = (idx * 40503) % NUM_KEYS
        return np.where(u < 0.7, hot, cold).astype(np.int64)

    def run(name: str, *, mesh: bool, chk: Optional[str] = None):
        from flink_tpu.api.datastream import StreamExecutionEnvironment
        from flink_tpu.api.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.config import (
            CheckpointingOptions,
            Configuration,
            ExecutionOptions,
            ObservabilityOptions,
            RestartOptions,
        )
        from flink_tpu.connectors.sink import CollectSink
        from flink_tpu.connectors.source import Batch, DataGeneratorSource
        from flink_tpu.core.watermarks import WatermarkStrategy

        config = Configuration()
        config.set(ExecutionOptions.BATCH_SIZE, 512)
        # history/doctor plane (ISSUE-19): fast rings + the watchdog's
        # opt-in p99 floor so a health.* span deterministically lands
        config.set(ObservabilityOptions.HISTORY_INTERVAL_MS, 25)
        config.set(ObservabilityOptions.DOCTOR_P99_BREACH_MS, 0.001)
        # distinctive ring capacity (the bench-gate pattern): these
        # executables must be this scenario's own
        config.set(ExecutionOptions.KEY_CAPACITY, NUM_KEYS)
        # dispatch every 4 steps so device state (and with it the skew
        # telemetry the rebalancer reads) materializes early in the run
        config.set(ExecutionOptions.SUPERBATCH_STEPS, 4)
        config.set(RestartOptions.INITIAL_BACKOFF_MS, 1)
        if mesh:
            config.set(ParallelOptions.MESH_ENABLED, True)
            config.set(ParallelOptions.MESH_SKEW_REBALANCE, True)
            config.set(ParallelOptions.MESH_LOCAL_COMBINE, True)
            config.set(ParallelOptions.MESH_REBALANCE_SKEW_THRESHOLD, 1.2)
            config.set(ParallelOptions.MESH_REBALANCE_INTERVAL_MS, 0)
        if chk is not None:
            config.set(CheckpointingOptions.INTERVAL_MS, 1)
            config.set(CheckpointingOptions.DIRECTORY, chk)
            config.set(CheckpointingOptions.MAX_RETAINED, 50)

        count = 40 * 512

        def gen(idx: np.ndarray) -> Batch:
            ts = (idx * 2).astype(np.int64)
            return Batch(keys_of(idx), ts)

        env = StreamExecutionEnvironment(config)
        stream = env.from_source(
            DataGeneratorSource(gen, count=count, num_splits=1),
            watermark_strategy=WatermarkStrategy.for_monotonous_timestamps(),
        )
        sink = CollectSink()
        (stream.key_by(lambda col: col, vectorized=True)
               .window(TumblingEventTimeWindows.of(1000)).count()
               .sink_to(sink))
        client = env.execute_async(name)
        client.wait(120)
        return client, sorted((int(k), int(n)) for k, n in sink.results)

    _oracle_client, expected = run("rebalance-oracle", mesh=False)
    chk = tempfile.mkdtemp(prefix="flink-tpu-rebal-")
    t0_ms = time.time() * 1000.0
    try:
        with fault_injection(rules=[
            # the 14th device dispatch lands after the first rebalance
            # (first dispatch at step 4, skew visible from step ~5, the
            # remapped table live within a couple of boundaries)
            {"scope": "device", "fault": "error", "nth": 14},
        ]) as plan:
            client, results = run("chip-loss-during-rebalance", mesh=True,
                                  chk=chk)
        parity = results == expected
        _check(problems, client.status().value == "FINISHED",
               f"job ended {client.status().value}")
        _check(problems, parity, "result parity broken vs the oracle")
        _check(problems, client.mesh_rebalances >= 1,
               "no skew rebalance completed before the injected loss — "
               "the scenario never reached the state under test")
        _check(problems, client.num_restarts == 1,
               f"expected 1 restart, saw {client.num_restarts}")
        _check(problems, plan.total_fired == 1,
               f"expected 1 injected chip loss, fired {plan.total_fired}")
        exc = client.exceptions.payload()
        entry = exc["entries"][0] if exc["entries"] else {}
        attributed = bool(entry.get("injected"))
        _check(problems, attributed,
               "injected chip loss not attributed injected:true")
        recs = [r for r in exc["recoveries"] if r.get("kind") == "restart"]
        recovery_ms = recs[0]["downtime_ms"] if recs else None
        _check(problems,
               bool(recs) and recs[0]["restored_checkpoint_id"] is not None,
               "recovery timeline missing the rewound checkpoint")
        # the degrade policy halves the mesh on the attributed device
        # loss; the rebuilt attempt's routing table must be live and
        # valid for the REDUCED size — a stale 8-device assignment
        # restored verbatim would have nowhere to place half its groups
        from flink_tpu.parallel.mesh import usable_mesh_size

        initial = usable_mesh_size(0, n_devices, NUM_KEYS)
        final = client._runtime.mesh_devices()
        _check(problems, final == max(1, initial // 2),
               f"restart did not reduce the mesh: {initial} -> {final}")
        version = client._runtime.mesh_routing_version()
        _check(problems, version is not None,
               "rebuilt attempt lost its routing table")
        # ISSUE-19: the doctor must name the injected fault family and
        # the watchdog must have emitted a health.* span in the window
        verdict = _doctor_checks(problems, client, t0_ms)
    finally:
        shutil.rmtree(chk, ignore_errors=True)
    return _result("chip-loss-during-rebalance", "mini", plan, problems,
                   parity=parity, restarts=client.num_restarts,
                   recovery_ms=recovery_ms, attributed=attributed,
                   doctor=verdict)


def scenario_rpc_flap() -> Dict[str, Any]:
    """Transient rpc-plane flap on idempotent control calls: the first two
    checkpoint-ack attempts and two heartbeat shipments fail with
    connection errors. The gateway retry (backoff + jitter + deadline)
    absorbs all of it: zero restarts, checkpoints complete, exact results.
    Pre-chaos runtime: the first failed ack kills the task and restarts
    the whole job."""
    problems: List[str] = []
    source = PacedKeyedSource(steps=40, batch=40, n_keys=9, interval_s=0.08)
    expected = _dist_expected(source)
    with _cluster(num_tms=2) as (client, _jm, _tes):
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error",
             "match": "jobmanager.ack_checkpoint", "nth": 1, "max_fires": 2},
            {"scope": "rpc", "fault": "error",
             "match": "jobmanager.heartbeat_tm", "nth": 6, "max_fires": 2},
        ]) as plan:
            job_id = client.submit_job(
                _dist_spec(source, "rpc-flap").to_bytes(), 2)
            st = _await_job(client, job_id)
            parity = _collect_dist(
                client.job_result(job_id) if st["status"] == "FINISHED"
                else None) == expected
            _check(problems, st["status"] == "FINISHED",
                   f"job ended {st['status']}: {st.get('failure')}")
            _check(problems, parity, "result parity broken")
            _check(problems, st["restarts"] == 0,
                   f"flap was not absorbed: {st['restarts']} restart(s)")
            _check(problems, bool(st["checkpoints"]),
                   "no checkpoint completed under the flap")
            _check(problems, plan.total_fired >= 3,
                   f"expected >=3 injected rpc faults, fired "
                   f"{plan.total_fired}")
            restarts = st["restarts"]
    return _result("rpc-flap", "distributed", plan, problems, parity=parity,
                   restarts=restarts)


def scenario_dataplane_blip() -> Dict[str, Any]:
    """One injected connection error on a keyed-exchange sender (shard 0 →
    shard 1). The sender must reconnect inside the bounded window, verify
    sequence continuity on the re-run open/credit negotiation, resend, and
    the job completes with zero restarts. Pre-chaos runtime: the error
    fails the task and restarts the job."""
    problems: List[str] = []
    source = PacedKeyedSource(steps=60, batch=40, n_keys=9, interval_s=0.02)
    expected = _dist_expected(source)
    with _cluster(num_tms=2) as (client, jm, _tes):
        with fault_injection(rules=[
            {"scope": "dataplane", "fault": "error", "match": "0->1",
             "nth": 5, "max_fires": 1},
        ]) as plan:
            job_id = client.submit_job(
                _dist_spec(source, "dataplane-blip").to_bytes(), 2)
            st = _await_job(client, job_id)
            parity = _collect_dist(
                client.job_result(job_id) if st["status"] == "FINISHED"
                else None) == expected
            _check(problems, st["status"] == "FINISHED",
                   f"job ended {st['status']}: {st.get('failure')}")
            _check(problems, parity, "result parity broken")
            _check(problems, st["restarts"] == 0,
                   f"blip was not absorbed: {st['restarts']} restart(s)")
            _check(problems, plan.total_fired == 1,
                   f"expected 1 injected send error, fired "
                   f"{plan.total_fired}")
            metrics = client.job_metrics(job_id)["job"]
            _check(problems,
                   metrics.get("job.numDataplaneReconnects", 0) >= 1,
                   "no dataplane reconnect was recorded")
            restarts = st["restarts"]
    return _result("dataplane-blip", "distributed", plan, problems,
                   parity=parity, restarts=restarts)


def scenario_tm_crash_during_rescale() -> Dict[str, Any]:
    """A deliberate live rescale 1→2 whose deploy onto the second TM fails
    as if the TM crashed mid-rescale. The rescale must degrade into a
    plain restart that lands the job back at a healthy parallelism, with
    exact results — and the degraded rescale must NOT stamp a completed
    rescale duration (the PR-6 outcome hygiene the chaos plane verifies)."""
    problems: List[str] = []
    source = PacedKeyedSource(steps=140, batch=40, n_keys=9, interval_s=0.05)
    expected = _dist_expected(source)
    with _cluster(num_tms=2) as (client, jm, _tes):
        with fault_injection(rules=[
            {"scope": "rpc", "fault": "error",
             "match": "taskexecutor.deploy_task", "nth": 3, "max_fires": 1},
        ]) as plan:
            job_id = client.submit_job(
                _dist_spec(source, "tm-crash-rescale").to_bytes(), 1)
            _check(problems,
                   _await(lambda: bool(
                       client.job_status(job_id)["checkpoints"]), 30.0),
                   "no checkpoint completed before the rescale")
            res = client.rescale_job(job_id, 2, "chaos-drill")
            _check(problems, res["accepted"],
                   f"rescale rejected: {res['detail']}")
            st = _await_job(client, job_id)
            parity = _collect_dist(
                client.job_result(job_id) if st["status"] == "FINISHED"
                else None) == expected
            _check(problems, st["status"] == "FINISHED",
                   f"job ended {st['status']}: {st.get('failure')}")
            _check(problems, parity, "result parity broken")
            _check(problems, st["rescales"] == 1,
                   f"expected 1 rescale, saw {st['rescales']}")
            _check(problems, plan.total_fired == 1,
                   f"expected 1 injected deploy failure, fired "
                   f"{plan.total_fired}")
            auto = client.job_autoscaler(job_id)
            _check(problems, float(auto["last_rescale_duration_ms"]) == 0.0,
                   "degraded rescale stamped a completed-rescale duration "
                   "(outcome hygiene broken)")
            exc = client.job_exceptions(job_id)
            kinds = [r["kind"] for r in exc["recoveries"]]
            _check(problems, "rescale" in kinds,
                   f"no rescale record in the recovery timeline: {kinds}")
            recs = [r for r in exc["recoveries"] if r["kind"] == "rescale"]
            recovery_ms = recs[0]["downtime_ms"] if recs else None
            restarts = st["restarts"]
    return _result("tm-crash-during-rescale", "distributed", plan, problems,
                   parity=parity, restarts=restarts, recovery_ms=recovery_ms)


def scenario_heartbeat_partition() -> Dict[str, Any]:
    """A one-way partition between one TM and the JM (its heartbeats are
    dropped for ~25 beats). The JM must declare the TM dead, fail over,
    adaptively rescale the job down onto the surviving TM from the latest
    checkpoint, and finish with exact results — with the TM loss
    attributed to the partitioned TM in the exception history."""
    problems: List[str] = []
    source = PacedKeyedSource(steps=160, batch=40, n_keys=9, interval_s=0.05)
    expected = _dist_expected(source)
    with _cluster(num_tms=2, tm_ids=["tm-chaos-a", "tm-chaos-b"],
                  heartbeat_timeout=1.2) as (client, jm, _tes):
        with fault_injection(rules=[
            {"scope": "heartbeat", "fault": "partition",
             "match": "tm-chaos-b", "nth": 30, "max_fires": 35},
        ]) as plan:
            job_id = client.submit_job(
                _dist_spec(source, "hb-partition").to_bytes(), 2)
            st = _await_job(client, job_id, timeout_s=120.0)
            parity = _collect_dist(
                client.job_result(job_id) if st["status"] == "FINISHED"
                else None) == expected
            _check(problems, st["status"] == "FINISHED",
                   f"job ended {st['status']}: {st.get('failure')}")
            _check(problems, parity, "result parity broken")
            _check(problems, st["restarts"] >= 1,
                   "partition did not trigger failover")
            _check(problems, plan.total_fired >= 5,
                   f"too few heartbeats dropped ({plan.total_fired})")
            exc = client.job_exceptions(job_id)
            attributed_entries = [
                e for e in exc["entries"]
                if e.get("task_manager") == "tm-chaos-b"
                and "heartbeat" in e["exception"]]
            _check(problems, bool(attributed_entries),
                   "TM loss not attributed to the partitioned TM")
            recs = exc["recoveries"]
            recovery_ms = (recs[0].get("downtime_ms") if recs else None)
            _check(problems, bool(recs) and recs[-1]["downtime_ms"] is not None,
                   "recovery timeline not closed after failover")
            restarts = st["restarts"]
    return _result("heartbeat-partition", "distributed", plan, problems,
                   parity=parity, restarts=restarts, recovery_ms=recovery_ms)


SCENARIOS: Dict[str, Callable[[], Dict[str, Any]]] = {
    "torn-checkpoint": scenario_torn_checkpoint,
    "storage-brownout": scenario_storage_brownout,
    "device-dispatch-error": scenario_device_dispatch_error,
    "latency-mode-restore": scenario_latency_mode_restore,
    "join-restore": scenario_join_restore,
    "chip-loss-sharded": scenario_chip_loss_sharded,
    "cold-tier-read-error": scenario_cold_tier_read_error,
    "chip-loss-during-rebalance": scenario_chip_loss_during_rebalance,
    "rpc-flap": scenario_rpc_flap,
    "dataplane-blip": scenario_dataplane_blip,
    "tm-crash-during-rescale": scenario_tm_crash_during_rescale,
    "heartbeat-partition": scenario_heartbeat_partition,
}


def run_scenario(name: str) -> Dict[str, Any]:
    try:
        return SCENARIOS[name]()
    except Exception as e:  # noqa: BLE001 — a crashed scenario is a failure,
        # not a crashed matrix: the remaining scenarios still run
        from flink_tpu.chaos.plan import active_plan, uninstall_plan

        if active_plan() is not None:   # fault_injection unwinds its own
            uninstall_plan()            # install; this guards partial setup
        return _result(name, "?", None, [f"scenario crashed: {e!r}"])


def run_matrix(names: Optional[List[str]] = None) -> Dict[str, Any]:
    """Run the (selected) scenario matrix and fold the bench summary:
    scenarios_passed/total, overall parity, and the p50 of the observed
    recovery times (fail → RUNNING downtime) across scenarios that
    recovered."""
    picked = names or list(SCENARIOS)
    results = [run_scenario(n) for n in picked]
    recoveries = [r["recovery_ms"] for r in results
                  if r["recovery_ms"] is not None]
    return {
        "scenarios": results,
        "scenarios_total": len(results),
        "scenarios_passed": sum(1 for r in results if r["passed"]),
        "parity": all(r["parity"] for r in results),
        "recovery_time_ms_p50": (round(statistics.median(recoveries), 3)
                                 if recoveries else None),
    }
