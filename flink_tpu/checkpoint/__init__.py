"""Checkpointing & recovery: step-aligned snapshots, storage, restart strategies."""

from flink_tpu.checkpoint.storage import (
    CheckpointStorage,
    FsCheckpointStorage,
    MemoryCheckpointStorage,
)
from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
from flink_tpu.checkpoint.restart import restart_strategy_from_config
