"""Checkpoint coordinator: step-aligned consistent snapshots.

The reference coordinates checkpoints with barriers injected at sources and
aligned across channels (CheckpointCoordinator.java:567 triggerCheckpoint →
barrier flow → per-operator snapshots → acks → completePendingCheckpoint
:1359 → notifyCheckpointComplete). In the stepped runtime, a "barrier" is
simply a step boundary: between two device steps the whole pipeline is
quiescent, so alignment is free and a checkpoint is:

  1. capture source positions (splits + reader offsets) and every stateful
     runner's snapshot (device state pulled to host),
  2. persist atomically to CheckpointStorage,
  3. on success, notifyCheckpointComplete → 2PC sinks commit their epoch
     (Committer.java:39 semantics).

Exactly-once = replayable source positions + state snapshot + transactional
sinks, identical contract to the reference (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from flink_tpu.chaos.plan import InjectedCrash
from flink_tpu.checkpoint.storage import CheckpointStorage, CorruptCheckpointError


class CheckpointFailuresExhaustedError(RuntimeError):
    """More consecutive checkpoint failures than
    execution.checkpointing.tolerable-failed-checkpoints allows — raised
    from trigger() so the job's restart strategy takes over (the
    CheckpointFailureManager escalation of the reference)."""


class CheckpointCoordinator:
    def __init__(
        self,
        storage: CheckpointStorage,
        interval_ms: int,
        max_retained: int = 3,
        clock: Callable[[], float] = time.monotonic,
        traces=None,
        stats=None,
        tolerable_failures: int = 0,
    ):
        self.storage = storage
        self.interval_s = interval_ms / 1000.0
        self.max_retained = max_retained
        self._clock = clock
        self._last_trigger = clock()
        self._next_id = 1
        self.num_completed = 0
        # execution.checkpointing.tolerable-failed-checkpoints: consecutive
        # capture/persist failures absorbed (FAILED stats record, job keeps
        # running) before trigger() escalates to the restart strategy
        self.tolerable_failures = tolerable_failures
        self._consecutive_failures = 0
        self._on_complete: List[Callable[[int], None]] = []
        self.traces = traces  # TraceRegistry; checkpoint lifecycle spans (O2)
        # CheckpointStatsTracker (metrics/checkpoint_stats.py): per-checkpoint
        # records + lifetime counters, fed here and read by REST/Prometheus.
        # Stats flow OUTWARD through this callback-shaped seam — the
        # checkpoint layer never reaches into the runtime (architecture lint).
        self.stats = stats
        # optional per-operator state-bytes provider (the runtime's
        # state_bytes() gauges), re-pointed at every attempt's JobRuntime
        self.state_bytes_fn: Optional[Callable[[], Dict[str, int]]] = None

    def register_on_complete(self, fn: Callable[[int], None]) -> None:
        self._on_complete.append(fn)

    def reset_failure_streak(self) -> None:
        """A new job attempt starts with its FULL failure tolerance: the
        coordinator outlives restarts (MiniCluster constructs it once),
        and carrying the exhausted streak over would make the very first
        isolated failure of the restarted attempt escalate again —
        hot-looping restarts until a checkpoint happens to complete."""
        self._consecutive_failures = 0

    def set_next_id(self, next_id: int) -> None:
        self._next_id = max(self._next_id, next_id)

    def due(self) -> bool:
        return self.interval_s > 0 and (self._clock() - self._last_trigger) >= self.interval_s

    def maybe_trigger(self, capture_fn: Callable[[], dict]) -> Optional[int]:
        if not self.due():
            return None
        return self.trigger(capture_fn)

    def trigger(self, capture_fn: Callable[[], dict]) -> Optional[int]:
        """Returns the completed checkpoint id, or None when a failure was
        TOLERATED (within tolerable_failures — the stats record is FAILED,
        the job keeps running, the next interval retries with a fresh id).
        Beyond tolerance the phase error is re-raised (chained into
        CheckpointFailuresExhaustedError) for the restart strategy."""
        cid = self._next_id
        span = self.traces.span("checkpointing", "Checkpoint") if self.traces else None
        if self.stats is not None:
            self.stats.report_pending(cid)
        # sync phase: pull device state to host + source positions (the
        # reference's synchronous snapshot part)
        cap_span = (self.traces.span("checkpointing", "CheckpointCapture")
                    if self.traces else None)
        t_cap = self._clock()
        try:
            data = capture_fn()
        except BaseException as e:  # noqa: BLE001 — record, close spans,
            return self._failed(cid, e, span, cap_span)  # tolerate/raise
        sync_ms = (self._clock() - t_cap) * 1000.0
        if cap_span is not None:
            self.traces.report(cap_span.set_attribute("checkpointId", cid).end())
        data["checkpoint_id"] = cid
        # async phase: persist to checkpoint storage. A failed persist must
        # not leak the open spans or leave the tracker PENDING forever —
        # the record flips to FAILED and the spans close with the cause.
        persist_span = (self.traces.span("checkpointing", "CheckpointPersist")
                        if self.traces else None)
        t_save = self._clock()
        try:
            self.storage.save(cid, data)
        except BaseException as e:  # noqa: BLE001
            return self._failed(cid, e, span, persist_span)
        async_ms = (self._clock() - t_save) * 1000.0
        self._consecutive_failures = 0   # tolerance counts CONSECUTIVE
        if persist_span is not None:
            self.traces.report(
                persist_span.set_attribute("checkpointId", cid).end())
        self._next_id += 1
        self._last_trigger = self._clock()
        self.num_completed += 1
        if self.stats is not None:
            per_op = None
            if self.state_bytes_fn is not None:
                try:
                    per_op = self.state_bytes_fn()
                except Exception:
                    per_op = None
            self.stats.report_completed(
                cid,
                sync_duration_ms=sync_ms,
                async_duration_ms=async_ms,
                state_size_bytes=getattr(self.storage, "last_save_bytes", None),
                operator_bytes=per_op,
            )
        for fn in self._on_complete:
            fn(cid)
        self._retain()
        if span is not None:
            self.traces.report(
                span.set_attribute("checkpointId", cid)
                .set_attribute("status", "COMPLETED").end())
        return cid

    def _failed(self, cid: int, exc: BaseException, span, phase_span) -> None:
        """A checkpoint phase raised: record FAILED, close the spans, then
        either TOLERATE (within tolerable_failures: bump the failed id so
        the retry never reuses it, restart the interval clock, return
        None) or re-raise for the restart strategy. Non-Exception
        BaseExceptions (KeyboardInterrupt, SystemExit) and InjectedCrash
        are NEVER tolerated: tolerance is for storage faults, not for
        interpreter shutdown or chaos process-death models."""
        self._abort(cid, exc, span, phase_span)
        if not isinstance(exc, Exception) or isinstance(exc, InjectedCrash):
            raise exc
        self._consecutive_failures += 1
        if self._consecutive_failures <= self.tolerable_failures:
            self._next_id = cid + 1
            self._last_trigger = self._clock()   # no hot-loop retriggering
            return None
        if self.tolerable_failures > 0:
            raise CheckpointFailuresExhaustedError(
                f"checkpoint {cid} failed; {self._consecutive_failures} "
                f"consecutive failures exceed tolerable-failed-checkpoints "
                f"{self.tolerable_failures}") from exc
        raise exc

    def _abort(self, cid: int, exc: BaseException, span, phase_span) -> None:
        """A checkpoint phase raised: flip the tracker record to FAILED and
        close the open spans with the failure attribute."""
        if self.stats is not None:
            self.stats.report_failed(cid, repr(exc))
        if phase_span is not None:
            self.traces.report(
                phase_span.set_attribute("checkpointId", cid)
                .set_attribute("status", "FAILED").end())
        if span is not None:
            self.traces.report(
                span.set_attribute("checkpointId", cid)
                .set_attribute("status", "FAILED")
                .set_attribute("failureCause", repr(exc)[:200]).end())

    def _retain(self) -> None:
        cps = self.storage.list_checkpoints()
        while len(cps) > self.max_retained:
            cid, _ = cps.pop(0)
            self.storage.discard(cid)

    def latest_snapshot(self) -> Optional[dict]:
        """Newest LOADABLE snapshot: a torn/corrupt checkpoint artifact
        (CorruptCheckpointError — e.g. truncated `_metadata` left by a
        crash or disk fault) is SKIPPED and the rewind continues to the
        previous complete checkpoint instead of crash-looping the restart
        path on an unreadable file. None when nothing loadable remains
        (the job replays from scratch — still exactly-once)."""
        for _cid, handle in reversed(self.storage.list_checkpoints()):
            try:
                return self.storage.load(handle)
            except CorruptCheckpointError:
                continue
        return None
