"""Checkpoint coordinator: step-aligned consistent snapshots.

The reference coordinates checkpoints with barriers injected at sources and
aligned across channels (CheckpointCoordinator.java:567 triggerCheckpoint →
barrier flow → per-operator snapshots → acks → completePendingCheckpoint
:1359 → notifyCheckpointComplete). In the stepped runtime, a "barrier" is
simply a step boundary: between two device steps the whole pipeline is
quiescent, so alignment is free and a checkpoint is:

  1. capture source positions (splits + reader offsets) and every stateful
     runner's snapshot (device state pulled to host),
  2. persist atomically to CheckpointStorage,
  3. on success, notifyCheckpointComplete → 2PC sinks commit their epoch
     (Committer.java:39 semantics).

Exactly-once = replayable source positions + state snapshot + transactional
sinks, identical contract to the reference (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from flink_tpu.checkpoint.storage import CheckpointStorage


class CheckpointCoordinator:
    def __init__(
        self,
        storage: CheckpointStorage,
        interval_ms: int,
        max_retained: int = 3,
        clock: Callable[[], float] = time.monotonic,
        traces=None,
    ):
        self.storage = storage
        self.interval_s = interval_ms / 1000.0
        self.max_retained = max_retained
        self._clock = clock
        self._last_trigger = clock()
        self._next_id = 1
        self.num_completed = 0
        self._on_complete: List[Callable[[int], None]] = []
        self.traces = traces  # TraceRegistry; checkpoint lifecycle spans (O2)

    def register_on_complete(self, fn: Callable[[int], None]) -> None:
        self._on_complete.append(fn)

    def set_next_id(self, next_id: int) -> None:
        self._next_id = max(self._next_id, next_id)

    def due(self) -> bool:
        return self.interval_s > 0 and (self._clock() - self._last_trigger) >= self.interval_s

    def maybe_trigger(self, capture_fn: Callable[[], dict]) -> Optional[int]:
        if not self.due():
            return None
        return self.trigger(capture_fn)

    def trigger(self, capture_fn: Callable[[], dict]) -> int:
        cid = self._next_id
        span = self.traces.span("checkpointing", "Checkpoint") if self.traces else None
        data = capture_fn()
        data["checkpoint_id"] = cid
        self.storage.save(cid, data)
        self._next_id += 1
        self._last_trigger = self._clock()
        self.num_completed += 1
        for fn in self._on_complete:
            fn(cid)
        self._retain()
        if span is not None:
            self.traces.report(span.set_attribute("checkpointId", cid).end())
        return cid

    def _retain(self) -> None:
        cps = self.storage.list_checkpoints()
        while len(cps) > self.max_retained:
            cid, _ = cps.pop(0)
            self.storage.discard(cid)

    def latest_snapshot(self) -> Optional[dict]:
        latest = self.storage.latest()
        if latest is None:
            return None
        _cid, handle = latest
        return self.storage.load(handle)
