"""Restart/backoff strategies (reference: runtime/executiongraph/failover/
ExponentialDelayRestartBackoffTimeStrategy.java, FixedDelay..., FailureRate...)."""

from __future__ import annotations

import time
from typing import Optional

from flink_tpu.config import Configuration, RestartOptions


class RestartStrategy:
    def next_delay_ms(self, attempt: int) -> Optional[float]:
        """Delay before restart `attempt` (1-based); None = give up."""
        raise NotImplementedError

    def record_success(self) -> None:
        pass


class NoRestartStrategy(RestartStrategy):
    def next_delay_ms(self, attempt: int) -> Optional[float]:
        return None


class FixedDelayRestartStrategy(RestartStrategy):
    def __init__(self, max_attempts: int, delay_ms: float):
        self.max_attempts = max_attempts
        self.delay_ms = delay_ms

    def next_delay_ms(self, attempt: int) -> Optional[float]:
        return self.delay_ms if attempt <= self.max_attempts else None


class ExponentialDelayRestartStrategy(RestartStrategy):
    def __init__(self, max_attempts: int, initial_ms: float, max_ms: float, multiplier: float):
        self.max_attempts = max_attempts
        self.initial_ms = initial_ms
        self.max_ms = max_ms
        self.multiplier = multiplier

    def next_delay_ms(self, attempt: int) -> Optional[float]:
        if attempt > self.max_attempts:
            return None
        return min(self.initial_ms * (self.multiplier ** (attempt - 1)), self.max_ms)


class FailureRateRestartStrategy(RestartStrategy):
    """Gives up when more than max_failures occur within interval_ms."""

    def __init__(self, max_failures: int, interval_ms: float, delay_ms: float,
                 clock=time.monotonic):
        self.max_failures = max_failures
        self.interval_s = interval_ms / 1000.0
        self.delay_ms = delay_ms
        self._clock = clock
        self._failures = []

    def next_delay_ms(self, attempt: int) -> Optional[float]:
        now = self._clock()
        self._failures = [t for t in self._failures if now - t <= self.interval_s]
        self._failures.append(now)
        if len(self._failures) > self.max_failures:
            return None
        return self.delay_ms


def restart_strategy_from_config(config: Configuration) -> RestartStrategy:
    kind = config.get(RestartOptions.STRATEGY)
    attempts = config.get(RestartOptions.MAX_ATTEMPTS)
    initial = config.get(RestartOptions.INITIAL_BACKOFF_MS)
    if kind == "none":
        return NoRestartStrategy()
    if kind == "fixed-delay":
        return FixedDelayRestartStrategy(attempts, initial)
    if kind == "failure-rate":
        return FailureRateRestartStrategy(attempts, 60_000, initial)
    return ExponentialDelayRestartStrategy(
        attempts,
        initial,
        config.get(RestartOptions.MAX_BACKOFF_MS),
        config.get(RestartOptions.BACKOFF_MULTIPLIER),
    )
