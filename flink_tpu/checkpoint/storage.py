"""Checkpoint storage (CheckpointStorage SPI analogue:
runtime/state/filesystem/FsCheckpointStorageAccess.java:43 and the JM-heap
MemoryBackendCheckpointStorageAccess).

A checkpoint is one dict (numpy arrays + plain data), written atomically
(temp file + rename) under <dir>/chk-<id>/; the `_metadata` name and
completed-marker protocol mirror the reference's checkpoint layout. Device
arrays must already be pulled to host by the snapshot capture."""

from __future__ import annotations

import os
import pickle
import re
import shutil
import tempfile
from typing import Dict, List, Optional, Tuple


class CheckpointStorage:
    # size of the most recent save()'s persisted artifact, reported to the
    # CheckpointStatsTracker (the handle knows its size in the reference's
    # StreamStateHandle.getStateSize; here the storage remembers the last
    # write — saves are serialized per coordinator)
    last_save_bytes: int = 0

    def save(self, checkpoint_id: int, data: dict) -> str:
        raise NotImplementedError

    def load(self, handle: str) -> dict:
        raise NotImplementedError

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        """Sorted (id, handle) of COMPLETE checkpoints."""
        raise NotImplementedError

    def latest(self) -> Optional[Tuple[int, str]]:
        cps = self.list_checkpoints()
        return cps[-1] if cps else None

    def discard(self, checkpoint_id: int) -> None:
        pass


class MemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._store: Dict[int, bytes] = {}

    def save(self, checkpoint_id: int, data: dict) -> str:
        blob = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
        self._store[checkpoint_id] = blob
        self.last_save_bytes = len(blob)
        return f"mem:{checkpoint_id}"

    def load(self, handle: str) -> dict:
        return pickle.loads(self._store[int(handle.split(":", 1)[1])])

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        return [(i, f"mem:{i}") for i in sorted(self._store)]

    def discard(self, checkpoint_id: int) -> None:
        self._store.pop(checkpoint_id, None)


class FsCheckpointStorage(CheckpointStorage):
    _DIR_RE = re.compile(r"^chk-(\d+)$")

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _chk_dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.directory, f"chk-{checkpoint_id}")

    def save(self, checkpoint_id: int, data: dict) -> str:
        chk = self._chk_dir(checkpoint_id)
        os.makedirs(chk, exist_ok=True)
        final = os.path.join(chk, "_metadata")
        fd, tmp = tempfile.mkstemp(dir=chk, prefix=".inprogress-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, final)  # atomic completion marker
            self.last_save_bytes = os.path.getsize(final)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return final

    def load(self, handle: str) -> dict:
        with open(handle, "rb") as f:
            return pickle.load(f)

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = self._DIR_RE.match(name)
            if not m:
                continue
            meta = os.path.join(self.directory, name, "_metadata")
            if os.path.exists(meta):
                out.append((int(m.group(1)), meta))
        return sorted(out)

    def discard(self, checkpoint_id: int) -> None:
        shutil.rmtree(self._chk_dir(checkpoint_id), ignore_errors=True)
