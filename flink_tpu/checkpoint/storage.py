"""Checkpoint storage (CheckpointStorage SPI analogue:
runtime/state/filesystem/FsCheckpointStorageAccess.java:43 and the JM-heap
MemoryBackendCheckpointStorageAccess).

A checkpoint is one dict (numpy arrays + plain data), written atomically
(temp file + fsync + rename + parent-dir fsync) under <dir>/chk-<id>/; the
`_metadata` name and completed-marker protocol mirror the reference's
checkpoint layout. Device arrays must already be pulled to host by the
snapshot capture.

Durability contract (chaos-plane hardening): `save` fsyncs the temp file
BEFORE the rename and the parent directory AFTER it, so a crash can leave
either the previous checkpoint or the new one — never a torn `_metadata`
that looks complete. `load` wraps every missing/torn-artifact failure in
the typed :class:`CorruptCheckpointError`, so restore paths can skip a
damaged checkpoint and rewind to the previous complete one instead of
crash-looping on a bare ``UnpicklingError``."""

from __future__ import annotations

import os
import pickle
import re
import shutil
import tempfile
from typing import Dict, List, Optional, Tuple

from flink_tpu.chaos import plan as _chaos


class CorruptCheckpointError(Exception):
    """A checkpoint artifact is missing or unreadable (torn/truncated
    `_metadata`, deleted chk dir, evicted in-memory handle). Typed so
    restore can distinguish "this checkpoint is damaged — rewind further"
    from a programming error."""

    def __init__(self, handle: str, cause: BaseException):
        super().__init__(f"checkpoint artifact {handle!r} is missing or "
                         f"corrupt: {cause!r}")
        self.handle = handle
        self.__cause__ = cause


class CheckpointStorage:
    # size of the most recent save()'s persisted artifact, reported to the
    # CheckpointStatsTracker (the handle knows its size in the reference's
    # StreamStateHandle.getStateSize; here the storage remembers the last
    # write — saves are serialized per coordinator)
    last_save_bytes: int = 0

    def save(self, checkpoint_id: int, data: dict) -> str:
        raise NotImplementedError

    def load(self, handle: str) -> dict:
        """Raises CorruptCheckpointError when the artifact is missing or
        unreadable."""
        raise NotImplementedError

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        """Sorted (id, handle) of COMPLETE checkpoints."""
        raise NotImplementedError

    def latest(self) -> Optional[Tuple[int, str]]:
        cps = self.list_checkpoints()
        return cps[-1] if cps else None

    def discard(self, checkpoint_id: int) -> None:
        pass


def _chaos_storage(site: str) -> Optional[str]:
    """The chaos plane's storage seam: one is-None check when chaos is
    off; `error` raises here, `torn` returns the directive for save()."""
    hook = _chaos.HOOK
    if hook is not None:
        return hook("storage", site)
    return None


class MemoryCheckpointStorage(CheckpointStorage):
    def __init__(self):
        self._store: Dict[int, bytes] = {}

    def save(self, checkpoint_id: int, data: dict) -> str:
        directive = _chaos_storage(f"save:{checkpoint_id}")
        blob = pickle.dumps(data, protocol=pickle.HIGHEST_PROTOCOL)
        if directive == "torn":
            blob = blob[: max(len(blob) // 3, 1)]
        self._store[checkpoint_id] = blob
        self.last_save_bytes = len(blob)
        return f"mem:{checkpoint_id}"

    def load(self, handle: str) -> dict:
        _chaos_storage(f"load:{handle}")
        try:
            return pickle.loads(self._store[int(handle.split(":", 1)[1])])
        except CorruptCheckpointError:
            raise
        except Exception as e:  # noqa: BLE001 — missing key, torn pickle
            raise CorruptCheckpointError(handle, e) from e

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        return [(i, f"mem:{i}") for i in sorted(self._store)]

    def discard(self, checkpoint_id: int) -> None:
        self._store.pop(checkpoint_id, None)


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss
    (no-op on platforms that cannot open directories)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass                       # e.g. network fs refusing dir fsync
    finally:
        os.close(fd)


class FsCheckpointStorage(CheckpointStorage):
    _DIR_RE = re.compile(r"^chk-(\d+)$")

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _chk_dir(self, checkpoint_id: int) -> str:
        return os.path.join(self.directory, f"chk-{checkpoint_id}")

    def save(self, checkpoint_id: int, data: dict) -> str:
        directive = _chaos_storage(f"save:{checkpoint_id}")
        chk = self._chk_dir(checkpoint_id)
        os.makedirs(chk, exist_ok=True)
        final = os.path.join(chk, "_metadata")
        fd, tmp = tempfile.mkstemp(dir=chk, prefix=".inprogress-")
        try:
            with os.fdopen(fd, "wb") as f:
                pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                # fsync BEFORE the rename: without it the rename can land
                # while the data blocks are still dirty, and a crash leaves
                # a torn file behind the atomic-completion marker
                os.fsync(f.fileno())
            os.replace(tmp, final)  # atomic completion marker
            # fsync the parent so the rename itself is durable
            _fsync_dir(chk)
            if directive == "torn":
                # chaos: simulate the torn-metadata outcome fsync exists to
                # prevent (disk corruption / pre-hardening crash artifact)
                size = os.path.getsize(final)
                with open(final, "r+b") as f:
                    f.truncate(max(size // 3, 1))
            self.last_save_bytes = os.path.getsize(final)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return final

    def load(self, handle: str) -> dict:
        _chaos_storage(f"load:{handle}")
        try:
            with open(handle, "rb") as f:
                return pickle.load(f)
        except CorruptCheckpointError:
            raise
        except Exception as e:  # noqa: BLE001 — missing dir/file, torn or
            # truncated pickle (EOFError/UnpicklingError), unreadable bytes
            raise CorruptCheckpointError(handle, e) from e

    def list_checkpoints(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = self._DIR_RE.match(name)
            if not m:
                continue
            meta = os.path.join(self.directory, name, "_metadata")
            if os.path.exists(meta):
                out.append((int(m.group(1)), meta))
        return sorted(out)

    def discard(self, checkpoint_id: int) -> None:
        shutil.rmtree(self._chk_dir(checkpoint_id), ignore_errors=True)
