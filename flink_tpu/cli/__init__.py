"""Command-line client (reference: flink-clients CliFrontend.java:93)."""

from flink_tpu.cli.frontend import main
