from flink_tpu.cli.frontend import main

raise SystemExit(main())
