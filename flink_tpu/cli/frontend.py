"""CLI frontend: run / list / info / cancel / savepoint / metrics.

Capability parity with the reference client CLI (CliFrontend.java:93 actions
run, list, cancel, savepoint, info) against the REST endpoint
(runtime/rest.py), or embedded (local MiniCluster + blocking run) when no
--address is given — the LocalExecutor vs RestClusterClient split
(flink-clients LocalExecutor.java:49 / RestClusterClient.java:173).

Usage:
  python -m flink_tpu.cli run <script.py> [--entry main] [--address URL] [--detached]
  python -m flink_tpu.cli list --address URL
  python -m flink_tpu.cli info <job_id> --address URL
  python -m flink_tpu.cli cancel <job_id> --address URL
  python -m flink_tpu.cli savepoint <job_id> <target_dir> --address URL
  python -m flink_tpu.cli metrics <job_id> --address URL
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _http(method: str, url: str, body: dict = None) -> dict:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=60) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


def _run_local(script: str, entry: str, detached: bool) -> int:
    import importlib.util

    spec = importlib.util.spec_from_file_location("flink_tpu_cli_app", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    fn = getattr(mod, entry)
    result = fn()
    from flink_tpu.api.datastream import StreamExecutionEnvironment
    from flink_tpu.runtime.minicluster import JobClient

    if isinstance(result, StreamExecutionEnvironment):
        result = result.execute_async()
    if not isinstance(result, JobClient):
        print(f"{entry}() must return JobClient or StreamExecutionEnvironment", file=sys.stderr)
        return 2
    print(f"Job submitted: {result.job_id}")
    if not detached:
        status = result.wait()
        print(f"Job {result.job_id} finished with status {status.value}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="flink-tpu")
    sub = parser.add_subparsers(dest="action", required=True)

    p_run = sub.add_parser("run", help="run a pipeline script")
    p_run.add_argument("script")
    p_run.add_argument("--entry", default="main")
    p_run.add_argument("--address", default=None, help="REST endpoint; omit for embedded run")
    p_run.add_argument("--detached", action="store_true")

    for name in ("list",):
        p = sub.add_parser(name)
        p.add_argument("--address", required=True)

    for name in ("info", "cancel", "metrics"):
        p = sub.add_parser(name)
        p.add_argument("job_id")
        p.add_argument("--address", required=True)

    p_sp = sub.add_parser("savepoint")
    p_sp.add_argument("job_id")
    p_sp.add_argument("target_dir")
    p_sp.add_argument("--address", required=True)

    args = parser.parse_args(argv)

    if args.action == "run":
        if args.address is None:
            return _run_local(args.script, args.entry, args.detached)
        out = _http("POST", f"{args.address}/jars/run", {"module": args.script, "entry": args.entry})
        print(json.dumps(out))
        return 0 if "jobid" in out else 1
    if args.action == "list":
        print(json.dumps(_http("GET", f"{args.address}/jobs"), indent=2))
        return 0
    if args.action == "info":
        print(json.dumps(_http("GET", f"{args.address}/jobs/{args.job_id}"), indent=2))
        return 0
    if args.action == "metrics":
        print(json.dumps(_http("GET", f"{args.address}/jobs/{args.job_id}/metrics"), indent=2))
        return 0
    if args.action == "cancel":
        print(json.dumps(_http("POST", f"{args.address}/jobs/{args.job_id}/cancel")))
        return 0
    if args.action == "savepoint":
        out = _http(
            "POST",
            f"{args.address}/jobs/{args.job_id}/savepoints",
            {"target-directory": args.target_dir},
        )
        print(json.dumps(out))
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
