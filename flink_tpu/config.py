"""Typed, layered configuration system.

Capability parity with the reference's config stack
(flink-core .../configuration/Configuration.java:53, ConfigOption.java:41,
ConfigOptions builder): typed options with defaults, fallback (deprecated)
keys, descriptions for doc generation, and layered resolution
(defaults < file < dynamic properties < per-job overrides).

Unlike the reference there is no string-serialization round-trip through
flink-conf.yaml key=value pairs as the primary representation — options hold
native Python values, and YAML/env layers are parsed at the edge.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Generic, Iterable, List, Optional, TypeVar

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class ConfigOption(Generic[T]):
    """A typed configuration key with a default value.

    Mirrors ConfigOption.java:41 (key, default, fallback keys, description).
    """

    key: str
    default: T = None  # type: ignore[assignment]
    type: type = object
    description: str = ""
    fallback_keys: tuple = ()

    def with_description(self, description: str) -> "ConfigOption[T]":
        return dataclasses.replace(self, description=description)

    def with_fallback_keys(self, *keys: str) -> "ConfigOption[T]":
        return dataclasses.replace(self, fallback_keys=tuple(keys))

    def __hash__(self) -> int:
        return hash(self.key)


class ConfigOptions:
    """Builder entry point, mirroring ConfigOptions.key(...).xType().defaultValue()."""

    @staticmethod
    def key(key: str) -> "_OptionBuilder":
        return _OptionBuilder(key)


class _OptionBuilder:
    def __init__(self, key: str):
        self._key = key

    def int_type(self) -> "_TypedBuilder[int]":
        return _TypedBuilder(self._key, int)

    def float_type(self) -> "_TypedBuilder[float]":
        return _TypedBuilder(self._key, float)

    def bool_type(self) -> "_TypedBuilder[bool]":
        return _TypedBuilder(self._key, bool)

    def string_type(self) -> "_TypedBuilder[str]":
        return _TypedBuilder(self._key, str)

    def duration_ms_type(self) -> "_TypedBuilder[int]":
        """Durations are plain ints in milliseconds (event-time native unit)."""
        return _TypedBuilder(self._key, int)

    def list_type(self) -> "_TypedBuilder[list]":
        return _TypedBuilder(self._key, list)


class _TypedBuilder(Generic[T]):
    def __init__(self, key: str, typ: type):
        self._key = key
        self._type = typ

    def default_value(self, value: T) -> ConfigOption[T]:
        return ConfigOption(key=self._key, default=value, type=self._type)

    def no_default_value(self) -> ConfigOption[Optional[T]]:
        return ConfigOption(key=self._key, default=None, type=self._type)


def _coerce(value: Any, typ: type) -> Any:
    if typ is object or value is None or isinstance(value, typ):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "on")
        return bool(value)
    if typ in (int, float, str):
        return typ(value)
    if typ is list and isinstance(value, str):
        return [v.strip() for v in value.split(";") if v.strip()]
    return value


class Configuration:
    """Layered key/value store resolved against typed ConfigOptions.

    Mirrors Configuration.java:53: get/set by option, fallback-key
    resolution, cloning, and merge (`add_all`).
    """

    def __init__(self, data: Optional[Dict[str, Any]] = None):
        self._data: Dict[str, Any] = dict(data or {})

    # -- typed access -----------------------------------------------------
    def get(self, option: ConfigOption[T], override_default: Optional[T] = None) -> T:
        if option.key in self._data:
            return _coerce(self._data[option.key], option.type)
        for fk in option.fallback_keys:
            if fk in self._data:
                return _coerce(self._data[fk], option.type)
        return override_default if override_default is not None else option.default

    def set(self, option: ConfigOption[T], value: T) -> "Configuration":
        self._data[option.key] = value
        return self

    def contains(self, option: ConfigOption) -> bool:
        return option.key in self._data or any(fk in self._data for fk in option.fallback_keys)

    def remove(self, option: ConfigOption) -> bool:
        return self._data.pop(option.key, _SENTINEL) is not _SENTINEL

    # -- raw access -------------------------------------------------------
    def set_string(self, key: str, value: Any) -> "Configuration":
        self._data[key] = value
        return self

    def get_string(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def key_set(self) -> Iterable[str]:
        return self._data.keys()

    # -- layering ---------------------------------------------------------
    def add_all(self, other: "Configuration") -> "Configuration":
        self._data.update(other._data)
        return self

    def clone(self) -> "Configuration":
        return Configuration(dict(self._data))

    def to_dict(self) -> Dict[str, Any]:
        return dict(self._data)

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "Configuration":
        return Configuration(dict(data))

    @staticmethod
    def from_env(prefix: str = "FLINK_TPU_") -> "Configuration":
        """Dynamic-property layer from environment variables.

        FLINK_TPU_FOO_BAR=1 -> key "foo.bar" (reference: dynamic -D props)."""
        data = {}
        for k, v in os.environ.items():
            if k.startswith(prefix):
                data[k[len(prefix):].lower().replace("_", ".")] = v
        return Configuration(data)

    @staticmethod
    def load(path: str) -> "Configuration":
        """File layer. JSON or simple `key: value` YAML subset (no deps)."""
        with open(path) as f:
            text = f.read()
        try:
            return Configuration(json.loads(text))
        except json.JSONDecodeError:
            data: Dict[str, Any] = {}
            for line in text.splitlines():
                line = line.strip()
                if not line or line.startswith("#") or ":" not in line:
                    continue
                key, _, val = line.partition(":")
                data[key.strip()] = val.strip()
            return Configuration(data)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Configuration) and self._data == other._data

    def __repr__(self) -> str:
        return f"Configuration({self._data!r})"


_SENTINEL = object()


# ---------------------------------------------------------------------------
# Core option holders (reference: CheckpointingOptions, TaskManagerOptions, …)
# ---------------------------------------------------------------------------

class PipelineOptions:
    NAME = ConfigOptions.key("pipeline.name").string_type().default_value("flink-tpu-job")
    MAX_PARALLELISM = ConfigOptions.key("pipeline.max-parallelism").int_type().default_value(128)
    PARALLELISM = ConfigOptions.key("pipeline.parallelism").int_type().default_value(1)
    AUTO_WATERMARK_INTERVAL = (
        ConfigOptions.key("pipeline.auto-watermark-interval").duration_ms_type().default_value(200)
    )
    OBJECT_REUSE = ConfigOptions.key("pipeline.object-reuse").bool_type().default_value(True)


class ExecutionOptions:
    BATCH_SIZE = (
        ConfigOptions.key("execution.step.batch-size").int_type().default_value(65536)
    ).with_description("Records per device step; the TPU analogue of buffer timeout batching.")
    BATCH_TIMEOUT_MS = (
        ConfigOptions.key("execution.step.batch-timeout-ms").duration_ms_type().default_value(10)
    ).with_description("Max time to wait filling a step batch (BufferDebloater analogue).")
    RUNTIME_MODE = ConfigOptions.key("execution.runtime-mode").string_type().default_value("STREAMING")
    KEY_CAPACITY = (
        ConfigOptions.key("execution.state.key-capacity").int_type().default_value(1 << 16)
    ).with_description("Initial per-shard distinct-key capacity of device columnar state; grows by doubling.")
    FUSED_WINDOWS = (
        ConfigOptions.key("execution.window.fused").bool_type().default_value(True)
    ).with_description(
        "Select the fused superscan window operator (one compiled dispatch per "
        "superbatch) for eligible event-time window aggregates; fall back to the "
        "per-step device operator when off or ineligible."
    )
    DEVICE_SESSIONS = (
        ConfigOptions.key("execution.window.device-sessions").bool_type().default_value(True)
    ).with_description(
        "Select the device session-window operator (per-slice fragments + "
        "vectorized gap-merge) for eligible event-time session aggregates. "
        "Its late contract drops records whose standalone session is already "
        "expired, which matches the merging oracle only while watermark "
        "out-of-orderness stays below the session gap — set to false to force "
        "the per-record oracle for streams with larger disorder."
    )
    CHAIN_FUSION = (
        ConfigOptions.key("execution.chain.device-fusion").bool_type().default_value(True)
    ).with_description(
        "Compile eligible operator chains (traceable map/filter/map_ts "
        "prologue + traceable keyBy/value extraction + device-eligible "
        "event-time window aggregate) into ONE jitted multi-step device "
        "program with device-resident intermediates (whole-graph fusion, "
        "docs/fusion.md). Requires execution.window.fused; UDFs must be "
        "declared traceable=True at the API. Off, or for any ineligible "
        "chain, execution keeps the per-step ChainRunner + window operator "
        "path with identical results."
    )
    SHARED_PARTIALS = (
        ConfigOptions.key("execution.window.shared-partials").bool_type().default_value(True)
    ).with_description(
        "Compile correlated window aggregates — sibling window() steps over "
        "the same keyed stream with the same aggregate (e.g. 1m/5m/1h "
        "dashboards) — into ONE shared-partial device program: slices are "
        "computed once at the gcd granule and every member window derives "
        "its result from the shared partials at fire time (Factor Windows, "
        "docs/windows.md). Requires execution.chain.device-fusion "
        "eligibility for every sibling; a perf switch, never a semantics "
        "switch — off, or for any ineligible group, each window keeps its "
        "own fused program with identical results."
    )
    SUPERBATCH_STEPS = (
        ConfigOptions.key("execution.window.superbatch-steps").int_type().default_value(32)
    ).with_description(
        "Steps buffered per fused-window dispatch; higher amortizes host-device "
        "round trips, lower reduces emission latency."
    )
    COLUMNAR_OUTPUT = (
        ConfigOptions.key("execution.window.columnar-output").bool_type().default_value(False)
    ).with_description(
        "Emit window fires as packed (window, key-ids, values) rows instead of "
        "one (key, value) row per key — emission cost becomes independent of "
        "key cardinality (high-cardinality analytics sinks)."
    )
    MINI_BATCH_GROUP_AGG = (
        ConfigOptions.key("execution.group-agg.mini-batch").bool_type().default_value(True)
    ).with_description(
        "Continuous (non-windowed) aggregates emit one changelog transition "
        "per distinct key per step batch (the reference's "
        "table.exec.mini-batch optimization) instead of per input record. "
        "Set to false for the exact per-record emission sequence."
    )
    DEVICE_JOINS = (
        ConfigOptions.key("execution.join.device-enabled").bool_type().default_value(True)
    ).with_description(
        "Select the device join operator (per-key time-bucketed rings in "
        "HBM + segment-wise cross-match, docs/joins.md) for eligible "
        "event-time window equi-joins. Ineligible shapes — processing "
        "time, session windows, coGroup, outer joins — keep the host "
        "operator with an attributed reason (joinFallbackReason); off "
        "forces the host operator for every join. A perf switch, never a "
        "semantics switch."
    )
    JOIN_BUCKET_CAPACITY = (
        ConfigOptions.key("execution.join.bucket-capacity").int_type().default_value(128)
    ).with_description(
        "Record slots per (key, time bucket, side) in the device join "
        "ring. A (key, bucket) side that exceeds it mid-stream degrades "
        "that operator to the host join — state carried over, "
        "exactly-once preserved, reason recorded — for the rest of the "
        "job. Size it to the worst per-key burst inside one bucket "
        "granule (gcd of window size and slide)."
    )
    JOIN_RING_SLACK = (
        ConfigOptions.key("execution.join.ring-slack-buckets").int_type().default_value(64)
    ).with_description(
        "Extra ring depth beyond one window's buckets: how many bucket "
        "granules event time may run ahead of the purge horizon before "
        "the ring would wrap onto a live bucket (which degrades to the "
        "host join, never corrupts). Raise for very disordered streams."
    )
    DEVICE_GROUP_AGG = (
        ConfigOptions.key("execution.group-agg.device").bool_type().default_value(False)
    ).with_description(
        "Keep continuous-aggregation accumulators in device HBM with one "
        "scatter-add dispatch per batch (COUNT/SUM/AVG only; MIN/MAX need "
        "the host retractable multiset). COUNT columns are int32 on device "
        "and stay exact up to int32 range — a key whose count ever exceeds "
        "~2.1e9 increments (2**31 - 1) wraps where the host path's Python "
        "ints would not; SUM/AVG accumulate in float32, so very large "
        "running sums round where the host path's float64 would not."
    )


class LatencyOptions:
    """Latency-mode execution (execution.latency.*, docs/latency.md): the
    fused window path trades superbatch amortization for emission latency
    under an explicit target. Default off — throughput mode is untouched
    and the flag is a perf switch, never a semantics switch."""

    TARGET_MS = (
        ConfigOptions.key("execution.latency.target-ms").int_type().default_value(0)
    ).with_description(
        "Emission-latency target for the fused window path; 0 (default) "
        "keeps pure throughput mode, byte-identical dispatch behavior. "
        "When set, a scheduler-side controller adapts the staged "
        "superbatch depth between execution.latency.floor-steps and the "
        "full execution.window.superbatch-steps span from windowed "
        "arrival-rate estimates, snapping to a pow2 rung ladder so "
        "adaptation never compiles more than the ladder's shapes."
    )
    MAX_INFLIGHT = (
        ConfigOptions.key("execution.latency.max-inflight-dispatches")
        .int_type().default_value(1)
    ).with_description(
        "Bound of the fused operator's in-flight dispatch ring: how many "
        "enqueued superbatch dispatches may await deferred resolution at "
        "once. 1 (default) is the classic one-outstanding-dispatch "
        "behavior; deeper rings let dispatch N+1 stage and launch while "
        "N's emissions resolve. Watermark/checkpoint barriers drain the "
        "whole ring in dispatch order, so capture points and emission "
        "order never change."
    )
    FLOOR_STEPS = (
        ConfigOptions.key("execution.latency.floor-steps").int_type().default_value(2)
    ).with_description(
        "Smallest superbatch depth (steps per dispatch) the latency "
        "controller may select — the bottom rung of the pow2 ladder. "
        "Bounds the buffering delay to roughly floor-steps batch fill "
        "times at the cost of per-dispatch amortization."
    )
    READBACK_STEPS = (
        ConfigOptions.key("execution.latency.readback-steps").int_type().default_value(8)
    ).with_description(
        "Streaming fire readback: split each dispatch into step groups of "
        "this size so fired-window rows start their async device-to-host "
        "copy per group instead of waiting for span completion (results "
        "still resolve through the same DeferredEmissions layout, bit "
        "identical). 0 keeps span-granular readback. Single-chip XLA path "
        "only; the mesh and pallas paths keep span-granular readback."
    )
    MIN_DWELL_MS = (
        ConfigOptions.key("execution.latency.min-dwell-ms")
        .duration_ms_type().default_value(500)
    ).with_description(
        "Minimum time the latency controller holds a chosen rung before a "
        "non-escalation move (the autoscaler's stabilization-interval "
        "discipline applied to batch geometry). Rate spikes that demand "
        "the full span escalate immediately regardless."
    )
    HYSTERESIS_PCT = (
        ConfigOptions.key("execution.latency.hysteresis-pct").int_type().default_value(25)
    ).with_description(
        "Dead band around each rung boundary, in percent of the boundary "
        "rate: the windowed arrival rate must overshoot a boundary by "
        "this margin before the controller changes rung, so a rate "
        "oscillating across a boundary never flaps geometries."
    )


class TableOptions:
    """The Table/SQL front door (flink_tpu/table + flink_tpu/planner)."""

    DEVICE_FUSION = (
        ConfigOptions.key("table.device-fusion").bool_type().default_value(True)
    ).with_description(
        "Route SQL statements through the table-plan planner "
        "(flink_tpu/planner): supported windowed GROUP BY aggregates lower "
        "onto the SAME fused StepGraph path a hand-built DataStream job "
        "takes (one compiled superscan via whole-graph fusion, "
        "docs/sql.md) — requires declared field_types (columnar or "
        "row-mode registration; the GROUP BY key must be a declared "
        "int). Statements outside the fused core (joins, "
        "session windows, UDF/ML projections, untyped row tables, ...) "
        "fall back to the interpreted table path with an attributed "
        "reason; set to false to force the interpreted path for every "
        "statement. A perf switch, never a semantics switch: both paths "
        "produce identical rows."
    )


class ExchangeOptions:
    """The cross-host dataplane exchange (runtime/dataplane.py — the DCN
    counterpart of the reference's Netty shuffle and its
    taskmanager.network.* options). Wire format and credit cadence are
    negotiated per connection, so mixed-version clusters interoperate:
    a peer that does not speak the binary wire downgrades that channel to
    the legacy pickled frames transparently."""

    WIRE_FORMAT = (
        ConfigOptions.key("exchange.wire-format").string_type().default_value("binary")
    ).with_description(
        "Encoding for record batches on cross-host exchange channels. "
        "'binary' (default) is the zero-copy columnar wire "
        "(flink_tpu/security/wire.py): little-endian header + raw array "
        "buffers sent with scatter-gather I/O and incrementally MACed — no "
        "serialization copy for contiguous numeric columns. 'pickle' forces "
        "the legacy restricted-pickle frames everywhere (debugging / "
        "downgrade). Control frames always stay on the pickle codec."
    )
    CREDIT_BATCH = (
        ConfigOptions.key("exchange.credit-batch").int_type().default_value(0)
    ).with_description(
        "Coalescing grain for credit grants: the receiver banks freed ring "
        "slots and sends one credit frame per this many slots instead of "
        "one per consumed batch. 0 (default) derives capacity/4 from the "
        "ring capacity; 1 restores per-batch grants. Backpressure blocking "
        "semantics are unchanged — only the control-frame rate drops."
    )
    DEBLOAT_ENABLED = (
        ConfigOptions.key("exchange.debloat.enabled").bool_type().default_value(True)
    ).with_description(
        "Adaptive batch sizing on stage-boundary senders (BufferDebloater "
        "analogue): each sender EMAs its observed send throughput and "
        "splits outgoing batches larger than throughput x target latency, "
        "so a backpressured channel carries smaller batches (lower queueing "
        "latency) while a fast channel passes batches through whole."
    )
    DEBLOAT_TARGET_LATENCY_MS = (
        ConfigOptions.key("exchange.debloat.target-latency-ms")
        .duration_ms_type().default_value(200)
    ).with_description(
        "Target per-batch transit latency the debloater sizes toward "
        "(taskmanager.network.memory.buffer-debloat.target analogue)."
    )
    RECONNECT_WINDOW_MS = (
        ConfigOptions.key("exchange.reconnect.window-ms")
        .duration_ms_type().default_value(5000)
    ).with_description(
        "Bounded window a keyed-exchange sender spends re-dialing a peer "
        "after a transient dataplane failure (connection reset, injected "
        "blip) before escalating to the normal task-failure/restart path. "
        "The reconnect re-runs the open/credit negotiation and resumes "
        "only when the receiver's next expected sequence number matches "
        "the sender's (no frame was lost); a real loss, or a peer whose "
        "TaskManager stopped heartbeating, fails over immediately. 0 "
        "disables reconnection (every dataplane error restarts the job, "
        "the pre-chaos behavior)."
    )


class CheckpointingOptions:
    INTERVAL_MS = ConfigOptions.key("execution.checkpointing.interval").duration_ms_type().default_value(0)
    DIRECTORY = ConfigOptions.key("execution.checkpointing.dir").string_type().no_default_value()
    MODE = ConfigOptions.key("execution.checkpointing.mode").string_type().default_value("EXACTLY_ONCE")
    MAX_RETAINED = ConfigOptions.key("execution.checkpointing.max-retained").int_type().default_value(3)
    TOLERABLE_FAILED_CHECKPOINTS = (
        ConfigOptions.key("execution.checkpointing.tolerable-failed-checkpoints")
        .int_type().default_value(0)
    ).with_description(
        "Consecutive checkpoint failures (capture or persist) the job "
        "tolerates before the failure restarts it (Flink's "
        "execution.checkpointing.tolerable-failed-checkpoints). Each "
        "tolerated failure still lands a FAILED record in the checkpoint "
        "stats ring and bumps the consecutiveFailedCheckpoints gauge; a "
        "completed checkpoint resets the count. 0 (default, reference "
        "parity) restarts on the first failure. Savepoint declines never "
        "count — an outrun savepoint retries by design."
    )


class DeviceOptions:
    MESH_AXIS_NAME = ConfigOptions.key("device.mesh.axis-name").string_type().default_value("shards")
    NUM_SHARDS = (
        ConfigOptions.key("device.mesh.num-shards").int_type().default_value(0)
    ).with_description("0 = use all visible devices.")
    DONATE_STATE = ConfigOptions.key("device.donate-state").bool_type().default_value(True)


class ParallelOptions:
    """Multichip SPMD execution over the local device mesh
    (flink_tpu/parallel/, docs/multichip.md): eligible fused keyed window
    jobs shard their window-state columns by key-group over the mesh and
    run the keyBy shuffle as an on-device all-to-all inside the compiled
    superscan — the mesh is a slot resource of the process, not a cluster
    of tasks."""

    MESH_ENABLED = (
        ConfigOptions.key("parallel.mesh.enabled").bool_type().default_value(False)
    ).with_description(
        "Run eligible fused keyed window jobs SPMD over the local device "
        "mesh: window-state columns shard by contiguous key-group range, "
        "each device transforms and keys its slice of the ingest batch, and "
        "ONE all-to-all collective per step routes records to their "
        "key-range owners (the keyBy shuffle over ICI instead of a host "
        "dataplane hop). Results are byte-identical to the single-chip "
        "fused path; snapshots stay canonical [K, S], so checkpoints "
        "restore across any mesh size. Requires >= 2 visible devices and a "
        "jax build with shard_map; otherwise execution silently stays "
        "single-chip."
    )
    MESH_DEVICES = (
        ConfigOptions.key("parallel.mesh.devices").int_type().default_value(0)
    ).with_description(
        "Devices in the job's mesh. 0 (default) uses every visible device. "
        "Clamped to the visible device count, then rounded down to the "
        "largest divisor of the key capacity so contiguous key ranges "
        "divide evenly across shards."
    )
    MESH_DEGRADE_ON_DEVICE_LOSS = (
        ConfigOptions.key("parallel.mesh.degrade-on-device-loss")
        .bool_type().default_value(True)
    ).with_description(
        "When a mesh job fails with a device-plane error (a lost chip/host "
        "surfaces as an XLA runtime error; chaos drills inject the same "
        "shape at the dispatch seam), the restart rebuilds the job at a "
        "REDUCED mesh size instead of retrying the dead geometry forever: "
        "the latest checkpoint's canonical [K, S] snapshot re-shards over "
        "the surviving devices (halving per restart, floor 1 = single-chip). "
        "Off restarts at the configured size every time."
    )
    MESH_AUTOSCALE = (
        ConfigOptions.key("parallel.mesh.autoscale").bool_type().default_value(True)
    ).with_description(
        "Let the autoscaler (autoscaler.enabled) treat MESH SIZE as the "
        "parallelism axis it rescales on the in-process path: scaling "
        "decisions execute as a live checkpoint-rewind + key-group re-shard "
        "onto a different device count at a step boundary, exactly-once. "
        "Off keeps the autoscaler observe-only for mesh jobs."
    )
    MESH_LOCAL_COMBINE = (
        ConfigOptions.key("parallel.mesh.local-combine")
        .bool_type().default_value(False)
    ).with_description(
        "Map-side combiner for the mesh keyBy exchange: each shard "
        "segment-reduces its slice of every step by (key, rel-slice) "
        "BEFORE the all-to-all, so what crosses the interconnect is at "
        "most one partial per (source shard, key, slice) instead of the "
        "key's full tuple mass — under zipf-skewed traffic a hot key "
        "costs n_shards partials per slice, not its record count. "
        "Applies to decomposable builtin aggregates (count/sum/min/max, "
        "mean as its two add-scatter fields); non-decomposable aggregates "
        "transparently keep the route-raw exchange. A performance switch, "
        "never a semantics switch: partial pre-reduction uses the same "
        "scatter combiners the ring ingest applies — counts and integer/"
        "min/max fields are bit-exact; float-ADD fields are reassociated "
        "(partials per source shard, then a cross-shard fold), which like "
        "any parallel pre-aggregation is bit-exact for integer-valued "
        "payloads and may differ in final ulps otherwise."
    )
    MESH_SKEW_REBALANCE = (
        ConfigOptions.key("parallel.mesh.skew-rebalance")
        .bool_type().default_value(False)
    ).with_description(
        "Skew-aware key-group routing on the in-process mesh path: the "
        "static owner function (key-group -> contiguous device range) "
        "becomes a device-resident routing table, and a rebalancer in the "
        "scheduler watches the key-skew telemetry (keyGroupLoad / "
        "meshLoadSkew) and remaps the hottest key-groups across devices "
        "at a step-aligned boundary through the mesh-rescale "
        "capture/restore machinery — exactly-once, with checkpoints "
        "staying canonical [K, S] (routing is placement, never "
        "semantics). Off keeps the static contiguous owner function."
    )
    MESH_KEY_GROUPS = (
        ConfigOptions.key("parallel.mesh.key-groups").int_type()
        .default_value(0)
    ).with_description(
        "Key-group count of the skew-rebalance routing table (0 = auto: "
        "up to 128, rounded to a multiple of the mesh size that divides "
        "the key capacity). More groups = finer-grained rebalancing at "
        "a slightly larger replicated routing table."
    )
    MESH_REBALANCE_SKEW_THRESHOLD = (
        ConfigOptions.key("parallel.mesh.rebalance.skew-threshold")
        .float_type().default_value(1.25)
    ).with_description(
        "meshLoadSkew (max/mean per-device resident records) above which "
        "the skew rebalancer considers remapping key-groups. A rebalance "
        "only triggers when the replanned assignment also improves the "
        "predicted skew by at least ~10% — a single unsplittable hot "
        "group never causes rebuild churn."
    )
    MESH_REBALANCE_INTERVAL_MS = (
        ConfigOptions.key("parallel.mesh.rebalance.interval-ms")
        .int_type().default_value(1000)
    ).with_description(
        "Minimum milliseconds between skew-rebalancer decisions (and "
        "between a completed rebalance and the next check). 0 decides on "
        "every step boundary — test/bench cadence, not production."
    )


class StateTierOptions:
    """The million-key state plane (flink_tpu/state/vocab.py +
    tier_manager.py, docs/state.md): a dynamic key vocabulary bounds the
    RESIDENT key set to a fixed number of HBM ring rows, demotes cold
    keys' rows through the host/disk cold tier, promotes them on
    re-admission, and (optionally) journals every interval's delta so
    checkpoints are incremental."""

    TIER_ENABLED = (
        ConfigOptions.key("state.tier.enabled").bool_type().default_value(False)
    ).with_description(
        "Decouple key cardinality from HBM key capacity on the host-keyed "
        "fused window path: at most state.tier.hot-key-capacity keys stay "
        "RESIDENT as device ring rows (admission/eviction per "
        "state.tier.eviction-policy), every other key's state lives in the "
        "cold tier (host memtable + spilled runs) and aggregates there; "
        "window fires merge both tiers exactly. Results are identical to "
        "the untired path — tiering is placement, never semantics. Applies "
        "to FusedWindowOperator jobs with host key dictionaries (traced "
        "dense-keyed chains keep their fixed device keying) and forces "
        "row-mode emission (dense ids are recycled, so packed columnar "
        "output would alias keys)."
    )
    HOT_KEY_CAPACITY = (
        ConfigOptions.key("state.tier.hot-key-capacity").int_type()
        .default_value(1 << 13)
    ).with_description(
        "Resident dense-id capacity of the hot tier (HBM [K, S] ring "
        "rows) when state.tier.enabled. Power of two recommended (the "
        "mesh clamp divides it across shards). Unlike "
        "execution.state.key-capacity this never grows: the vocabulary "
        "evicts instead."
    )
    EVICTION_POLICY = (
        ConfigOptions.key("state.tier.eviction-policy").string_type()
        .default_value("lru")
    ).with_description(
        "Victim selection when the hot tier is full: 'lru' (least "
        "recently used, frequency tiebreak) or 'lfu' (least frequently "
        "used, recency tiebreak). Keys touched by the batch being routed "
        "are pinned either way."
    )
    ADMISSION_MIN_COUNT = (
        ConfigOptions.key("state.tier.admission-min-count").int_type()
        .default_value(1)
    ).with_description(
        "Doorkeeper: while the hot tier is full, a key must be sighted "
        "this many times before it may evict a resident (tiny-LFU "
        "admission; 1 = always admit). Raise under heavy-tailed traffic "
        "so one-touch keys aggregate cold instead of churning hot rows."
    )
    COLD_DIR = (
        ConfigOptions.key("state.tier.cold-dir").string_type().default_value("")
    ).with_description(
        "Directory for the cold tier's spilled runs (and the native LSM "
        "store when available). Empty = a fresh temp directory per "
        "operator instance; set it to survive in-place restarts."
    )
    CHANGELOG_ENABLED = (
        ConfigOptions.key("state.changelog.enabled").bool_type()
        .default_value(False)
    ).with_description(
        "Incremental checkpoints for tiered operators: cold-tier "
        "mutations and vocabulary ops journal into an append-only segment "
        "log as they happen, and each checkpoint appends ONE entry with "
        "the interval-touched device cells — a checkpoint handle is "
        "(materialized base, log offset), so checkpoint bytes scale with "
        "the per-interval delta, not the full [K, S] state. Restore "
        "replays the log over the base host-side into the canonical full "
        "snapshot (mesh-size independent). Requires state.tier.enabled."
    )
    CHANGELOG_DIR = (
        ConfigOptions.key("state.changelog.dir").string_type().default_value("")
    ).with_description(
        "Directory for changelog segments and materialized bases. Empty = "
        "a fresh temp directory per operator instance (restores still "
        "find the original via the checkpoint handle's absolute path); "
        "set it so every attempt of a job shares one log."
    )
    CHANGELOG_MATERIALIZE_INTERVAL = (
        ConfigOptions.key("state.changelog.materialize-interval").int_type()
        .default_value(8)
    ).with_description(
        "Checkpoints between full materializations: every Nth checkpoint "
        "folds the log into a fresh base file and truncates segments "
        "below the oldest retained base. Lower = faster restores, higher "
        "= smaller amortized checkpoint cost."
    )
    CHANGELOG_RETAINED_BASES = (
        ConfigOptions.key("state.changelog.retained-bases").int_type()
        .default_value(4)
    ).with_description(
        "Materialized base files kept on disk. Must cover the checkpoint "
        "coordinator's max-retained window (a restorable handle must "
        "always find its base), mirroring the cold tier's manifest GC "
        "window."
    )


class MetricOptions:
    LATENCY_INTERVAL_MS = ConfigOptions.key("metrics.latency.interval").duration_ms_type().default_value(0)
    REPORTERS = ConfigOptions.key("metrics.reporters").list_type().default_value([])


class ObservabilityOptions:
    """The streaming observability plane (reference: LatencyMarker emission,
    TaskIOMetricGroup busy/idle/backPressured sampling, the REST backpressure
    handlers, and flame-graph/profiler capture). All knobs default to a
    configuration whose steady-state overhead is negligible (< 2% on the
    bench hot path): markers piggyback on source batches, ratio sampling is
    arithmetic over counters the run loop already maintains, and the
    profiler is off."""

    MARKER_INTERVAL_MS = (
        ConfigOptions.key("observability.latency-markers.interval-ms")
        .duration_ms_type().default_value(0)
    ).with_description(
        "Minimum wall-clock spacing between latency markers stamped at each "
        "source (LatencyMarker analogue). 0 stamps one marker per source "
        "batch; -1 disables marker emission entirely. Markers forwarded "
        "from an upstream stage over the dataplane always pass through "
        "regardless of this interval."
    )
    SAMPLING_INTERVAL_MS = (
        ConfigOptions.key("observability.sampling.interval-ms")
        .duration_ms_type().default_value(100)
    ).with_description(
        "Window over which busy/idle/backPressured time deltas are sampled "
        "into the *MsPerSecond gauges (the reference's backpressure "
        "sampling period). Lifetime ratios are maintained continuously and "
        "are unaffected."
    )
    DEVICE_TIMING_ENABLED = (
        ConfigOptions.key("observability.device-timing.enabled")
        .bool_type().default_value(True)
    ).with_description(
        "Time the host-side device sections of each window step (kernel "
        "dispatch + any blocking readback) into per-operator "
        "deviceDispatchMs histograms and deviceTimeMsTotal gauges. Timing "
        "is host-clock around already-synchronous sections — it never "
        "inserts extra block_until_ready syncs into deferred pipelines."
    )
    PROFILER_ENABLED = (
        ConfigOptions.key("observability.profiler.enabled")
        .bool_type().default_value(False)
    ).with_description(
        "Capture a jax.profiler trace for the duration of each job attempt "
        "(written under observability.profiler.dir). Heavyweight: device "
        "tracing serializes dispatches — for offline analysis only, never "
        "in production."
    )
    PROFILER_DIR = (
        ConfigOptions.key("observability.profiler.dir")
        .string_type().default_value("/tmp/flink-tpu-profile")
    ).with_description(
        "Output directory for observability.profiler.enabled trace dumps "
        "(TensorBoard-loadable)."
    )
    SHIPPING_INTERVAL_MS = (
        ConfigOptions.key("observability.shipping.interval-ms")
        .duration_ms_type().default_value(500)
    ).with_description(
        "How often a TaskExecutor ships metric snapshots and trace spans to "
        "the JobManager over the authenticated RPC plane (piggybacked on "
        "the heartbeat; the JM aggregates and serves them via REST and "
        "Prometheus)."
    )
    CHECKPOINT_HISTORY_SIZE = (
        ConfigOptions.key("observability.checkpoint-history.size")
        .int_type().default_value(10)
    ).with_description(
        "Per-checkpoint stat records retained in the CheckpointStatsTracker "
        "ring per job (trigger timestamp, capture/persist durations, "
        "per-task ack latency, state sizes, status and failure cause), "
        "served at /jobs/:id/checkpoints. Lifetime counters and the "
        "last-checkpoint gauges are unaffected by the ring size."
    )
    EXCEPTION_HISTORY_SIZE = (
        ConfigOptions.key("observability.exception-history.size")
        .int_type().default_value(16)
    ).with_description(
        "Exception-history entries and recovery-timeline records retained "
        "per job (timestamp, task/TaskManager attribution, root-cause "
        "chain, restart number; restore duration, rewound checkpoint id, "
        "replay depth, downtime), served at /jobs/:id/exceptions."
    )
    DEVICE_STATS_ENABLED = (
        ConfigOptions.key("observability.device.enabled")
        .bool_type().default_value(True)
    ).with_description(
        "Device-plane observability for device window operators: XLA "
        "compile/recompile tracking with shape-signature cause attribution "
        "(ring doubling, batch-geometry churn, dtype change), per-kernel "
        "cost/roofline gauges (hbmUtilizationPct, flopsUtilizationPct), "
        "per-phase ingest/fire/purge step counters threaded through the "
        "superscan carry, and per-key-group load telemetry (keySkew, hot "
        "keys). Served at /jobs/:id/device and shipped TM->JM on the "
        "heartbeat. Per-batch host cost is O(1); the key-stats fold runs "
        "on device on its own sampling interval."
    )
    DEVICE_RECOMPILE_HISTORY_SIZE = (
        ConfigOptions.key("observability.device.recompile-history.size")
        .int_type().default_value(32)
    ).with_description(
        "Compile events retained in the per-job recompile-event ring "
        "(program, shape signature, cause, compile wall time). The "
        "lifetime compile/recompile counters are unaffected by the ring "
        "size."
    )
    DEVICE_RECOMPILE_STORM_THRESHOLD = (
        ConfigOptions.key("observability.device.recompile-storm.threshold")
        .int_type().default_value(4)
    ).with_description(
        "Recompiles within observability.device.recompile-storm.window-ms "
        "that flip the recompileStorm warning gauge to 1 — a job re-jitting "
        "at this rate is paying compile latency on the hot path (growing "
        "key dictionary, churning batch geometry)."
    )
    DEVICE_RECOMPILE_STORM_WINDOW_MS = (
        ConfigOptions.key("observability.device.recompile-storm.window-ms")
        .duration_ms_type().default_value(60_000)
    ).with_description(
        "Sliding window over which recompiles are counted for the "
        "recompileStorm warning gauge."
    )
    DEVICE_COST_ANALYSIS_ENABLED = (
        ConfigOptions.key("observability.device.cost-analysis.enabled")
        .bool_type().default_value(True)
    ).with_description(
        "Capture XLA cost analysis (FLOPs, bytes accessed) for each "
        "compiled device program at compile time — the numerator of the "
        "roofline gauges. Costs one extra trace (no compile) per program "
        "signature; utilization gauges read 0 when disabled."
    )
    DEVICE_MEMORY_ANALYSIS_ENABLED = (
        ConfigOptions.key("observability.device.memory-analysis.enabled")
        .bool_type().default_value(False)
    ).with_description(
        "Additionally capture compiled-executable memory analysis (temp/"
        "output/argument HBM bytes) per program signature. jax exposes "
        "this only on AOT-compiled executables, so enabling it costs one "
        "EXTRA compile per program signature — leave off on TPU jobs "
        "whose superscan compiles take seconds; the cost-analysis roofline "
        "does not need it."
    )
    DEVICE_KEY_STATS_INTERVAL_MS = (
        ConfigOptions.key("observability.device.key-stats.interval-ms")
        .duration_ms_type().default_value(1000)
    ).with_description(
        "How often the per-key-group load fold runs (one device "
        "segment-sum over the resident window state + a tiny host "
        "readback). Gauges (keySkew, activeKeys, keyGroupLoad histogram, "
        "top-K hot keys) hold the latest fold between runs."
    )
    DEVICE_KEY_STATS_TOP_K = (
        ConfigOptions.key("observability.device.key-stats.top-k")
        .int_type().default_value(8)
    ).with_description(
        "Hot keys reported per operator by the key-stats fold (dense key "
        "id + resident record count, hottest first)."
    )
    DEVICE_HBM_GBPS = (
        ConfigOptions.key("observability.device.hbm-gbps")
        .float_type().default_value(0.0)
    ).with_description(
        "HBM bandwidth (GB/s) used as the denominator of the "
        "hbmUtilizationPct roofline gauge. 0 picks a per-platform default "
        "(tpu/gpu/cpu); set it to the bench-measured hbm_gbps of the "
        "actual part for calibrated utilization."
    )
    DEVICE_PEAK_TFLOPS = (
        ConfigOptions.key("observability.device.peak-tflops")
        .float_type().default_value(0.0)
    ).with_description(
        "Peak compute (TFLOP/s) used as the denominator of the "
        "flopsUtilizationPct roofline gauge. 0 picks a per-platform "
        "default."
    )
    EMISSION_LATENCY_ENABLED = (
        ConfigOptions.key("observability.emission-latency.enabled")
        .bool_type().default_value(True)
    ).with_description(
        "Record per-operator emission latency — host_resolve_wall_ms minus "
        "(window_end_event_ms + allowed lateness) — into a log-bucketed "
        "emissionLatencyMs histogram at the instant deferred emissions "
        "resolve, plus a watermarkLagMs gauge per windowed operator. "
        "Stamping happens on already-host-side resolve paths (it never "
        "forces a device sync); the fold across mesh shards merges "
        "histogram buckets and takes MAX lag. Serves /jobs/:id/latency, "
        "Prometheus summaries and the bench latency_frontier block."
    )
    EMISSION_LATENCY_OUTLIER_PCT = (
        ConfigOptions.key("observability.emission-latency.outlier-percentile")
        .float_type().default_value(99.0)
    ).with_description(
        "Fires whose emission latency lands at or above this percentile of "
        "the operator's own histogram (once 16+ samples exist) are captured "
        "as outliers: kept in a bounded ring and reported as latency-scope "
        "EmissionStall spans for tail attribution against concurrent "
        "control-plane spans (checkpoint, restart, rescale, rebalance, "
        "recompile)."
    )
    EMISSION_LATENCY_OUTLIER_FLOOR_MS = (
        ConfigOptions.key("observability.emission-latency.outlier-floor-ms")
        .float_type().default_value(5.0)
    ).with_description(
        "Absolute floor under which a fire is never treated as an outlier "
        "regardless of percentile rank — keeps a uniformly-fast operator "
        "(sub-millisecond tail) from spamming EmissionStall spans over "
        "noise."
    )
    EMISSION_LATENCY_OUTLIER_RING = (
        ConfigOptions.key("observability.emission-latency.outlier-ring-size")
        .int_type().default_value(64)
    ).with_description(
        "Outlier records retained per operator (resolve wall time + "
        "latency) for the /jobs/:id/latency stall-attribution report. The "
        "histogram and lifetime counters are unaffected by the ring size."
    )
    EMISSION_LATENCY_OUTLIER_MIN_SAMPLES = (
        ConfigOptions.key(
            "observability.emission-latency.outlier-min-samples")
        .int_type().default_value(16)
    ).with_description(
        "Recorded fires an operator needs before any fire can be captured "
        "as an outlier — the percentile threshold is meaningless over a "
        "near-empty histogram. Chaos/validation runs set 1 so the first "
        "post-restore fire is capture-eligible and its stall interval "
        "pins the recovery span."
    )
    HISTORY_INTERVAL_MS = (
        ConfigOptions.key("observability.history.interval-ms")
        .duration_ms_type().default_value(1000)
    ).with_description(
        "Sampling interval of the metric history plane: every registered "
        "job/operator metric is sampled into a bounded time-series ring "
        "on the existing processing-time tick (MiniCluster step boundary "
        "/ JobManager schedule tick) — counters recorded as windowed "
        "rates, gauges as values, histograms as per-sample p50/p99 "
        "sub-series. Served at /jobs/:id/history on both execution paths."
    )
    HISTORY_RETENTION_POINTS = (
        ConfigOptions.key("observability.history.retention-points")
        .int_type().default_value(256)
    ).with_description(
        "Points retained per metric series (a bounded ring — the oldest "
        "point falls off when the ring is full). Together with the "
        "sampling interval this bounds the lookback window: 256 points "
        "at 1000 ms is ~4.3 minutes of trajectory per metric."
    )
    DOCTOR_ENABLED = (
        ConfigOptions.key("observability.doctor.enabled")
        .bool_type().default_value(True)
    ).with_description(
        "Run the job doctor and its health watchdog: /jobs/:id/doctor "
        "serves a ranked, evidence-attributed bottleneck diagnosis joined "
        "over the history rings and the span stream, and the watchdog "
        "turns threshold breaches (throughput collapse vs the job's own "
        "recent baseline, watermark stall, backpressure saturation, "
        "emission-p99 breach) into rate-limited health.* spans."
    )
    DOCTOR_WINDOW_MS = (
        ConfigOptions.key("observability.doctor.window-ms")
        .duration_ms_type().default_value(60000)
    ).with_description(
        "Lookback window of one doctor diagnosis: history points and "
        "spans older than this are ignored when scoring bottleneck "
        "families."
    )
    DOCTOR_WATCHDOG_MIN_GAP_MS = (
        ConfigOptions.key("observability.doctor.watchdog-min-gap-ms")
        .duration_ms_type().default_value(5000)
    ).with_description(
        "Rate limit per health.* span family: a sustained breach emits at "
        "most one span per gap, so a wedged job cannot flood the bounded "
        "span ring with identical watchdog spans."
    )
    DOCTOR_P99_BREACH_MS = (
        ConfigOptions.key("observability.doctor.p99-breach-ms")
        .float_type().default_value(0.0)
    ).with_description(
        "Emission-latency p99 threshold for the health.P99Breach watchdog "
        "span (0 disables the check — there is no universal latency SLO; "
        "jobs with one declare it here)."
    )


class WatchdogOptions:
    """Stuck-task detection (distributed JobManager). A task wedged inside
    a live TaskManager — blocked UDF, dead device dispatch, a lost RPC
    reply — is invisible to heartbeat failure detection: the TM keeps
    beating while the task makes no progress forever."""

    STUCK_TASK_TIMEOUT_MS = (
        ConfigOptions.key("execution.watchdog.stuck-task-timeout-ms")
        .duration_ms_type().default_value(0)
    ).with_description(
        "Fail a RUNNING job's task through the normal attributed "
        "restart path when its heartbeat-reported step counter has not "
        "advanced for this long while its TaskManager stays alive (and "
        "the task has not finished). 0 (default) disables the watchdog. "
        "Tune WELL above the longest legitimate pause a step can take — "
        "device compiles, cold restores and backpressure stalls all "
        "freeze the step counter; start at 10x the heartbeat timeout."
    )


class ChaosOptions:
    """Deterministic fault injection (flink_tpu/chaos — docs/robustness.md).
    Default OFF; when off the runtime pays one module-level `is None`
    check per seam call and nothing else. Scenario tests and the
    chaos_microbench install plans programmatically; these options exist
    so a live cluster (jobmanager/taskmanager --conf) can run a drill."""

    ENABLED = (
        ConfigOptions.key("chaos.enabled").bool_type().default_value(False)
    ).with_description(
        "Install the configured FaultPlan process-wide at startup. Every "
        "fault it injects is labeled and attributed `injected: true` in "
        "the job's exception history. Never enable in production except "
        "as a deliberate, supervised drill."
    )
    SEED = (
        ConfigOptions.key("chaos.seed").int_type().default_value(0)
    ).with_description(
        "Seed for the FaultPlan's RNG (probability triggers): the same "
        "seed over a deterministic workload replays the same fault "
        "sequence."
    )
    RULES = (
        ConfigOptions.key("chaos.rules").string_type().default_value("")
    ).with_description(
        "JSON list of FaultRule field dicts, e.g. "
        '[{"scope": "rpc", "fault": "error", "match": '
        '"jobmanager.ack_checkpoint", "nth": 3, "max_fires": 2}]. '
        "Scopes: transport|rpc|dataplane|storage|device|heartbeat; "
        "faults: error|crash|delay|drop|torn|partition; triggers: "
        "nth-call, probability, window_s since install, max_fires."
    )


class AutoscalerOptions:
    """The elastic autoscaler (flink_tpu/scheduler/ — the AdaptiveScheduler
    analogue): a JM-side reactive controller that watches the
    observability-plane gauges (busy/backpressure ratios, pool usage,
    watermark skew, checkpoint durations), decides scale-up/down per the
    configured policy, and rescales live jobs by rewinding to the latest
    completed checkpoint and remapping key-groups onto the new slot set.
    Off by default — rescaling costs a checkpoint rewind + replay."""

    ENABLED = (
        ConfigOptions.key("autoscaler.enabled").bool_type().default_value(False)
    ).with_description(
        "Enable reactive autoscaling. On the distributed JobManager the "
        "controller watches TM-shipped metric snapshots and executes "
        "policy-driven rescales (keyed single-vertex jobs only; staged "
        "pipelines and device-operator snapshots cannot re-shard). On a "
        "MiniCluster the controller runs observe-only: decisions appear in "
        "/jobs/:id/autoscaler but are never executed."
    )
    MIN_PARALLELISM = (
        ConfigOptions.key("autoscaler.min-parallelism").int_type().default_value(1)
    ).with_description(
        "Lower bound the autoscaler may scale a job down to."
    )
    MAX_PARALLELISM = (
        ConfigOptions.key("autoscaler.max-parallelism").int_type().default_value(0)
    ).with_description(
        "Upper bound the autoscaler may scale a job up to; 0 (default) "
        "bounds only by available slots and the job's own max-parallelism "
        "(key-group count)."
    )
    STABILIZATION_INTERVAL_MS = (
        ConfigOptions.key("autoscaler.stabilization-interval-ms")
        .duration_ms_type().default_value(30_000)
    ).with_description(
        "Quiet period after a job starts or a rescale completes before the "
        "next decision may execute: signals from a warming attempt (replay, "
        "cold caches, fresh counters) must not immediately trigger another "
        "rescale."
    )
    POLICY = (
        ConfigOptions.key("autoscaler.policy").string_type().default_value("threshold")
    ).with_description(
        "Decision engine: 'threshold' doubles/halves parallelism on the "
        "utilization thresholds; 'learning' wraps the threshold rule with a "
        "bounded history of past rescale outcomes and damps decisions that "
        "previously failed to improve throughput (the Adaptive Parallelism "
        "Tuning blueprint, PAPERS.md)."
    )
    INTERVAL_MS = (
        ConfigOptions.key("autoscaler.interval-ms")
        .duration_ms_type().default_value(1000)
    ).with_description(
        "How often the controller samples the job's aggregated gauges into "
        "the signal window and evaluates the policy."
    )
    SIGNAL_WINDOW = (
        ConfigOptions.key("autoscaler.signal-window").int_type().default_value(6)
    ).with_description(
        "Samples per vertex the signal aggregator averages over before the "
        "policy sees them — one noisy tick must not rescale a job. The "
        "3-sample decision warm-up and outcome-settling bars clamp to this "
        "window when it is smaller."
    )
    SCALE_UP_THRESHOLD = (
        ConfigOptions.key("autoscaler.utilization.scale-up-threshold")
        .float_type().default_value(0.85)
    ).with_description(
        "Windowed utilization (busy + backpressured fraction) at or above "
        "which the threshold policy scales up."
    )
    SCALE_DOWN_THRESHOLD = (
        ConfigOptions.key("autoscaler.utilization.scale-down-threshold")
        .float_type().default_value(0.3)
    ).with_description(
        "Windowed utilization at or below which the threshold policy "
        "scales down."
    )
    DECISION_HISTORY_SIZE = (
        ConfigOptions.key("autoscaler.decision-history.size")
        .int_type().default_value(32)
    ).with_description(
        "Decision-log entries retained per job (signals seen, action, "
        "target, outcome, rescale duration), served at "
        "/jobs/:id/autoscaler."
    )
    LEARNING_MIN_GAIN = (
        ConfigOptions.key("autoscaler.learning.min-gain")
        .float_type().default_value(1.1)
    ).with_description(
        "Throughput gain a past scale-up must have achieved (scale-down: "
        "1/min-gain retention) for the learning policy to repeat the same "
        "transition without damping."
    )
    LEARNING_PATIENCE = (
        ConfigOptions.key("autoscaler.learning.patience")
        .int_type().default_value(4)
    ).with_description(
        "Number of triggers the learning policy suppresses a previously "
        "unhelpful transition for before retrying it (load may have "
        "changed shape since the bad outcome)."
    )


class SecurityOptions:
    """Transport security (reference: SecurityOptions + security.ssl.internal.*).

    One per-cluster shared secret authenticates every internal plane (RPC,
    dataplane exchange, blob) via a connection handshake + per-frame HMACs,
    and derives the REST bearer token. Resolution order for the secret:
    `security.transport.secret` > `security.transport.secret-file` (e.g. a
    mounted K8s Secret) > `FLINK_TPU_SECURITY_TRANSPORT_SECRET[_FILE]` env
    > an auto-generated per-user secret file (0600) shared by all local
    processes. See flink_tpu/security/transport.py."""

    TRANSPORT_ENABLED = (
        ConfigOptions.key("security.transport.enabled").bool_type().default_value(True)
    ).with_description(
        "Authenticate and MAC-sign every internal network frame (RPC, "
        "dataplane, blob) and deserialize through the restricted allowlist. "
        "Set to false to restore the legacy plaintext protocol for local "
        "debugging — never on a network you do not fully trust."
    )
    TRANSPORT_SECRET = (
        ConfigOptions.key("security.transport.secret").string_type().no_default_value()
    ).with_description(
        "Per-cluster shared secret. Prefer security.transport.secret-file "
        "(or the env vars) so the secret stays out of config files."
    )
    TRANSPORT_SECRET_FILE = (
        ConfigOptions.key("security.transport.secret-file").string_type().no_default_value()
    ).with_fallback_keys(
        # Configuration.from_env maps FLINK_TPU_SECURITY_TRANSPORT_SECRET_FILE
        # to the all-dots form; accept both spellings
        "security.transport.secret.file",
    ).with_description(
        "Path to a file holding the cluster secret (e.g. a mounted "
        "Kubernetes Secret; see flink_tpu/deploy/kubernetes.py)."
    )
    TRANSPORT_CLUSTER_ID = (
        ConfigOptions.key("security.transport.cluster-id").string_type().default_value("flink-tpu")
    ).with_fallback_keys("security.transport.cluster.id").with_description(
        "Cluster identity exchanged in the connection handshake; peers from "
        "a different cluster are rejected even when they share a secret."
    )
    SSL_INTERNAL_ENABLED = (
        ConfigOptions.key("security.ssl.internal.enabled").bool_type().default_value(False)
    ).with_description(
        "Layer TLS (stdlib ssl) under the HMAC framing on internal "
        "connections, mirroring the reference's security.ssl.internal.*."
    )
    SSL_INTERNAL_CERT = (
        ConfigOptions.key("security.ssl.internal.cert").string_type().no_default_value()
    ).with_description("PEM certificate chain presented by this process.")
    SSL_INTERNAL_KEY = (
        ConfigOptions.key("security.ssl.internal.key").string_type().no_default_value()
    ).with_description("PEM private key for security.ssl.internal.cert.")
    SSL_INTERNAL_CA = (
        ConfigOptions.key("security.ssl.internal.ca").string_type().no_default_value()
    ).with_description(
        "PEM CA bundle peers must chain to; when set on the server side, "
        "client certificates are required (mutual TLS)."
    )
    REST_AUTH_ENABLED = (
        ConfigOptions.key("security.rest.auth.enabled").bool_type().default_value(False)
    ).with_description(
        "Require `Authorization: Bearer <token>` on the REST API, with the "
        "token derived from the cluster secret "
        "(flink_tpu.security.rest_bearer_token)."
    )


class RestartOptions:
    STRATEGY = ConfigOptions.key("restart-strategy.type").string_type().default_value("exponential-delay")
    MAX_ATTEMPTS = ConfigOptions.key("restart-strategy.max-attempts").int_type().default_value(10)
    INITIAL_BACKOFF_MS = ConfigOptions.key("restart-strategy.initial-backoff").duration_ms_type().default_value(100)
    MAX_BACKOFF_MS = ConfigOptions.key("restart-strategy.max-backoff").duration_ms_type().default_value(10_000)
    BACKOFF_MULTIPLIER = ConfigOptions.key("restart-strategy.backoff-multiplier").float_type().default_value(2.0)
