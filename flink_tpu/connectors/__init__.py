"""Sources and sinks (reference: FLIP-27 Source SPI flink-core
.../api/connector/source/Source.java:37, Sink V2 .../sink2/Sink.java:38,
built-ins under flink-connectors/)."""

from flink_tpu.connectors.source import (
    Source,
    SourceReader,
    SourceSplit,
    SplitEnumerator,
    CollectionSource,
    DataGeneratorSource,
    FileSource,
)
from flink_tpu.connectors.sink import Sink, SinkWriter, Committer, CollectSink, PrintSink, FileSink
