"""Sinks (Sink V2 contract: Sink → SinkWriter (+ Committer for 2PC),
flink-core .../api/connector/sink2/Sink.java:38, SinkWriter.java:32,
Committer.java:39).

Exactly-once sinks stage output per checkpoint epoch and commit on
notify_checkpoint_complete — barrier-aligned two-phase commit, where our
"barrier" is a step boundary (SURVEY.md §7 stage 5)."""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Dict, List, Optional, Sequence, Tuple


class SinkWriter:
    def write(self, value, timestamp: Optional[int] = None) -> None:
        raise NotImplementedError

    def write_batch(self, values: Sequence, timestamps=None) -> None:
        for i, v in enumerate(values):
            self.write(v, None if timestamps is None else int(timestamps[i]))

    def prepare_commit(self, epoch_id: str = "final") -> List[Any]:
        """Returns committables for the current epoch (2PC phase 1).
        `epoch_id` identifies the checkpoint epoch: committable naming must
        be a pure function of it so replay after recovery is idempotent."""
        return []

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class Committer:
    def commit(self, committables: List[Any]) -> None:
        pass


class Sink:
    def create_writer(self) -> SinkWriter:
        raise NotImplementedError

    def create_committer(self) -> Optional[Committer]:
        return None


# ---------------------------------------------------------------------------

class _CollectWriter(SinkWriter):
    def __init__(self, store: List):
        self.store = store

    def write(self, value, timestamp=None) -> None:
        self.store.append(value)

    def write_batch(self, values, timestamps=None) -> None:
        self.store.extend(values)


class CollectSink(Sink):
    """Test/dev sink collecting into a Python list."""

    def __init__(self):
        self.results: List = []

    def create_writer(self) -> SinkWriter:
        return _CollectWriter(self.results)


class _PrintWriter(SinkWriter):
    def write(self, value, timestamp=None) -> None:
        print(value)


class PrintSink(Sink):
    def create_writer(self) -> SinkWriter:
        return _PrintWriter()


# ---------------------------------------------------------------------------
# FileSink with two-phase commit (FileSink + compaction analogue, simplified:
# one part file per epoch, moved into place on commit)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _PendingFile:
    temp_path: str
    final_path: str


class _FileWriter(SinkWriter):
    def __init__(self, directory: str, prefix: str):
        self.directory = directory
        self.prefix = prefix
        self._tmp = None
        self._fh = None
        os.makedirs(directory, exist_ok=True)
        self._open_epoch_file()

    def _open_epoch_file(self):
        fd, self._tmp = tempfile.mkstemp(prefix=f".{self.prefix}-inprogress-", dir=self.directory)
        self._fh = os.fdopen(fd, "w")

    def write(self, value, timestamp=None) -> None:
        self._fh.write(f"{value}\n")

    def prepare_commit(self, epoch_id: str = "final") -> List[_PendingFile]:
        """Part-file name is a pure function of epoch_id (the checkpoint id),
        so a replayed epoch atomically overwrites its own part file —
        exactly-once via idempotent rename."""
        self._fh.flush()
        self._fh.close()
        final = os.path.join(self.directory, f"{self.prefix}-part-{epoch_id}")
        pending = [_PendingFile(self._tmp, final)]
        self._open_epoch_file()
        return pending

    def close(self) -> None:
        if self._fh and not self._fh.closed:
            self._fh.close()
            if os.path.exists(self._tmp) and os.path.getsize(self._tmp) == 0:
                os.unlink(self._tmp)


class _FileCommitter(Committer):
    def commit(self, committables: List[_PendingFile]) -> None:
        for p in committables:
            if os.path.exists(p.temp_path):
                os.replace(p.temp_path, p.final_path)


class FileSink(Sink):
    def __init__(self, directory: str, prefix: str = "out"):
        self.directory = directory
        self.prefix = prefix

    def create_writer(self) -> SinkWriter:
        return _FileWriter(self.directory, self.prefix)

    def create_committer(self) -> Optional[Committer]:
        return _FileCommitter()
