"""Split-based sources (FLIP-27 contract re-expressed for batched ingest).

Reference: Source → SplitEnumerator (control plane) + SourceReader (data
plane) (flink-core .../connector/source/Source.java:37,
SplitEnumerator.java:34, SourceReader.java:56). The enumerator discovers and
assigns splits; readers poll records. Checkpoints snapshot reader split
state so replay resumes exactly (the exactly-once source half).

The TPU-native reader contract is *columnar*: poll_batch returns
(values, timestamps) numpy columns (plus optional key column), sized for one
device step — not one record at a time.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.time import MIN_TIMESTAMP
from flink_tpu.utils.arrays import obj_array


@dataclasses.dataclass
class SourceSplit:
    """A unit of source work (file region, generator range, partition)."""

    split_id: str
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Batch:
    """Columnar poll result. `values` is either an object array of records
    (record mode) or a dict of numeric columns (columnar mode)."""

    values: Any
    timestamps: Optional[np.ndarray] = None  # int64 ms; None = no event time

    def __len__(self):
        if isinstance(self.values, dict):
            return len(next(iter(self.values.values())))
        return len(self.values)


class SplitEnumerator:
    """JM-side split discovery/assignment (SplitEnumerator.java:34)."""

    def __init__(self, splits: List[SourceSplit]):
        self._pending = list(splits)

    def next_split(self) -> Optional[SourceSplit]:
        return self._pending.pop(0) if self._pending else None

    def add_split_back(self, split: SourceSplit) -> None:
        """Failover: reader died before finishing the split."""
        self._pending.insert(0, split)

    def snapshot(self) -> List[SourceSplit]:
        return list(self._pending)

    def restore(self, splits: List[SourceSplit]) -> None:
        self._pending = list(splits)


class SourceReader:
    """TM-side reader: polls columnar batches from its assigned splits."""

    def add_split(self, split: SourceSplit) -> None:
        raise NotImplementedError

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        """None = currently exhausted (need another split or end)."""
        raise NotImplementedError

    def snapshot_position(self) -> Dict[str, Any]:
        """Split progress for exactly-once replay."""
        return {}

    def restore_position(self, state: Dict[str, Any]) -> None:
        pass


class Source:
    """Factory for enumerator + readers (Source.java:37)."""

    boundedness: str = "BOUNDED"  # or 'CONTINUOUS_UNBOUNDED'

    def create_enumerator(self) -> SplitEnumerator:
        raise NotImplementedError

    def create_reader(self) -> SourceReader:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# CollectionSource (fromData / env.from_collection analogue)
# ---------------------------------------------------------------------------

class _CollectionReader(SourceReader):
    def __init__(self, timestamp_fn):
        self._items: List = []
        self._pos = 0
        self._ts_fn = timestamp_fn

    def add_split(self, split: SourceSplit) -> None:
        self._items = split.payload["items"]
        self._pos = 0

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        if self._pos >= len(self._items):
            return None
        chunk = self._items[self._pos : self._pos + max_records]
        self._pos += len(chunk)
        if self._ts_fn is not None:
            ts = np.asarray([self._ts_fn(x) for x in chunk], dtype=np.int64)
        else:
            ts = np.full(len(chunk), MIN_TIMESTAMP, dtype=np.int64)
        return Batch(obj_array(chunk), ts)

    def snapshot_position(self) -> Dict[str, Any]:
        return {"pos": self._pos}

    def restore_position(self, state: Dict[str, Any]) -> None:
        self._pos = state["pos"]


class CollectionSource(Source):
    def __init__(self, items: Sequence, timestamp_fn: Optional[Callable] = None):
        self.items = list(items)
        self.timestamp_fn = timestamp_fn

    def create_enumerator(self) -> SplitEnumerator:
        return SplitEnumerator([SourceSplit("collection-0", {"items": self.items})])

    def create_reader(self) -> SourceReader:
        return _CollectionReader(self.timestamp_fn)


# ---------------------------------------------------------------------------
# DataGeneratorSource (flink-connector-datagen DataGeneratorSource.java:95)
# ---------------------------------------------------------------------------

class _GeneratorReader(SourceReader):
    def __init__(self, generator_fn):
        self._gen = generator_fn
        self._start = 0
        self._end = 0
        self._next = 0

    def add_split(self, split: SourceSplit) -> None:
        self._start = split.payload["start"]
        self._end = split.payload["end"]
        self._next = self._start

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        if self._next >= self._end:
            return None
        n = min(max_records, self._end - self._next)
        idx = np.arange(self._next, self._next + n, dtype=np.int64)
        self._next += n
        return self._gen(idx)

    def snapshot_position(self) -> Dict[str, Any]:
        return {"next": self._next, "end": self._end}

    def restore_position(self, state: Dict[str, Any]) -> None:
        self._next = state["next"]
        self._end = state["end"]


class DataGeneratorSource(Source):
    """generator_fn(index_array) -> Batch; indices are a deterministic
    sequence so replay after restore is exact (the datagen connector's
    contract)."""

    def __init__(self, generator_fn: Callable[[np.ndarray], Batch], count: int, num_splits: int = 1):
        self.generator_fn = generator_fn
        self.count = count
        self.num_splits = num_splits

    def create_enumerator(self) -> SplitEnumerator:
        per = self.count // self.num_splits
        splits = []
        for i in range(self.num_splits):
            start = i * per
            end = self.count if i == self.num_splits - 1 else (i + 1) * per
            splits.append(SourceSplit(f"gen-{i}", {"start": start, "end": end}))
        return SplitEnumerator(splits)

    def create_reader(self) -> SourceReader:
        return _GeneratorReader(self.generator_fn)


# ---------------------------------------------------------------------------
# FileSource (flink-connector-files FileSource.java:98, text lines)
# ---------------------------------------------------------------------------

class _FileReader(SourceReader):
    def __init__(self, parse_fn, timestamp_fn):
        self._parse = parse_fn
        self._ts_fn = timestamp_fn
        self._path: Optional[str] = None
        self._offset = 0  # line offset within file
        self._lines: Optional[List[str]] = None

    def add_split(self, split: SourceSplit) -> None:
        self._path = split.payload["path"]
        self._offset = split.payload.get("offset", 0)
        self._lines = None

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        if self._path is None:
            return None
        if self._lines is None:
            with open(self._path) as f:
                self._lines = f.read().splitlines()
        if self._offset >= len(self._lines):
            self._path = None
            return None
        chunk = self._lines[self._offset : self._offset + max_records]
        self._offset += len(chunk)
        values = [self._parse(line) for line in chunk] if self._parse else chunk
        if self._ts_fn is not None:
            ts = np.asarray([self._ts_fn(v) for v in values], dtype=np.int64)
        else:
            ts = np.full(len(values), MIN_TIMESTAMP, dtype=np.int64)
        return Batch(obj_array(values), ts)

    def snapshot_position(self) -> Dict[str, Any]:
        return {"path": self._path, "offset": self._offset}

    def restore_position(self, state: Dict[str, Any]) -> None:
        self._path = state["path"]
        self._offset = state["offset"]
        self._lines = None


class FileSource(Source):
    def __init__(self, paths: Sequence[str], parse_fn: Optional[Callable] = None,
                 timestamp_fn: Optional[Callable] = None):
        self.paths = [str(p) for p in paths]
        self.parse_fn = parse_fn
        self.timestamp_fn = timestamp_fn

    def create_enumerator(self) -> SplitEnumerator:
        return SplitEnumerator(
            [SourceSplit(f"file-{i}", {"path": p}) for i, p in enumerate(self.paths)]
        )

    def create_reader(self) -> SourceReader:
        return _FileReader(self.parse_fn, self.timestamp_fn)
