"""Core contracts: key groups, event time, watermarks, columnar record batches."""
