"""FileSystem abstraction: scheme-routed pluggable filesystems (C4).

Analogue of flink-core/.../core/fs/FileSystem.java (+ the plugin-loaded
implementations under flink-filesystems/): URIs route to a registered
implementation by scheme. In-repo: `file://` (local posix, atomic writes via
temp+rename) and `mem://` (process-local object store — the test stand-in
for S3/GCS-style stores). Cloud stores register the same way
(`register_file_system("s3", ...)`) when their SDKs are present.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse


class FileSystem:
    scheme: str = ""

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        """Atomic full-object write (create or replace)."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    scheme = "file"

    @staticmethod
    def _p(path: str) -> str:
        return urlparse(path).path if "://" in path else path

    def read(self, path: str) -> bytes:
        with open(self._p(path), "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        p = self._p(path)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(p) or ".")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, p)
        except BaseException:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            raise

    def exists(self, path: str) -> bool:
        return os.path.exists(self._p(path))

    def list(self, path: str) -> List[str]:
        p = self._p(path)
        return sorted(os.path.join(p, n) for n in os.listdir(p))

    def delete(self, path: str, recursive: bool = False) -> None:
        import shutil

        p = self._p(path)
        if os.path.isdir(p):
            if not recursive:
                raise IsADirectoryError(p)
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.unlink(p)

    def mkdirs(self, path: str) -> None:
        os.makedirs(self._p(path), exist_ok=True)


class MemoryFileSystem(FileSystem):
    """Process-local object store: flat key space, prefix listing — the
    semantics of S3-style stores (no real directories)."""

    scheme = "mem"

    def __init__(self):
        self._objects: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _k(path: str) -> str:
        u = urlparse(path)
        return (u.netloc + u.path).rstrip("/")

    def read(self, path: str) -> bytes:
        with self._lock:
            try:
                return self._objects[self._k(path)]
            except KeyError:
                raise FileNotFoundError(path) from None

    def write(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[self._k(path)] = bytes(data)

    def exists(self, path: str) -> bool:
        k = self._k(path)
        with self._lock:
            return k in self._objects or any(
                o.startswith(k + "/") for o in self._objects
            )

    def list(self, path: str) -> List[str]:
        k = self._k(path)
        with self._lock:
            return sorted(
                f"mem://{o}" for o in self._objects if o.startswith(k + "/") or o == k
            )

    def delete(self, path: str, recursive: bool = False) -> None:
        k = self._k(path)
        with self._lock:
            if k in self._objects:
                del self._objects[k]
                return
            children = [o for o in self._objects if o.startswith(k + "/")]
            if children and not recursive:
                raise IsADirectoryError(path)
            for o in children:
                del self._objects[o]

    def mkdirs(self, path: str) -> None:
        pass  # object stores have no directories


_REGISTRY: Dict[str, FileSystem] = {}
_REGISTRY_LOCK = threading.Lock()


def register_file_system(scheme: str, fs: FileSystem) -> None:
    with _REGISTRY_LOCK:
        _REGISTRY[scheme] = fs


def get_file_system(uri: str) -> FileSystem:
    scheme = urlparse(uri).scheme if "://" in uri else "file"
    with _REGISTRY_LOCK:
        fs = _REGISTRY.get(scheme)
    if fs is None:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(registered: {sorted(_REGISTRY)})"
        )
    return fs


register_file_system("file", LocalFileSystem())
register_file_system("mem", MemoryFileSystem())
