"""User function interfaces (reference: flink-core .../api/common/functions/
MapFunction, FlatMapFunction, FilterFunction, ReduceFunction,
AggregateFunction; window functions in .../streaming/api/functions/windowing/).
"""

from __future__ import annotations

from typing import Any, Callable, Generic, Iterable, Iterator, List, Optional, TypeVar

IN = TypeVar("IN")
OUT = TypeVar("OUT")
ACC = TypeVar("ACC")
KEY = TypeVar("KEY")


class MapFunction(Generic[IN, OUT]):
    def map(self, value: IN) -> OUT:
        raise NotImplementedError


class FlatMapFunction(Generic[IN, OUT]):
    def flat_map(self, value: IN) -> Iterator[OUT]:
        raise NotImplementedError


class FilterFunction(Generic[IN]):
    def filter(self, value: IN) -> bool:
        raise NotImplementedError


class ReduceFunction(Generic[IN]):
    """reduce(a, b) must be associative; used as the window pre-aggregator
    (WindowedStream.reduce:181)."""

    def reduce(self, a: IN, b: IN) -> IN:
        raise NotImplementedError


class AggregateFunction(Generic[IN, ACC, OUT]):
    """create/add/get_result/merge contract (AggregateFunction.java).
    `merge` is required for session windows and distributed combines."""

    def create_accumulator(self) -> ACC:
        raise NotImplementedError

    def add(self, value: IN, accumulator: ACC) -> ACC:
        raise NotImplementedError

    def get_result(self, accumulator: ACC) -> OUT:
        raise NotImplementedError

    def merge(self, a: ACC, b: ACC) -> ACC:
        raise NotImplementedError


class _LambdaReduce(ReduceFunction):
    def __init__(self, fn: Callable[[Any, Any], Any]):
        self._fn = fn

    def reduce(self, a, b):
        return self._fn(a, b)


def as_reduce_function(fn) -> ReduceFunction:
    return fn if isinstance(fn, ReduceFunction) else _LambdaReduce(fn)


class ReduceAggregate(AggregateFunction):
    """Adapts a ReduceFunction to the AggregateFunction contract the way
    WindowedStream.reduce wraps into ReducingStateDescriptor."""

    _EMPTY = object()

    def __init__(self, reduce_fn: ReduceFunction):
        self.reduce_fn = as_reduce_function(reduce_fn)

    def create_accumulator(self):
        return ReduceAggregate._EMPTY

    def add(self, value, acc):
        if acc is ReduceAggregate._EMPTY:
            return value
        return self.reduce_fn.reduce(acc, value)

    def get_result(self, acc):
        return None if acc is ReduceAggregate._EMPTY else acc

    def merge(self, a, b):
        if a is ReduceAggregate._EMPTY:
            return b
        if b is ReduceAggregate._EMPTY:
            return a
        return self.reduce_fn.reduce(a, b)


class ProcessWindowFunction(Generic[IN, OUT, KEY]):
    """Receives the (pre-aggregated or buffered) window contents at fire time
    (ProcessWindowFunction.java). `context.window` is the firing window."""

    class Context:
        def __init__(self, window, current_watermark: int):
            self.window = window
            self.current_watermark = current_watermark

    def process(self, key: KEY, context: "ProcessWindowFunction.Context",
                elements: Iterable[IN]) -> Iterator[OUT]:
        raise NotImplementedError


class PassThroughWindowFunction(ProcessWindowFunction):
    def process(self, key, context, elements):
        for e in elements:
            yield e


class ProcessFunction(Generic[IN, OUT]):
    """Low-level per-record function with timers and side outputs
    (KeyedProcessFunction.java). Oracle/CPU path only in v0."""

    class Context:
        def __init__(self, timestamp, timer_service, side_collector):
            self.timestamp = timestamp
            self.timer_service = timer_service
            self._side = side_collector

        def output(self, tag: str, value) -> None:
            self._side(tag, value)

    def process_element(self, value: IN, ctx: "ProcessFunction.Context") -> Iterator[OUT]:
        raise NotImplementedError

    def on_timer(self, timestamp: int, ctx: "ProcessFunction.Context") -> Iterator[OUT]:
        return iter(())


class KeySelector(Generic[IN, KEY]):
    def get_key(self, value: IN) -> KEY:
        raise NotImplementedError


def as_key_selector(fn) -> Callable[[Any], Any]:
    if isinstance(fn, KeySelector):
        return fn.get_key
    return fn


class OutputTag:
    """Side-output tag (OutputTag.java). Late data uses LATE_DATA_TAG."""

    def __init__(self, tag_id: str):
        self.tag_id = tag_id

    def __hash__(self):
        return hash(self.tag_id)

    def __eq__(self, other):
        return isinstance(other, OutputTag) and other.tag_id == self.tag_id

    def __repr__(self):
        return f"OutputTag({self.tag_id!r})"


LATE_DATA_TAG = OutputTag("late-data")
