"""Key-group assignment with exact reference parity.

Key groups are the unit of state sharding and rescaling: every key maps to a
key group via a murmur-style finalizer over the key's hash, and each parallel
operator instance (here: each device shard) owns a contiguous range of key
groups. Parity targets (semantics reproduced exactly, per SURVEY.md §2.10):

- key group = murmur(keyHash) % maxParallelism
  (flink-runtime .../state/KeyGroupRangeAssignment.java:75,
   flink-core .../util/MathUtils.java:137 murmurHash)
- operator i owns [ceil(i*max/p), floor(((i+1)*max - 1)/p)]
  (KeyGroupRangeAssignment.java:93-106)
- key hash parity with java.lang hashCode for int/long/str keys so identical
  inputs land in identical key groups as the reference.

All functions have vectorized numpy forms (used on the host ingest path for
whole record batches) and jnp forms usable inside jitted programs.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Union

import numpy as np

DEFAULT_MAX_PARALLELISM = 128
UPPER_BOUND_MAX_PARALLELISM = 1 << 15  # Short.MAX_VALUE + 1, reference bound


# ---------------------------------------------------------------------------
# Java-compatible hashes (int32 wraparound arithmetic)
# ---------------------------------------------------------------------------

_U32 = 0xFFFFFFFF


def _to_i32(x: int) -> int:
    x &= _U32
    return x - (1 << 32) if x >= (1 << 31) else x


def java_hash_int(v: int) -> int:
    """Integer.hashCode / Long.hashCode((int)(v ^ (v >>> 32))) for wide ints."""
    if -(1 << 31) <= v < (1 << 31):
        return v
    v64 = v & 0xFFFFFFFFFFFFFFFF
    return _to_i32(v64 ^ (v64 >> 32))


def java_hash_string(s: Union[str, bytes]) -> int:
    """String.hashCode: s[0]*31^(n-1) + ... + s[n-1], int32 wraparound."""
    if isinstance(s, bytes):
        s = s.decode("utf-8", "surrogatepass")
    h = 0
    for ch in s:
        h = (h * 31 + ord(ch)) & _U32
    return _to_i32(h)


def key_hash(key) -> int:
    """hashCode-equivalent for supported key types; tuples combine like
    java.util.Arrays.hashCode."""
    if isinstance(key, bool):
        return 1231 if key else 1237
    if isinstance(key, (int, np.integer)):
        return java_hash_int(int(key))
    if isinstance(key, (str, bytes)):
        return java_hash_string(key)
    if isinstance(key, tuple):
        h = 1
        for item in key:
            h = (h * 31 + (key_hash(item) & _U32)) & _U32
        return _to_i32(h)
    if isinstance(key, float):
        # Double.hashCode over IEEE bits
        bits = np.float64(key).view(np.uint64)
        return _to_i32(int(bits) ^ (int(bits) >> 32))
    raise TypeError(f"Unsupported key type for key-group assignment: {type(key)}")


def murmur_finalize(code: int) -> int:
    """MathUtils.murmurHash(int): murmur3-32 body over one int + fmix,
    then absolute value (MathUtils.java:137-155). Returns non-negative."""
    c = code & _U32
    c = (c * 0xCC9E2D51) & _U32
    c = ((c << 15) | (c >> 17)) & _U32  # rotl 15
    c = (c * 0x1B873593) & _U32
    c = ((c << 13) | (c >> 19)) & _U32  # rotl 13
    c = (c * 5 + 0xE6546B64) & _U32
    c ^= 4  # length in bytes
    # fmix / bitMix (MathUtils.java:194)
    c ^= c >> 16
    c = (c * 0x85EBCA6B) & _U32
    c ^= c >> 13
    c = (c * 0xC2B2AE35) & _U32
    c ^= c >> 16
    signed = _to_i32(c)
    if signed >= 0:
        return signed
    if signed != -(1 << 31):
        return -signed
    return 0


def compute_key_group_for_key_hash(key_hash_val: int, max_parallelism: int) -> int:
    """KeyGroupRangeAssignment.computeKeyGroupForKeyHash:75."""
    return murmur_finalize(key_hash_val) % max_parallelism


def assign_to_key_group(key, max_parallelism: int = DEFAULT_MAX_PARALLELISM) -> int:
    """KeyGroupRangeAssignment.assignToKeyGroup:63."""
    return compute_key_group_for_key_hash(key_hash(key), max_parallelism)


# ---------------------------------------------------------------------------
# Vectorized (host batch path)
# ---------------------------------------------------------------------------

def murmur_finalize_np(codes: np.ndarray) -> np.ndarray:
    """Vectorized murmur_finalize over an int array -> non-negative int32."""
    c = codes.astype(np.uint32)
    c = c * np.uint32(0xCC9E2D51)
    c = (c << np.uint32(15)) | (c >> np.uint32(17))
    c = c * np.uint32(0x1B873593)
    c = (c << np.uint32(13)) | (c >> np.uint32(19))
    c = c * np.uint32(5) + np.uint32(0xE6546B64)
    c = c ^ np.uint32(4)
    c = c ^ (c >> np.uint32(16))
    c = c * np.uint32(0x85EBCA6B)
    c = c ^ (c >> np.uint32(13))
    c = c * np.uint32(0xC2B2AE35)
    c = c ^ (c >> np.uint32(16))
    signed = c.astype(np.int64)
    signed = np.where(signed >= (1 << 31), signed - (1 << 32), signed)
    out = np.where(signed >= 0, signed, np.where(signed != -(1 << 31), -signed, 0))
    return out.astype(np.int32)


def key_groups_for_hashes(key_hashes: np.ndarray, max_parallelism: int) -> np.ndarray:
    """Vectorized key-group assignment for a batch of java-style key hashes."""
    return (murmur_finalize_np(key_hashes).astype(np.int64) % max_parallelism).astype(np.int32)


# ---------------------------------------------------------------------------
# Ranges
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class KeyGroupRange:
    """Inclusive [start, end] range of key groups owned by one parallel instance
    (reference: runtime/state/KeyGroupRange.java:31)."""

    start: int
    end: int

    def __post_init__(self):
        if self.start > self.end:
            object.__setattr__(self, "start", 0)
            object.__setattr__(self, "end", -1)  # empty range convention

    @property
    def num_key_groups(self) -> int:
        return max(0, self.end - self.start + 1)

    def contains(self, key_group: int) -> bool:
        return self.start <= key_group <= self.end

    def __iter__(self) -> Iterable[int]:
        return iter(range(self.start, self.end + 1))

    def __len__(self) -> int:
        return self.num_key_groups


def key_group_range_for_operator(
    max_parallelism: int, parallelism: int, operator_index: int
) -> KeyGroupRange:
    """KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex:93-106."""
    if parallelism > max_parallelism:
        raise ValueError(
            f"parallelism {parallelism} > maxParallelism {max_parallelism}"
        )
    if max_parallelism > UPPER_BOUND_MAX_PARALLELISM:
        raise ValueError(f"maxParallelism must be <= {UPPER_BOUND_MAX_PARALLELISM}")
    start = (operator_index * max_parallelism + parallelism - 1) // parallelism
    end = ((operator_index + 1) * max_parallelism - 1) // parallelism
    return KeyGroupRange(start, end)


def operator_index_for_key_group(
    max_parallelism: int, parallelism: int, key_group: int
) -> int:
    """KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup."""
    return key_group * parallelism // max_parallelism


def shard_for_key_groups_np(
    key_groups: np.ndarray, max_parallelism: int, parallelism: int
) -> np.ndarray:
    """Vectorized operator/shard index for a batch of key groups — this is the
    host-side half of the keyBy shuffle (the device half is the all-to-all)."""
    return (key_groups.astype(np.int64) * parallelism // max_parallelism).astype(np.int32)
