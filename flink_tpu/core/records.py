"""Record representations: per-record stream elements and columnar batches.

The reference moves individual serialized records through a tagged-union
stream (StreamElementSerializer.java:45: record/watermark/latency-marker/
status). The TPU-native design instead moves *columnar batches*: the host
ingest loop accumulates records into struct-of-arrays `RecordBatch`es that
map 1:1 onto device arrays, and watermarks/latency markers travel out-of-band
as scalars attached to the batch (there is exactly one combined watermark per
step, see core/watermarks.py).

Per-record `StreamRecord` objects still exist for the pure-Python oracle
operators (parity testing, sessions) and for user process functions.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.core.keygroups import key_hash, key_groups_for_hashes
from flink_tpu.core.time import MIN_TIMESTAMP


@dataclasses.dataclass
class StreamRecord:
    """A single value + event timestamp (StreamRecord.java)."""

    value: Any
    timestamp: int = MIN_TIMESTAMP

    def has_timestamp(self) -> bool:
        return self.timestamp != MIN_TIMESTAMP


@dataclasses.dataclass
class LatencyMarker:
    """Source-injected marker for end-to-end latency tracking
    (streamrecord/LatencyMarker.java:32)."""

    marked_time_ms: int
    source_id: int
    subtask_index: int


class RecordBatch:
    """Struct-of-arrays batch: the unit of work of a device step.

    Columns:
      timestamps : int64[n]  event-time ms
      keys       : object[n] raw keys (host only; never shipped to device)
      key_hashes : int32[n]  java-hashCode-parity hashes
      key_groups : int32[n]  murmur(key_hash) % max_parallelism
      values     : {name: np.ndarray[n]} numeric payload columns
    """

    __slots__ = ("timestamps", "keys", "key_hashes", "key_groups", "values")

    def __init__(
        self,
        timestamps: np.ndarray,
        keys: np.ndarray,
        key_hashes: np.ndarray,
        key_groups: np.ndarray,
        values: Dict[str, np.ndarray],
    ):
        self.timestamps = timestamps
        self.keys = keys
        self.key_hashes = key_hashes
        self.key_groups = key_groups
        self.values = values

    def __len__(self) -> int:
        return int(self.timestamps.shape[0])

    @staticmethod
    def from_columns(
        timestamps: np.ndarray,
        keys: Sequence[Any],
        values: Dict[str, np.ndarray],
        max_parallelism: int,
        key_hashes: Optional[np.ndarray] = None,
    ) -> "RecordBatch":
        keys_arr = np.asarray(keys, dtype=object)
        if key_hashes is None:
            key_hashes = hash_keys(keys_arr)
        key_groups = key_groups_for_hashes(key_hashes, max_parallelism)
        return RecordBatch(
            np.asarray(timestamps, dtype=np.int64), keys_arr, key_hashes, key_groups, values
        )

    @staticmethod
    def from_records(
        records: Sequence[StreamRecord],
        key_selector: Callable[[Any], Any],
        value_selector: Callable[[Any], float],
        max_parallelism: int,
        value_dtype=np.float32,
    ) -> "RecordBatch":
        ts = np.fromiter((r.timestamp for r in records), dtype=np.int64, count=len(records))
        keys = [key_selector(r.value) for r in records]
        vals = np.fromiter(
            (value_selector(r.value) for r in records), dtype=value_dtype, count=len(records)
        )
        return RecordBatch.from_columns(ts, keys, {"value": vals}, max_parallelism)

    def select(self, mask: np.ndarray) -> "RecordBatch":
        return RecordBatch(
            self.timestamps[mask],
            self.keys[mask],
            self.key_hashes[mask],
            self.key_groups[mask],
            {k: v[mask] for k, v in self.values.items()},
        )

    def concat(self, other: "RecordBatch") -> "RecordBatch":
        return RecordBatch(
            np.concatenate([self.timestamps, other.timestamps]),
            np.concatenate([self.keys, other.keys]),
            np.concatenate([self.key_hashes, other.key_hashes]),
            np.concatenate([self.key_groups, other.key_groups]),
            {k: np.concatenate([v, other.values[k]]) for k, v in self.values.items()},
        )

    @staticmethod
    def empty(value_dtypes: Dict[str, Any] = None) -> "RecordBatch":
        value_dtypes = value_dtypes or {"value": np.float32}
        return RecordBatch(
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=object),
            np.empty(0, dtype=np.int32),
            np.empty(0, dtype=np.int32),
            {k: np.empty(0, dtype=dt) for k, dt in value_dtypes.items()},
        )


def hash_keys(keys: np.ndarray) -> np.ndarray:
    """Java-parity hashes for a batch of keys. Integer arrays vectorize;
    object/string keys fall back to a per-element loop (the C++ codec in
    native/ is the fast path for string keys, see native/README)."""
    if keys.dtype != object and np.issubdtype(keys.dtype, np.integer):
        v = keys.astype(np.int64)
        small = (v >= -(1 << 31)) & (v < (1 << 31))
        folded = (v.view(np.uint64) ^ (v.view(np.uint64) >> np.uint64(32))).astype(np.uint32)
        out = np.where(small, v.astype(np.int64), folded.astype(np.int64))
        out = np.where(out >= (1 << 31), out - (1 << 32), out)
        return out.astype(np.int32)
    return np.fromiter((key_hash(k) for k in keys), dtype=np.int32, count=len(keys))
