"""TypeSerializers + serializer snapshots (state schema evolution).

Mirrors the reference's TypeSerializer (flink-core/.../typeutils/
TypeSerializer.java:59) and TypeSerializerSnapshot contract: durable state
(savepoints, typed blobs) embeds a snapshot of the serializer that wrote it;
on restore the new serializer's snapshot is resolved against the written one
producing COMPATIBLE_AS_IS / COMPATIBLE_AFTER_MIGRATION / INCOMPATIBLE —
row/dataclass types migrate by field name (added fields take defaults,
removed fields are dropped), the analogue of PojoSerializer's evolution
rules.

Binary format conventions: little-endian fixed-width numerics, varint
lengths, a null byte before nullable values. Snapshots themselves serialize
to plain JSON-able dicts (class + config), the analogue of
TypeSerializerSnapshot#writeSnapshot.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_MISSING = object()  # sentinel: field absent from the old schema (migration)

# compatibility verdicts
COMPATIBLE_AS_IS = "as_is"
COMPATIBLE_AFTER_MIGRATION = "after_migration"
INCOMPATIBLE = "incompatible"


def write_varint(out: io.BytesIO, n: int) -> None:
    assert n >= 0
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_varint(inp: io.BytesIO) -> int:
    shift = n = 0
    while True:
        byte = inp.read(1)
        if not byte:
            raise EOFError("truncated varint (blob cut short?)")
        b = byte[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n
        shift += 7


class TypeSerializer:
    def write(self, value: Any, out: io.BytesIO) -> None:
        raise NotImplementedError

    def read(self, inp: io.BytesIO) -> Any:
        raise NotImplementedError

    def serialize(self, value: Any) -> bytes:
        out = io.BytesIO()
        self.write(value, out)
        return out.getvalue()

    def deserialize(self, data: bytes) -> Any:
        return self.read(io.BytesIO(data))

    def snapshot(self) -> "TypeSerializerSnapshot":
        return TypeSerializerSnapshot(type(self).__name__, self._snapshot_config())

    def _snapshot_config(self) -> dict:
        return {}

    # evolution hook: build this serializer's reader for data written by
    # `old` (only called when resolve says AFTER_MIGRATION)
    def migrating_reader(self, old: "TypeSerializerSnapshot"):
        raise NotImplementedError(f"{type(self).__name__} cannot migrate")


class TypeSerializerSnapshot:
    """JSON-able record of how state bytes were written."""

    def __init__(self, serializer_class: str, config: dict):
        self.serializer_class = serializer_class
        self.config = config

    def to_dict(self) -> dict:
        return {"class": self.serializer_class, "config": self.config}

    @staticmethod
    def from_dict(d: dict) -> "TypeSerializerSnapshot":
        return TypeSerializerSnapshot(d["class"], d.get("config", {}))

    _ROW_FAMILY = ("RowSerializer", "DataclassSerializer")

    def resolve_compatibility(self, new_serializer: TypeSerializer) -> str:
        new = new_serializer.snapshot()
        row_to_row = (
            self.serializer_class in self._ROW_FAMILY
            and new.serializer_class in self._ROW_FAMILY
        )
        if new.serializer_class != self.serializer_class and not row_to_row:
            return INCOMPATIBLE
        if new.config == self.config:
            return COMPATIBLE_AS_IS
        if row_to_row:
            # wire-identical row<->dataclass (e.g. reading with the class
            # gone) is as-is; otherwise fields migrate by name, recursing
            # into nested rows
            if (self.config["names"] == new.config["names"]
                    and self.config["fields"] == new.config["fields"]):
                return COMPATIBLE_AS_IS
            old_f = dict(zip(self.config["names"], self.config["fields"]))
            new_f = dict(zip(new.config["names"], new.config["fields"]))
            for name in set(old_f) & set(new_f):
                if old_f[name] == new_f[name]:
                    continue
                old_snap = TypeSerializerSnapshot.from_dict(old_f[name])
                new_field = _restore_raw(TypeSerializerSnapshot.from_dict(new_f[name]))
                if old_snap.resolve_compatibility(new_field) == INCOMPATIBLE:
                    return INCOMPATIBLE
            return COMPATIBLE_AFTER_MIGRATION
        return INCOMPATIBLE

    def __repr__(self):
        return f"Snapshot({self.serializer_class}, {self.config})"


def _read_exact(inp: io.BytesIO, n: int) -> bytes:
    b = inp.read(n)
    if len(b) != n:
        raise EOFError(f"truncated value: wanted {n} bytes, got {len(b)}")
    return b


class _StructSerializer(TypeSerializer):
    fmt = ""

    def write(self, value, out):
        out.write(struct.pack(self.fmt, value))

    def read(self, inp):
        (v,) = struct.unpack(self.fmt, _read_exact(inp, struct.calcsize(self.fmt)))
        return v


class LongSerializer(_StructSerializer):
    fmt = "<q"

    def write(self, value, out):
        out.write(struct.pack(self.fmt, int(value)))


class IntSerializer(_StructSerializer):
    fmt = "<i"

    def write(self, value, out):
        out.write(struct.pack(self.fmt, int(value)))


class DoubleSerializer(_StructSerializer):
    fmt = "<d"

    def write(self, value, out):
        out.write(struct.pack(self.fmt, float(value)))


class FloatSerializer(_StructSerializer):
    fmt = "<f"

    def write(self, value, out):
        out.write(struct.pack(self.fmt, float(value)))


class BooleanSerializer(TypeSerializer):
    def write(self, value, out):
        out.write(b"\x01" if value else b"\x00")

    def read(self, inp):
        b = inp.read(1)
        if not b:
            raise EOFError("truncated boolean")
        return b == b"\x01"


class BytesSerializer(TypeSerializer):
    def write(self, value, out):
        write_varint(out, len(value))
        out.write(value)

    def read(self, inp):
        return _read_exact(inp, read_varint(inp))


class StringSerializer(TypeSerializer):
    def write(self, value, out):
        b = value.encode("utf-8")
        write_varint(out, len(b))
        out.write(b)

    def read(self, inp):
        return _read_exact(inp, read_varint(inp)).decode("utf-8")


class NumpyScalarSerializer(TypeSerializer):
    def __init__(self, dtype):
        self.dtype = np.dtype(dtype)

    def write(self, value, out):
        out.write(np.asarray(value, dtype=self.dtype).tobytes())

    def read(self, inp):
        return np.frombuffer(_read_exact(inp, self.dtype.itemsize), dtype=self.dtype)[0]

    def _snapshot_config(self):
        return {"dtype": self.dtype.str}


class PickleSerializer(TypeSerializer):
    """Kryo-fallback analogue."""

    def write(self, value, out):
        b = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        write_varint(out, len(b))
        out.write(b)

    def read(self, inp):
        return pickle.loads(_read_exact(inp, read_varint(inp)))


class TupleSerializer(TypeSerializer):
    def __init__(self, fields: Sequence[TypeSerializer]):
        self.fields = list(fields)

    def write(self, value, out):
        if len(value) != len(self.fields):
            raise ValueError(
                f"tuple arity {len(value)} != serializer arity {len(self.fields)}"
            )
        for s, v in zip(self.fields, value):
            s.write(v, out)

    def read(self, inp):
        return tuple(s.read(inp) for s in self.fields)

    def _snapshot_config(self):
        return {"fields": [s.snapshot().to_dict() for s in self.fields]}


class ListSerializer(TypeSerializer):
    def __init__(self, elem: TypeSerializer):
        self.elem = elem

    def write(self, value, out):
        write_varint(out, len(value))
        for v in value:
            self.elem.write(v, out)

    def read(self, inp):
        return [self.elem.read(inp) for _ in range(read_varint(inp))]

    def _snapshot_config(self):
        return {"elem": self.elem.snapshot().to_dict()}


class MapSerializer(TypeSerializer):
    def __init__(self, key: TypeSerializer, value: TypeSerializer):
        self.key = key
        self.value = value

    def write(self, value, out):
        write_varint(out, len(value))
        for k, v in value.items():
            self.key.write(k, out)
            self.value.write(v, out)

    def read(self, inp):
        return {self.key.read(inp): self.value.read(inp) for _ in range(read_varint(inp))}

    def _snapshot_config(self):
        return {"key": self.key.snapshot().to_dict(), "value": self.value.snapshot().to_dict()}


class RowSerializer(TypeSerializer):
    """Null-mask + named fields; migrates by field name across versions."""

    def __init__(self, names: Sequence[str], fields: Sequence[TypeSerializer]):
        assert len(names) == len(fields)
        self.names = list(names)
        self.fields = list(fields)

    def write(self, value, out):
        vals = list(value)
        mask = 0
        for i, v in enumerate(vals):
            if v is None:
                mask |= 1 << i
        write_varint(out, mask)
        for s, v in zip(self.fields, vals):
            if v is not None:
                s.write(v, out)

    def read(self, inp):
        mask = read_varint(inp)
        return tuple(
            None if mask & (1 << i) else s.read(inp) for i, s in enumerate(self.fields)
        )

    def _snapshot_config(self):
        return {
            "names": list(self.names),
            "fields": [s.snapshot().to_dict() for s in self.fields],
        }

    def migrating_reader(self, old: TypeSerializerSnapshot):
        """Reader that consumes the OLD wire format and emits rows in the NEW
        field order (dropped fields skipped, added fields None)."""
        old_names = old.config["names"]
        new_index = {n: i for i, n in enumerate(self.names)}
        # per old field: a reader that consumes the OLD wire bytes; shared
        # fields whose own schema evolved get a nested migrating reader
        readers = []
        for n, fdict in zip(old_names, old.config["fields"]):
            fsnap = TypeSerializerSnapshot.from_dict(fdict)
            idx = new_index.get(n)
            if idx is not None and self.fields[idx].snapshot().to_dict() != fdict:
                verdict = fsnap.resolve_compatibility(self.fields[idx])
                if verdict == COMPATIBLE_AFTER_MIGRATION:
                    readers.append(self.fields[idx].migrating_reader(fsnap))
                    continue
            readers.append(restore_serializer(fsnap).read)

        def read(inp: io.BytesIO):
            mask = read_varint(inp)
            out_vals: List[Any] = [_MISSING] * len(self.names)
            for i, (n, rd) in enumerate(zip(old_names, readers)):
                if mask & (1 << i):
                    v = None
                else:
                    v = rd(inp)
                if n in new_index:
                    out_vals[new_index[n]] = v
            return self._finish(out_vals)

        return read

    def _finish(self, vals: List[Any]):
        # fields absent from the old schema surface as None in plain rows
        return tuple(None if v is _MISSING else v for v in vals)


class DataclassSerializer(RowSerializer):
    def __init__(self, cls: type, names: Sequence[str], fields: Sequence[TypeSerializer]):
        super().__init__(names, fields)
        self.cls = cls

    def write(self, value, out):
        super().write([getattr(value, n) for n in self.names], out)

    def read(self, inp):
        vals = super().read(inp)
        return self.cls(**dict(zip(self.names, vals)))

    def _snapshot_config(self):
        cfg = super()._snapshot_config()
        cfg["cls"] = f"{self.cls.__module__}.{self.cls.__qualname__}"
        return cfg

    def _finish(self, vals):
        # absent fields are omitted so dataclass defaults apply; a required
        # added field without a default falls back to None
        kwargs = {n: v for n, v in zip(self.names, vals) if v is not _MISSING}
        try:
            return self.cls(**kwargs)
        except TypeError:
            full = {n: (None if v is _MISSING else v) for n, v in zip(self.names, vals)}
            return self.cls(**full)


_RESTORERS = {
    "LongSerializer": lambda c: LongSerializer(),
    "IntSerializer": lambda c: IntSerializer(),
    "DoubleSerializer": lambda c: DoubleSerializer(),
    "FloatSerializer": lambda c: FloatSerializer(),
    "BooleanSerializer": lambda c: BooleanSerializer(),
    "BytesSerializer": lambda c: BytesSerializer(),
    "StringSerializer": lambda c: StringSerializer(),
    "NumpyScalarSerializer": lambda c: NumpyScalarSerializer(c["dtype"]),
    "PickleSerializer": lambda c: PickleSerializer(),
    "TupleSerializer": lambda c: TupleSerializer(
        [restore_serializer(TypeSerializerSnapshot.from_dict(d)) for d in c["fields"]]
    ),
    "ListSerializer": lambda c: ListSerializer(
        restore_serializer(TypeSerializerSnapshot.from_dict(c["elem"]))
    ),
    "MapSerializer": lambda c: MapSerializer(
        restore_serializer(TypeSerializerSnapshot.from_dict(c["key"])),
        restore_serializer(TypeSerializerSnapshot.from_dict(c["value"])),
    ),
    "RowSerializer": lambda c: RowSerializer(
        c["names"],
        [restore_serializer(TypeSerializerSnapshot.from_dict(d)) for d in c["fields"]],
    ),
    # the writing dataclass may no longer be importable: restore as a plain
    # row over the same names/wire format (canonical-savepoint semantics)
    "DataclassSerializer": lambda c: RowSerializer(
        c["names"],
        [restore_serializer(TypeSerializerSnapshot.from_dict(d)) for d in c["fields"]],
    ),
}


def restore_serializer(snap: TypeSerializerSnapshot) -> TypeSerializer:
    """Rebuild a serializer purely from its snapshot (reading old blobs even
    when the writing code is gone — canonical-savepoint semantics)."""
    try:
        return _RESTORERS[snap.serializer_class](snap.config)
    except KeyError:
        raise ValueError(f"unknown serializer snapshot {snap.serializer_class}")


_restore_raw = restore_serializer  # internal alias (compat resolution)


# ---------------------------------------------------------------------------
# typed state blobs: length-prefixed values + embedded snapshot
# ---------------------------------------------------------------------------

def write_typed_blob(values: Sequence[Any], serializer: TypeSerializer) -> dict:
    """Durable, evolvable encoding of a list of values: bytes + snapshot."""
    out = io.BytesIO()
    write_varint(out, len(values))
    for v in values:
        serializer.write(v, out)
    return {"snapshot": serializer.snapshot().to_dict(), "data": out.getvalue()}


def read_typed_blob(blob: dict, serializer: TypeSerializer) -> List[Any]:
    """Read values back, migrating if the schema evolved; raises on
    incompatible schema change (the reference's restore-time failure)."""
    snap = TypeSerializerSnapshot.from_dict(blob["snapshot"])
    verdict = snap.resolve_compatibility(serializer)
    inp = io.BytesIO(blob["data"])
    n = read_varint(inp)
    if verdict == COMPATIBLE_AS_IS:
        return [serializer.read(inp) for _ in range(n)]
    if verdict == COMPATIBLE_AFTER_MIGRATION:
        reader = serializer.migrating_reader(snap)
        return [reader(inp) for _ in range(n)]
    raise ValueError(
        f"state written by {snap.serializer_class}{snap.config} is incompatible "
        f"with {serializer.snapshot().to_dict()}"
    )
