"""Event-time primitives: TimeWindow and window-start math at exact parity.

Parity targets (SURVEY.md §2.10):
- window start: start = ts - ((ts - offset) mod size) with negative-remainder
  correction (TimeWindow.getWindowStartWithOffset,
  flink-runtime .../windowing/windows/TimeWindow.java:264-272)
- windows are [start, end); a window may fire when
  watermark >= maxTimestamp() = end - 1
- sliding assignment walks start in {lastStart, lastStart - slide, ...} while
  start > ts - size (SlidingEventTimeWindows.assignWindows:77-85)

Timestamps are int64 epoch milliseconds on host; device programs use
int32/int64 *slice indices* (timestamp // slide rebased), never raw ms.
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np

MIN_TIMESTAMP = -(1 << 63)          # Long.MIN_VALUE: "no timestamp"
MAX_WATERMARK = (1 << 63) - 1       # Watermark.MAX_WATERMARK: end of stream
MIN_WATERMARK = -(1 << 63)


def window_start_with_offset(timestamp: int, offset: int, window_size: int) -> int:
    """TimeWindow.getWindowStartWithOffset:264-272 (exact semantics)."""
    remainder = _java_mod(timestamp - offset, window_size)
    if remainder < 0:
        return timestamp - (remainder + window_size)
    return timestamp - remainder


def _java_mod(a: int, b: int) -> int:
    """Java % (truncated toward zero), unlike Python's floored %."""
    r = abs(a) % abs(b)
    return -r if a < 0 else r


def window_start_with_offset_np(ts: np.ndarray, offset: int, window_size: int) -> np.ndarray:
    """Vectorized window start. For int64 ts, Java truncated-mod semantics."""
    d = ts - np.int64(offset)
    r = np.where(d < 0, -((-d) % np.int64(window_size)), d % np.int64(window_size))
    return np.where(r < 0, ts - (r + np.int64(window_size)), ts - r)


@dataclasses.dataclass(frozen=True, order=True)
class TimeWindow:
    """Half-open [start, end) event-time window (TimeWindow.java)."""

    start: int
    end: int

    def max_timestamp(self) -> int:
        return self.end - 1

    def intersects(self, other: "TimeWindow") -> bool:
        return self.start <= other.end and self.end >= other.start

    def cover(self, other: "TimeWindow") -> "TimeWindow":
        return TimeWindow(min(self.start, other.start), max(self.end, other.end))

    def __repr__(self) -> str:
        return f"TimeWindow[{self.start}, {self.end})"


def assign_tumbling(timestamp: int, size: int, offset: int = 0) -> List[TimeWindow]:
    if timestamp <= MIN_TIMESTAMP:
        raise ValueError("Record has no timestamp; assign timestamps & watermarks first.")
    start = window_start_with_offset(timestamp, offset, size)
    return [TimeWindow(start, start + size)]


def assign_sliding(timestamp: int, size: int, slide: int, offset: int = 0) -> List[TimeWindow]:
    """SlidingEventTimeWindows.assignWindows:77-85 (exact iteration order:
    newest window first)."""
    if timestamp <= MIN_TIMESTAMP:
        raise ValueError("Record has no timestamp; assign timestamps & watermarks first.")
    windows = []
    last_start = window_start_with_offset(timestamp, offset, slide)
    start = last_start
    while start > timestamp - size:
        windows.append(TimeWindow(start, start + size))
        start -= slide
    return windows


def cleanup_time(window: TimeWindow, allowed_lateness: int) -> int:
    """WindowOperator.cleanupTime:670 — state retained until
    maxTimestamp + allowedLateness (saturating)."""
    ct = window.max_timestamp() + allowed_lateness
    # Java long overflow check: wrapped sum < maxTimestamp ⇒ Long.MAX_VALUE
    if ct > MAX_WATERMARK:
        ct -= 1 << 64
    return ct if ct >= window.max_timestamp() else MAX_WATERMARK


def is_window_late(window: TimeWindow, allowed_lateness: int, current_watermark: int) -> bool:
    """WindowOperator.isWindowLate:609 — drop-on-assignment condition."""
    return cleanup_time(window, allowed_lateness) <= current_watermark
