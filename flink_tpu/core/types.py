"""Type information: schema descriptions for records, columns and state.

The reference's TypeInformation (flink-core/.../typeinfo/TypeInformation.java:80)
describes a value type, creates its TypeSerializer, and is extracted by
reflection (TypeExtractor.java:99). The TPU-native analogue serves two
masters:

1. **Columnar layout**: every type reports its device-columnar dtype
   (`columnar_dtype()`), i.e. how a column of such values lands in a
   struct-of-arrays RecordBatch / HBM DeviceArray — the analogue of the
   reference's serializer knowing its binary layout. Types without a fixed
   numeric layout (strings, arbitrary objects) are host-side columns that
   reach the device only through the key dictionary / codec paths.
2. **Durable serialization**: `serializer()` returns a TypeSerializer
   (core/serializers.py) used for savepoint/state blobs with snapshot-based
   schema evolution (TypeSerializerSnapshot semantics).

Extraction mirrors TypeExtractor: `TypeInformation.of()` accepts python
types, typing hints, dataclasses (the POJO analogue, PojoSerializer.java:48)
and falls back to pickle (the Kryo fallback, KryoSerializer.java:98).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class TypeInformation:
    """Describes a value type; factory for its serializer and column dtype."""

    def serializer(self):
        raise NotImplementedError

    def columnar_dtype(self) -> Optional[np.dtype]:
        """numpy dtype of a device-ready column of this type, or None if the
        type is host-only (variable length / object)."""
        return None

    @property
    def arity(self) -> int:
        return 1

    # -- extraction (TypeExtractor analogue) --------------------------------
    @staticmethod
    def of(hint: Any) -> "TypeInformation":
        if isinstance(hint, TypeInformation):
            return hint
        if hint is int:
            return Types.LONG
        if hint is float:
            return Types.DOUBLE
        if hint is bool:
            return Types.BOOLEAN
        if hint is str:
            return Types.STRING
        if hint is bytes:
            return Types.BYTES
        if isinstance(hint, np.dtype) or (isinstance(hint, type) and issubclass(hint, np.generic)):
            return NumpyTypeInfo(np.dtype(hint))
        import types as _pytypes

        origin = typing.get_origin(hint)
        if origin is typing.Union or origin is getattr(_pytypes, "UnionType", ()):
            args = [a for a in typing.get_args(hint) if a is not type(None)]
            if len(args) == 1:
                # Optional[X] ≡ X: the row null-mask already encodes None
                return TypeInformation.of(args[0])
            return Types.PICKLED
        if origin in (tuple,):
            args = typing.get_args(hint)
            if Ellipsis in args:  # variadic tuple[X, ...]: no fixed arity
                return Types.PICKLED
            return TupleTypeInfo([TypeInformation.of(a) for a in args])
        if origin in (list,):
            (elem,) = typing.get_args(hint) or (Any,)
            return ListTypeInfo(TypeInformation.of(elem) if elem is not Any else Types.PICKLED)
        if origin in (dict,):
            args = typing.get_args(hint) or (Any, Any)
            return MapTypeInfo(
                TypeInformation.of(args[0]) if args[0] is not Any else Types.PICKLED,
                TypeInformation.of(args[1]) if args[1] is not Any else Types.PICKLED,
            )
        if dataclasses.is_dataclass(hint) and isinstance(hint, type):
            fields = []
            hints = typing.get_type_hints(hint)
            for f in dataclasses.fields(hint):
                fields.append((f.name, TypeInformation.of(hints.get(f.name, Any))
                               if hints.get(f.name, Any) is not Any else Types.PICKLED))
            return DataclassTypeInfo(hint, fields)
        return Types.PICKLED

    @staticmethod
    def infer(value: Any) -> "TypeInformation":
        """Extract from a sample value (the runtime-extraction path)."""
        if isinstance(value, bool):
            return Types.BOOLEAN
        if isinstance(value, int):
            return Types.LONG
        if isinstance(value, float):
            return Types.DOUBLE
        if isinstance(value, str):
            return Types.STRING
        if isinstance(value, bytes):
            return Types.BYTES
        if isinstance(value, np.generic):
            return NumpyTypeInfo(value.dtype)
        if isinstance(value, tuple):
            return TupleTypeInfo([TypeInformation.infer(v) for v in value])
        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            return TypeInformation.of(type(value))
        return Types.PICKLED

    # identity by config
    def _config(self) -> tuple:
        return (type(self).__name__,)

    def __eq__(self, other):
        return isinstance(other, TypeInformation) and self._config() == other._config()

    def __hash__(self):
        return hash(self._config())

    def __repr__(self):
        return self._config()[0]


class BasicTypeInfo(TypeInformation):
    def __init__(self, name: str, dtype: Optional[np.dtype], serializer_factory):
        self.name = name
        self._dtype = np.dtype(dtype) if dtype is not None else None
        self._serializer_factory = serializer_factory

    def serializer(self):
        return self._serializer_factory()

    def columnar_dtype(self):
        return self._dtype

    def _config(self):
        return ("basic", self.name)

    def __repr__(self):
        return self.name


class NumpyTypeInfo(TypeInformation):
    def __init__(self, dtype: np.dtype):
        self.dtype = np.dtype(dtype)

    def serializer(self):
        from flink_tpu.core.serializers import NumpyScalarSerializer

        return NumpyScalarSerializer(self.dtype)

    def columnar_dtype(self):
        return self.dtype

    def _config(self):
        return ("numpy", self.dtype.str)


class TupleTypeInfo(TypeInformation):
    def __init__(self, field_types: Sequence[TypeInformation]):
        self.field_types = list(field_types)

    @property
    def arity(self):
        return len(self.field_types)

    def serializer(self):
        from flink_tpu.core.serializers import TupleSerializer

        return TupleSerializer([t.serializer() for t in self.field_types])

    def _config(self):
        return ("tuple", tuple(t._config() for t in self.field_types))

    def __repr__(self):
        return f"Tuple{self.field_types}"


class RowTypeInfo(TypeInformation):
    """Named, ordered fields — the schema type of the Table layer and the
    evolution unit for state (fields may be added/removed across restores)."""

    def __init__(self, names: Sequence[str], types: Sequence[TypeInformation]):
        assert len(names) == len(types)
        self.names = list(names)
        self.types = list(types)

    @property
    def arity(self):
        return len(self.names)

    def serializer(self):
        from flink_tpu.core.serializers import RowSerializer

        return RowSerializer(self.names, [t.serializer() for t in self.types])

    def field_index(self, name: str) -> int:
        return self.names.index(name)

    def _config(self):
        return ("row", tuple(self.names), tuple(t._config() for t in self.types))

    def __repr__(self):
        return "Row(" + ", ".join(f"{n}: {t!r}" for n, t in zip(self.names, self.types)) + ")"


class DataclassTypeInfo(RowTypeInfo):
    """POJO analogue: a dataclass is a row with a reconstructor."""

    def __init__(self, cls: type, fields: Sequence[Tuple[str, TypeInformation]]):
        super().__init__([n for n, _ in fields], [t for _, t in fields])
        self.cls = cls

    def serializer(self):
        from flink_tpu.core.serializers import DataclassSerializer

        return DataclassSerializer(self.cls, self.names, [t.serializer() for t in self.types])

    def _config(self):
        return ("dataclass", f"{self.cls.__module__}.{self.cls.__qualname__}",
                tuple(self.names), tuple(t._config() for t in self.types))


class ListTypeInfo(TypeInformation):
    def __init__(self, elem: TypeInformation):
        self.elem = elem

    def serializer(self):
        from flink_tpu.core.serializers import ListSerializer

        return ListSerializer(self.elem.serializer())

    def _config(self):
        return ("list", self.elem._config())


class MapTypeInfo(TypeInformation):
    def __init__(self, key: TypeInformation, value: TypeInformation):
        self.key = key
        self.value = value

    def serializer(self):
        from flink_tpu.core.serializers import MapSerializer

        return MapSerializer(self.key.serializer(), self.value.serializer())

    def _config(self):
        return ("map", self.key._config(), self.value._config())


class PickledTypeInfo(TypeInformation):
    """Fallback for arbitrary objects (the Kryo analogue)."""

    def serializer(self):
        from flink_tpu.core.serializers import PickleSerializer

        return PickleSerializer()

    def _config(self):
        return ("pickled",)


def _mk_basic():
    from flink_tpu.core import serializers as s

    return {
        "LONG": BasicTypeInfo("Long", np.int64, lambda: s.LongSerializer()),
        "INT": BasicTypeInfo("Int", np.int32, lambda: s.IntSerializer()),
        "DOUBLE": BasicTypeInfo("Double", np.float64, lambda: s.DoubleSerializer()),
        "FLOAT": BasicTypeInfo("Float", np.float32, lambda: s.FloatSerializer()),
        "BOOLEAN": BasicTypeInfo("Boolean", np.bool_, lambda: s.BooleanSerializer()),
        "STRING": BasicTypeInfo("String", None, lambda: s.StringSerializer()),
        "BYTES": BasicTypeInfo("Bytes", None, lambda: s.BytesSerializer()),
    }


class Types:
    """Static type catalogue (org.apache.flink.api.common.typeinfo.Types)."""

    LONG: BasicTypeInfo
    INT: BasicTypeInfo
    DOUBLE: BasicTypeInfo
    FLOAT: BasicTypeInfo
    BOOLEAN: BasicTypeInfo
    STRING: BasicTypeInfo
    BYTES: BasicTypeInfo
    PICKLED = PickledTypeInfo()

    @staticmethod
    def ROW(names: Sequence[str], types: Sequence[TypeInformation]) -> RowTypeInfo:
        return RowTypeInfo(names, types)

    @staticmethod
    def TUPLE(types: Sequence[TypeInformation]) -> TupleTypeInfo:
        return TupleTypeInfo(types)

    @staticmethod
    def LIST(elem: TypeInformation) -> ListTypeInfo:
        return ListTypeInfo(elem)

    @staticmethod
    def MAP(k: TypeInformation, v: TypeInformation) -> MapTypeInfo:
        return MapTypeInfo(k, v)


for _name, _ti in _mk_basic().items():
    setattr(Types, _name, _ti)
