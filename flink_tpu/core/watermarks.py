"""Watermark generation and combination.

Capability parity with the reference's event-time API
(flink-core .../eventtime/WatermarkStrategy.java:56, WatermarkGenerator,
BoundedOutOfOrdernessWatermarks, WatermarksWithIdleness) and the multi-input
combine rule (StatusWatermarkValve.java:48: min over non-idle channels,
SURVEY.md §2.10).

In the stepped-dataflow runtime a watermark is a per-source scalar advanced on
host between device steps; the valve combines per-channel watermarks before a
step is launched, so device programs see a single already-combined watermark.
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, List, Optional, Sequence

import numpy as np

from flink_tpu.core.time import MIN_WATERMARK, MAX_WATERMARK


@dataclasses.dataclass(frozen=True)
class Watermark:
    timestamp: int

    def __le__(self, other): return self.timestamp <= other.timestamp
    def __lt__(self, other): return self.timestamp < other.timestamp


class WatermarkGenerator:
    """on_event/on_periodic_emit contract (WatermarkGenerator.java)."""

    def on_event(self, event, event_timestamp: int) -> Optional[int]:
        """Returns a new watermark to emit now (punctuated), or None."""
        return None

    def on_periodic_emit(self) -> Optional[int]:
        """Returns the watermark to emit at a periodic checkpoint, or None."""
        return None

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass


class BoundedOutOfOrdernessWatermarks(WatermarkGenerator):
    """watermark = maxTimestamp - outOfOrderness - 1
    (BoundedOutOfOrdernessWatermarks.java semantics)."""

    def __init__(self, max_out_of_orderness_ms: int):
        self._delay = max_out_of_orderness_ms
        self._max_ts = MIN_WATERMARK + self._delay + 1

    def on_event(self, event, event_timestamp: int) -> Optional[int]:
        if event_timestamp > self._max_ts:
            self._max_ts = event_timestamp
        return None

    def on_periodic_emit(self) -> Optional[int]:
        return self._max_ts - self._delay - 1

    def on_batch_np(self, timestamps: np.ndarray) -> Optional[int]:
        """Vectorized batch form for the host ingest path."""
        if timestamps.size:
            m = int(timestamps.max())
            if m > self._max_ts:
                self._max_ts = m
        return self.on_periodic_emit()

    def snapshot(self) -> dict:
        return {"max_ts": self._max_ts}

    def restore(self, snap: dict) -> None:
        self._max_ts = snap["max_ts"]


class MonotonousTimestampsWatermarks(BoundedOutOfOrdernessWatermarks):
    """forMonotonousTimestamps == bounded with 0 delay (AscendingTimestampsWatermarks)."""

    def __init__(self):
        super().__init__(0)


class WatermarkStrategy:
    """Factory mirroring WatermarkStrategy.java:56's static builders."""

    def __init__(
        self,
        generator_factory: Callable[[], WatermarkGenerator],
        timestamp_assigner: Optional[Callable[[object, int], int]] = None,
        idle_timeout_ms: Optional[int] = None,
    ):
        self._generator_factory = generator_factory
        self.timestamp_assigner = timestamp_assigner
        self.idle_timeout_ms = idle_timeout_ms

    @staticmethod
    def for_monotonous_timestamps() -> "WatermarkStrategy":
        return WatermarkStrategy(MonotonousTimestampsWatermarks)

    @staticmethod
    def for_bounded_out_of_orderness(max_out_of_orderness_ms: int) -> "WatermarkStrategy":
        return WatermarkStrategy(lambda: BoundedOutOfOrdernessWatermarks(max_out_of_orderness_ms))

    @staticmethod
    def no_watermarks() -> "WatermarkStrategy":
        return WatermarkStrategy(WatermarkGenerator)

    def with_timestamp_assigner(self, fn: Callable[[object, int], int]) -> "WatermarkStrategy":
        return WatermarkStrategy(self._generator_factory, fn, self.idle_timeout_ms)

    def with_idleness(self, idle_timeout_ms: int) -> "WatermarkStrategy":
        return WatermarkStrategy(self._generator_factory, self.timestamp_assigner, idle_timeout_ms)

    def create_generator(self) -> WatermarkGenerator:
        return self._generator_factory()


@dataclasses.dataclass
class _Channel:
    watermark: int = MIN_WATERMARK
    idle: bool = False
    last_active_ns: int = 0


class WatermarkValve:
    """Combined watermark = min over non-idle channels; a channel that is idle
    is excluded; if all channels are idle the last combined watermark holds.
    (StatusWatermarkValve.inputWatermark:153, idleness handling :199.)

    Also the watermark-alignment point: `max_drift_ms` bounds how far any
    channel may run ahead of the combined watermark before `paused_channels`
    reports it (SourceCoordinator.announceCombinedWatermark:184 analogue).
    """

    def __init__(self, num_channels: int, max_drift_ms: Optional[int] = None):
        self._channels = [_Channel() for _ in range(num_channels)]
        self._combined = MIN_WATERMARK
        self._max_drift = max_drift_ms

    @property
    def combined_watermark(self) -> int:
        return self._combined

    def input_watermark(self, channel: int, watermark: int) -> Optional[int]:
        """Feed a channel watermark; returns new combined watermark if advanced."""
        ch = self._channels[channel]
        ch.idle = False
        if watermark > ch.watermark:
            ch.watermark = watermark
        return self._recompute()

    def mark_idle(self, channel: int) -> Optional[int]:
        self._channels[channel].idle = True
        return self._recompute()

    def mark_active(self, channel: int) -> None:
        self._channels[channel].idle = False

    def _recompute(self) -> Optional[int]:
        active = [c.watermark for c in self._channels if not c.idle]
        if not active:
            return None
        new = min(active)
        if new > self._combined:
            self._combined = new
            return new
        return None

    def paused_channels(self) -> List[int]:
        """Channels exceeding the alignment drift bound (to be paused)."""
        if self._max_drift is None:
            return []
        limit = self._combined + self._max_drift
        return [
            i for i, c in enumerate(self._channels)
            if not c.idle and c.watermark > limit
        ]


class IdlenessTimer:
    """Marks a source channel idle after no records for idle_timeout_ms
    (WatermarksWithIdleness semantics, driven by host wall-clock)."""

    def __init__(self, idle_timeout_ms: int, clock: Callable[[], float] = _time.monotonic):
        self._timeout_s = idle_timeout_ms / 1000.0
        self._clock = clock
        self._last_active = clock()
        self.idle = False

    def activity(self) -> None:
        self._last_active = self._clock()
        self.idle = False

    def check_idle(self) -> bool:
        if not self.idle and self._clock() - self._last_active >= self._timeout_s:
            self.idle = True
        return self.idle
