"""Deployment descriptors (Y2/Y3): Kubernetes manifests, YARN gating."""
