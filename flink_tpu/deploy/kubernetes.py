"""Kubernetes cluster descriptor: JM/TM manifests for a TPU cluster (Y2).

Analogue of flink-kubernetes/.../KubernetesClusterDescriptor.java +
KubernetesResourceManagerDriver.java (the RM creating TM pods) at the
declarative level this framework deploys at: generate the Deployment /
Service / ConfigMap objects (JSON — a strict YAML subset kubectl accepts)
for one JobManager and N TaskManager workers, with the TPU resource
requests and a pod-template decorator hook
(kubeclient/decorators/ analogue).

Apply with: `kubectl apply -f <(python -m flink_tpu.deploy.kubernetes ...)`.
"""

from __future__ import annotations

import base64
import json
import secrets as _secrets
from typing import Callable, Dict, List, Optional


DEFAULT_IMAGE = "flink-tpu:latest"

# where the cluster transport secret (flink_tpu/security) is mounted in
# every JM/TM pod; the runtime picks it up via the env var below
SECRET_MOUNT_PATH = "/etc/flink-tpu/secret"
SECRET_FILE_KEY = "transport.secret"
SECRET_ENV_VAR = "FLINK_TPU_SECURITY_TRANSPORT_SECRET_FILE"


def _container(name: str, args: List[str], image: str, env: Dict[str, str],
               resources: Optional[dict] = None) -> dict:
    c = {
        "name": name,
        "image": image,
        "args": args,
        "env": [{"name": k, "value": str(v)} for k, v in env.items()],
        "ports": [],
    }
    if resources:
        c["resources"] = resources
    return c


class KubernetesClusterDescriptor:
    def __init__(
        self,
        cluster_id: str,
        *,
        namespace: str = "default",
        image: str = DEFAULT_IMAGE,
        taskmanagers: int = 2,
        slots_per_tm: int = 1,
        tpu_type: Optional[str] = None,        # e.g. "v5litepod-8"
        tpu_chips_per_tm: int = 0,             # google.com/tpu resource count
        jm_port: int = 6123,
        pod_decorator: Optional[Callable[[dict], dict]] = None,
        transport_secret: Optional[str] = None,
        secret_name: Optional[str] = None,
    ):
        self.cluster_id = cluster_id
        self.namespace = namespace
        self.image = image
        self.taskmanagers = taskmanagers
        self.slots_per_tm = slots_per_tm
        self.tpu_type = tpu_type
        self.tpu_chips_per_tm = tpu_chips_per_tm
        self.jm_port = jm_port
        self.pod_decorator = pod_decorator or (lambda pod: pod)
        # transport auth (flink_tpu/security): every pod mounts one K8s
        # Secret and points the runtime at it. Pass secret_name to reference
        # an EXISTING Secret (holding key 'transport.secret') and keep the
        # secret value out of the rendered manifests; otherwise a fresh
        # random secret is generated per descriptor and rendered inline.
        # CAUTION with the generated form: every new render carries a NEW
        # random value, so re-applying regenerated manifests to a live
        # cluster rotates the secret mid-flight and splits old/new pods
        # until all restart — for anything long-lived, provision the Secret
        # once (`kubectl create secret generic <name>
        # --from-literal=transport.secret=$(openssl rand -hex 32)`) and
        # render with secret_name=<name> (CLI: --secret-name), or pin the
        # value with transport_secret= (CLI: --secret-file).
        self.secret_name = secret_name or f"{cluster_id}-transport-secret"
        self.render_secret = secret_name is None
        self.transport_secret = transport_secret or _secrets.token_hex(32)

    # -- manifests ----------------------------------------------------------
    def jobmanager_service(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{self.cluster_id}-jobmanager",
                         "namespace": self.namespace,
                         "labels": {"app": self.cluster_id, "component": "jobmanager"}},
            "spec": {
                "selector": {"app": self.cluster_id, "component": "jobmanager"},
                "ports": [
                    {"name": "rpc", "port": self.jm_port},
                    {"name": "rest", "port": 8081},
                ],
            },
        }

    def transport_secret_manifest(self) -> dict:
        """The cluster transport secret as a K8s Secret (Opaque). Only part
        of manifests() when this descriptor GENERATED the secret; with
        secret_name= the operator provisions it out of band:
        `kubectl create secret generic <name> --from-literal=transport.secret=...`"""
        return {
            "apiVersion": "v1",
            "kind": "Secret",
            "metadata": {"name": self.secret_name, "namespace": self.namespace,
                         "labels": {"app": self.cluster_id}},
            "type": "Opaque",
            "data": {SECRET_FILE_KEY: base64.b64encode(
                self.transport_secret.encode()).decode()},
        }

    def _mount_transport_secret(self, container: dict) -> dict:
        container.setdefault("volumeMounts", []).append(
            {"name": "transport-secret", "mountPath": SECRET_MOUNT_PATH,
             "readOnly": True})
        container["env"].append(
            {"name": SECRET_ENV_VAR,
             "value": f"{SECRET_MOUNT_PATH}/{SECRET_FILE_KEY}"})
        return container

    def _pod(self, component: str, container: dict, extra_spec: Optional[dict] = None) -> dict:
        spec: dict = {"containers": [self._mount_transport_secret(container)],
                      "volumes": [{"name": "transport-secret",
                                   "secret": {"secretName": self.secret_name,
                                              "defaultMode": 0o400}}]}
        if component == "taskmanager" and self.tpu_type:
            # TPU scheduling: nodeSelector + resource request per GKE conventions
            spec["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": self.tpu_type,
            }
        if extra_spec:
            spec.update(extra_spec)
        pod = {
            "metadata": {"labels": {"app": self.cluster_id, "component": component}},
            "spec": spec,
        }
        return self.pod_decorator(pod)

    def jobmanager_deployment(self) -> dict:
        container = _container(
            "jobmanager",
            ["python", "-m", "flink_tpu.runtime.cluster", "jobmanager",
             "--host", "0.0.0.0", "--port", str(self.jm_port),
             "--checkpoint-dir", "/checkpoints", "--checkpoint-interval", "30"],
            self.image, {"JAX_PLATFORMS": "cpu"},
        )
        container["ports"] = [{"containerPort": self.jm_port}]
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{self.cluster_id}-jobmanager",
                         "namespace": self.namespace},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": self.cluster_id,
                                             "component": "jobmanager"}},
                "template": self._pod("jobmanager", container),
            },
        }

    def taskmanager_deployment(self) -> dict:
        resources = None
        if self.tpu_chips_per_tm:
            resources = {"limits": {"google.com/tpu": self.tpu_chips_per_tm}}
        container = _container(
            "taskmanager",
            ["python", "-m", "flink_tpu.runtime.cluster", "taskmanager",
             "--jobmanager", f"{self.cluster_id}-jobmanager:{self.jm_port}",
             "--slots", str(self.slots_per_tm)],
            self.image, {}, resources,
        )
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{self.cluster_id}-taskmanager",
                         "namespace": self.namespace},
            "spec": {
                "replicas": self.taskmanagers,
                "selector": {"matchLabels": {"app": self.cluster_id,
                                             "component": "taskmanager"}},
                "template": self._pod("taskmanager", container),
            },
        }

    def manifests(self) -> List[dict]:
        out = [self.transport_secret_manifest()] if self.render_secret else []
        return out + [
            self.jobmanager_service(),
            self.jobmanager_deployment(),
            self.taskmanager_deployment(),
        ]

    def render(self) -> str:
        """kubectl-applicable multi-document output (JSON List object)."""
        return json.dumps({"apiVersion": "v1", "kind": "List",
                           "items": self.manifests()}, indent=2)


class YarnClusterDescriptor:
    """YARN deployment gate (Y3): the reference ships flink-yarn; this
    environment has no Hadoop — constructing the descriptor states that
    clearly instead of failing deep inside a submission."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "YARN deployment requires a Hadoop/YARN client environment, which "
            "this build does not vendor; deploy with KubernetesClusterDescriptor "
            "or bin/start-cluster.sh (standalone)"
        )


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="flink_tpu.deploy.kubernetes")
    p.add_argument("cluster_id")
    p.add_argument("--namespace", default="default")
    p.add_argument("--image", default=DEFAULT_IMAGE)
    p.add_argument("--taskmanagers", type=int, default=2)
    p.add_argument("--slots", type=int, default=1)
    p.add_argument("--tpu-type", default=None)
    p.add_argument("--tpu-chips", type=int, default=0)
    p.add_argument("--secret-name", default=None,
                   help="reference an existing K8s Secret (key "
                        "'transport.secret') instead of rendering a fresh "
                        "random one — REQUIRED for stable re-renders of a "
                        "live cluster")
    p.add_argument("--secret-file", default=None,
                   help="pin the rendered Secret's value from a local file")
    a = p.parse_args(argv)
    if a.secret_name and a.secret_file:
        p.error("--secret-name and --secret-file are mutually exclusive: a "
                "referenced Secret is provisioned out of band, so a pinned "
                "local value would be silently ignored")
    secret_value = None
    if a.secret_file:
        # same read path as the runtime: rejects an empty/whitespace file
        # instead of silently rendering a fresh random secret in its place
        from flink_tpu.security.transport import _read_secret_file

        secret_value = _read_secret_file(a.secret_file).decode()
    print(KubernetesClusterDescriptor(
        a.cluster_id, namespace=a.namespace, image=a.image,
        taskmanagers=a.taskmanagers, slots_per_tm=a.slots,
        tpu_type=a.tpu_type, tpu_chips_per_tm=a.tpu_chips,
        transport_secret=secret_value, secret_name=a.secret_name,
    ).render())


if __name__ == "__main__":
    main()
