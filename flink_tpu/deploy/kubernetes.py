"""Kubernetes cluster descriptor: JM/TM manifests for a TPU cluster (Y2).

Analogue of flink-kubernetes/.../KubernetesClusterDescriptor.java +
KubernetesResourceManagerDriver.java (the RM creating TM pods) at the
declarative level this framework deploys at: generate the Deployment /
Service / ConfigMap objects (JSON — a strict YAML subset kubectl accepts)
for one JobManager and N TaskManager workers, with the TPU resource
requests and a pod-template decorator hook
(kubeclient/decorators/ analogue).

Apply with: `kubectl apply -f <(python -m flink_tpu.deploy.kubernetes ...)`.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional


DEFAULT_IMAGE = "flink-tpu:latest"


def _container(name: str, args: List[str], image: str, env: Dict[str, str],
               resources: Optional[dict] = None) -> dict:
    c = {
        "name": name,
        "image": image,
        "args": args,
        "env": [{"name": k, "value": str(v)} for k, v in env.items()],
        "ports": [],
    }
    if resources:
        c["resources"] = resources
    return c


class KubernetesClusterDescriptor:
    def __init__(
        self,
        cluster_id: str,
        *,
        namespace: str = "default",
        image: str = DEFAULT_IMAGE,
        taskmanagers: int = 2,
        slots_per_tm: int = 1,
        tpu_type: Optional[str] = None,        # e.g. "v5litepod-8"
        tpu_chips_per_tm: int = 0,             # google.com/tpu resource count
        jm_port: int = 6123,
        pod_decorator: Optional[Callable[[dict], dict]] = None,
    ):
        self.cluster_id = cluster_id
        self.namespace = namespace
        self.image = image
        self.taskmanagers = taskmanagers
        self.slots_per_tm = slots_per_tm
        self.tpu_type = tpu_type
        self.tpu_chips_per_tm = tpu_chips_per_tm
        self.jm_port = jm_port
        self.pod_decorator = pod_decorator or (lambda pod: pod)

    # -- manifests ----------------------------------------------------------
    def jobmanager_service(self) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{self.cluster_id}-jobmanager",
                         "namespace": self.namespace,
                         "labels": {"app": self.cluster_id, "component": "jobmanager"}},
            "spec": {
                "selector": {"app": self.cluster_id, "component": "jobmanager"},
                "ports": [
                    {"name": "rpc", "port": self.jm_port},
                    {"name": "rest", "port": 8081},
                ],
            },
        }

    def _pod(self, component: str, container: dict, extra_spec: Optional[dict] = None) -> dict:
        spec: dict = {"containers": [container]}
        if component == "taskmanager" and self.tpu_type:
            # TPU scheduling: nodeSelector + resource request per GKE conventions
            spec["nodeSelector"] = {
                "cloud.google.com/gke-tpu-accelerator": self.tpu_type,
            }
        if extra_spec:
            spec.update(extra_spec)
        pod = {
            "metadata": {"labels": {"app": self.cluster_id, "component": component}},
            "spec": spec,
        }
        return self.pod_decorator(pod)

    def jobmanager_deployment(self) -> dict:
        container = _container(
            "jobmanager",
            ["python", "-m", "flink_tpu.runtime.cluster", "jobmanager",
             "--host", "0.0.0.0", "--port", str(self.jm_port),
             "--checkpoint-dir", "/checkpoints", "--checkpoint-interval", "30"],
            self.image, {"JAX_PLATFORMS": "cpu"},
        )
        container["ports"] = [{"containerPort": self.jm_port}]
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{self.cluster_id}-jobmanager",
                         "namespace": self.namespace},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": self.cluster_id,
                                             "component": "jobmanager"}},
                "template": self._pod("jobmanager", container),
            },
        }

    def taskmanager_deployment(self) -> dict:
        resources = None
        if self.tpu_chips_per_tm:
            resources = {"limits": {"google.com/tpu": self.tpu_chips_per_tm}}
        container = _container(
            "taskmanager",
            ["python", "-m", "flink_tpu.runtime.cluster", "taskmanager",
             "--jobmanager", f"{self.cluster_id}-jobmanager:{self.jm_port}",
             "--slots", str(self.slots_per_tm)],
            self.image, {}, resources,
        )
        return {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{self.cluster_id}-taskmanager",
                         "namespace": self.namespace},
            "spec": {
                "replicas": self.taskmanagers,
                "selector": {"matchLabels": {"app": self.cluster_id,
                                             "component": "taskmanager"}},
                "template": self._pod("taskmanager", container),
            },
        }

    def manifests(self) -> List[dict]:
        return [
            self.jobmanager_service(),
            self.jobmanager_deployment(),
            self.taskmanager_deployment(),
        ]

    def render(self) -> str:
        """kubectl-applicable multi-document output (JSON List object)."""
        return json.dumps({"apiVersion": "v1", "kind": "List",
                           "items": self.manifests()}, indent=2)


class YarnClusterDescriptor:
    """YARN deployment gate (Y3): the reference ships flink-yarn; this
    environment has no Hadoop — constructing the descriptor states that
    clearly instead of failing deep inside a submission."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "YARN deployment requires a Hadoop/YARN client environment, which "
            "this build does not vendor; deploy with KubernetesClusterDescriptor "
            "or bin/start-cluster.sh (standalone)"
        )


def main(argv=None) -> None:
    import argparse

    p = argparse.ArgumentParser(prog="flink_tpu.deploy.kubernetes")
    p.add_argument("cluster_id")
    p.add_argument("--namespace", default="default")
    p.add_argument("--image", default=DEFAULT_IMAGE)
    p.add_argument("--taskmanagers", type=int, default=2)
    p.add_argument("--slots", type=int, default=1)
    p.add_argument("--tpu-type", default=None)
    p.add_argument("--tpu-chips", type=int, default=0)
    a = p.parse_args(argv)
    print(KubernetesClusterDescriptor(
        a.cluster_id, namespace=a.namespace, image=a.image,
        taskmanagers=a.taskmanagers, slots_per_tm=a.slots,
        tpu_type=a.tpu_type, tpu_chips_per_tm=a.tpu_chips,
    ).render())


if __name__ == "__main__":
    main()
