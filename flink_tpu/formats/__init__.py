"""Record formats (K2 analogue of flink-formats): pluggable encoders/
decoders used by the file source/sink.

In-repo: CSV (native C++ codec path, native/flink_tpu_native.cpp
codec_parse_csv), JSON lines, Avro binary (self-contained reader/writer for
the core type subset — the reference vendors flink-avro), raw bytes.
Parquet/ORC are gated on pyarrow being installed (the reference ships them
as separate format jars; this image has no pyarrow, so the registration
degrades with a clear error instead of an import crash).
"""

from flink_tpu.formats.registry import FORMATS, get_format  # noqa: F401
