"""Formatted file connectors: FileSource/FileSink x the format registry.

The composition point of K1 x K2 in the reference: FileSource takes a
format's DeserializationSchema / BulkFormat, FileSink a BulkWriter factory.
Here the same Source/Sink SPIs (connectors/source.py:87, sink.py:44) are
implemented over `flink_tpu.formats.get_format`, rows are dicts.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from flink_tpu.connectors.sink import Committer, Sink, SinkWriter, _FileCommitter, _PendingFile
from flink_tpu.connectors.source import Batch, Source, SourceReader, SourceSplit, SplitEnumerator
from flink_tpu.core.records import MIN_TIMESTAMP
from flink_tpu.formats.registry import Format, get_format
from flink_tpu.utils.arrays import obj_array


class _FormattedFileReader(SourceReader):
    def __init__(self, fmt: Format, timestamp_fn):
        self._fmt = fmt
        self._ts_fn = timestamp_fn
        self._path: Optional[str] = None
        self._offset = 0  # row offset (resumable split position)
        self._rows: Optional[List[dict]] = None

    def add_split(self, split: SourceSplit) -> None:
        self._path = split.payload["path"]
        self._offset = split.payload.get("offset", 0)
        self._rows = None

    def poll_batch(self, max_records: int) -> Optional[Batch]:
        if self._path is None:
            return None
        if self._rows is None:
            self._rows = self._fmt.read_file(self._path)
        if self._offset >= len(self._rows):
            self._path = None
            return None
        chunk = self._rows[self._offset : self._offset + max_records]
        self._offset += len(chunk)
        if self._ts_fn is not None:
            ts = np.asarray([self._ts_fn(r) for r in chunk], dtype=np.int64)
        else:
            ts = np.full(len(chunk), MIN_TIMESTAMP, dtype=np.int64)
        return Batch(obj_array(chunk), ts)

    def snapshot_position(self) -> Dict[str, Any]:
        return {"path": self._path, "offset": self._offset}

    def restore_position(self, state: Dict[str, Any]) -> None:
        self._path = state["path"]
        self._offset = state["offset"]
        self._rows = None


class FormattedFileSource(Source):
    """Rows-from-files in any registered format (FileSource.java:98 x K2)."""

    def __init__(self, paths: Sequence[str], format: str = "json",
                 timestamp_fn: Optional[Callable[[dict], int]] = None, **format_kwargs):
        self.paths = [str(p) for p in paths]
        self.format_name = format
        self.format_kwargs = format_kwargs
        self.timestamp_fn = timestamp_fn

    def create_enumerator(self) -> SplitEnumerator:
        return SplitEnumerator(
            [SourceSplit(f"file-{i}", {"path": p}) for i, p in enumerate(self.paths)]
        )

    def create_reader(self) -> SourceReader:
        return _FormattedFileReader(
            get_format(self.format_name, **self.format_kwargs), self.timestamp_fn
        )


class _FormattedFileWriter(SinkWriter):
    """Buffers an epoch's rows, writes one part file per epoch through the
    format on prepare_commit (2PC: temp file renamed on commit — the
    exactly-once discipline of the plain FileSink)."""

    def __init__(self, directory: str, prefix: str, fmt: Format, ext: str):
        self.directory = directory
        self.prefix = prefix
        self.fmt = fmt
        self.ext = ext
        self._rows: List[dict] = []
        os.makedirs(directory, exist_ok=True)

    def write(self, value, timestamp=None) -> None:
        self._rows.append(value)

    def prepare_commit(self, epoch_id: str = "final") -> List[_PendingFile]:
        rows, self._rows = self._rows, []
        fd, tmp = tempfile.mkstemp(prefix=f".{self.prefix}-inprogress-", dir=self.directory)
        with os.fdopen(fd, "wb") as f:
            self.fmt.write(rows, f)
        final = os.path.join(self.directory, f"{self.prefix}-part-{epoch_id}.{self.ext}")
        return [_PendingFile(tmp, final)]

    def close(self) -> None:
        self._rows = []


class FormattedFileSink(Sink):
    def __init__(self, directory: str, format: str = "json", prefix: str = "out",
                 **format_kwargs):
        self.directory = directory
        self.format_name = format
        self.format_kwargs = format_kwargs
        self.prefix = prefix

    def create_writer(self) -> SinkWriter:
        return _FormattedFileWriter(
            self.directory, self.prefix,
            get_format(self.format_name, **self.format_kwargs), self.format_name,
        )

    def create_committer(self) -> Optional[Committer]:
        return _FileCommitter()
