"""Format SPI + registry: encode/decode row dicts to file bytes.

The reference's format modules (flink-formats/: flink-json, flink-csv,
flink-avro, flink-parquet, ...) plug into sources/sinks as
DeserializationSchema / BulkWriter factories; here a `Format` couples both
directions behind one name.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, Dict, Iterable, List, Optional

from flink_tpu.core.serializers import read_varint, write_varint


class Format:
    name: str = ""

    def write(self, rows: Iterable[dict], out: io.BufferedIOBase) -> None:
        raise NotImplementedError

    def read(self, inp: io.BufferedIOBase) -> List[dict]:
        raise NotImplementedError

    # convenience
    def write_file(self, rows: Iterable[dict], path: str) -> None:
        with open(path, "wb") as f:
            self.write(rows, f)

    def read_file(self, path: str) -> List[dict]:
        with open(path, "rb") as f:
            return self.read(f)


class JsonLinesFormat(Format):
    """One JSON object per line (flink-json's newline-delimited mode)."""

    name = "json"

    def write(self, rows, out):
        for r in rows:
            out.write(json.dumps(r, separators=(",", ":")).encode() + b"\n")

    def read(self, inp):
        return [json.loads(line) for line in inp.read().splitlines() if line.strip()]


class CsvFormat(Format):
    """RFC-4180 CSV (stdlib csv handles quoting/escaping); numeric columns
    parse back to int/float. The fast path for columnar batches is the
    native codec (native/flink_tpu_native.cpp codec_parse_csv)."""

    name = "csv"

    def write(self, rows, out):
        import csv

        rows = list(rows)
        if not rows:
            return
        cols = sorted({k for r in rows for k in r})
        text = io.StringIO()
        w = csv.DictWriter(text, fieldnames=cols)
        w.writeheader()
        for r in rows:
            w.writerow({c: r.get(c, "") for c in cols})
        out.write(text.getvalue().encode())

    def read(self, inp):
        import csv

        text = io.StringIO(inp.read().decode())
        out = []
        for rec in csv.DictReader(text):
            row = {}
            for c, v in rec.items():
                try:
                    row[c] = int(v)
                except (TypeError, ValueError):
                    try:
                        row[c] = float(v)
                    except (TypeError, ValueError):
                        row[c] = v
            out.append(row)
        return out


# ---------------------------------------------------------------------------
# Avro binary (self-contained subset: null/boolean/long/double/string/bytes)
# ---------------------------------------------------------------------------

def _zigzag_write(out, n: int) -> None:
    write_varint(out, (n << 1) ^ (n >> 63))


def _zigzag_read(inp) -> int:
    u = read_varint(inp)
    return (u >> 1) ^ -(u & 1)


_AVRO_WRITERS = {
    "null": lambda o, v: None,
    "boolean": lambda o, v: o.write(b"\x01" if v else b"\x00"),
    "long": lambda o, v: _zigzag_write(o, int(v)),
    "double": lambda o, v: o.write(struct.pack("<d", float(v))),
    "string": lambda o, v: (_zigzag_write(o, len(v.encode())), o.write(v.encode())),
    "bytes": lambda o, v: (_zigzag_write(o, len(v)), o.write(v)),
}

_AVRO_READERS = {
    "null": lambda i: None,
    "boolean": lambda i: i.read(1) == b"\x01",
    "long": _zigzag_read,
    "double": lambda i: struct.unpack("<d", i.read(8))[0],
    "string": lambda i: i.read(_zigzag_read(i)).decode(),
    "bytes": lambda i: i.read(_zigzag_read(i)),
}


class AvroFormat(Format):
    """Avro binary encoding with an embedded record schema (container-file
    style: magic, JSON schema header, record count, then the standard Avro
    binary encoding of each record; flink-avro analogue).

    Fields may be declared nullable via ["null", <type>] unions.
    """

    name = "avro"
    MAGIC = b"FTAv1\x00"

    def __init__(self, schema: Optional[Dict[str, Any]] = None):
        self.schema = schema

    @staticmethod
    def infer_schema(rows) -> dict:
        """Schema over ALL rows: fields missing in some rows (or ever None)
        become nullable unions, so heterogeneous rows neither crash mid-write
        nor lose columns."""
        def ftype(v):
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, int):
                return "long"
            if isinstance(v, float):
                return "double"
            if isinstance(v, bytes):
                return "bytes"
            return "string"

        seen: Dict[str, Optional[str]] = {}
        nullable = set()
        order: List[str] = []
        for r in rows:
            for k, v in r.items():
                if k not in seen:
                    seen[k] = None
                    order.append(k)
                if v is None:
                    nullable.add(k)
                elif seen[k] is None:
                    seen[k] = ftype(v)
        n = len(list(rows)) if not isinstance(rows, list) else len(rows)
        for r in rows:
            for k in order:
                if k not in r:
                    nullable.add(k)
        fields = []
        for k in order:
            t = seen[k] or "string"
            fields.append({"name": k, "type": ["null", t] if k in nullable else t})
        return {"type": "record", "name": "Row", "fields": fields}

    def _write_value(self, out, ftype, value):
        if isinstance(ftype, list):  # union: write the branch index, then value
            if value is None:
                idx = ftype.index("null")
                _zigzag_write(out, idx)
                return
            idx = next(i for i, t in enumerate(ftype) if t != "null")
            _zigzag_write(out, idx)
            _AVRO_WRITERS[ftype[idx]](out, value)
            return
        _AVRO_WRITERS[ftype](out, value)

    def _read_value(self, inp, ftype):
        if isinstance(ftype, list):
            idx = _zigzag_read(inp)
            t = ftype[idx]
            return None if t == "null" else _AVRO_READERS[t](inp)
        return _AVRO_READERS[ftype](inp)

    def write(self, rows, out):
        rows = list(rows)
        schema = self.schema or (self.infer_schema(rows) if rows else
                                 {"type": "record", "name": "Row", "fields": []})
        header = json.dumps(schema).encode()
        out.write(self.MAGIC)
        write_varint(out, len(header))
        out.write(header)
        write_varint(out, len(rows))
        for r in rows:
            for field in schema["fields"]:
                self._write_value(out, field["type"], r.get(field["name"]))

    def read(self, inp):
        magic = inp.read(len(self.MAGIC))
        if magic != self.MAGIC:
            raise ValueError("not an avro container written by this framework")
        schema = json.loads(inp.read(read_varint(inp)))
        n = read_varint(inp)
        out = []
        for _ in range(n):
            out.append({
                f["name"]: self._read_value(inp, f["type"]) for f in schema["fields"]
            })
        return out


class ParquetFormat(Format):
    """Gated on pyarrow (the image ships none — mirror of the reference's
    optional format jars)."""

    name = "parquet"

    def __init__(self):
        try:
            import pyarrow  # noqa: F401
            import pyarrow.parquet  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "parquet format requires pyarrow, which is not installed in "
                "this environment; use 'avro', 'json' or 'csv'"
            ) from e

    def write(self, rows, out):
        import pyarrow as pa
        import pyarrow.parquet as pq

        rows = list(rows)
        table = pa.Table.from_pylist(rows)
        pq.write_table(table, out)

    def read(self, inp):
        import pyarrow.parquet as pq

        return pq.read_table(inp).to_pylist()


FORMATS = {
    "json": JsonLinesFormat,
    "csv": CsvFormat,
    "avro": AvroFormat,
    "parquet": ParquetFormat,
}


def get_format(name: str, **kwargs) -> Format:
    try:
        factory = FORMATS[name]
    except KeyError:
        raise ValueError(f"unknown format {name!r}; available: {sorted(FORMATS)}")
    return factory(**kwargs)
