"""Cloud filesystem drivers for the scheme-routed FileSystem SPI (C4)."""
