"""Object-store FileSystem drivers: s3:// (SigV4 REST) and gs:// (JSON API).

The analogue of the reference's flink-filesystems plugin family
(flink-s3-fs-hadoop/presto, flink-gs-fs-hadoop, flink-azure-fs-hadoop...):
cloud object stores behind the same scheme-routed `FileSystem` SPI that
checkpoint storage, savepoints, file sources/sinks and HA stores consume.
No vendor SDK dependency: S3 speaks the REST API with AWS Signature V4
computed from stdlib hmac/hashlib; GCS speaks the JSON/upload API with a
bearer-token provider. Both route requests through an injectable
`transport(method, url, headers, body) -> (status, headers, body)`, so the
drivers run against real endpoints (default urllib transport), S3-compatible
stores (MinIO/GCS-interop via `endpoint`), and the in-process fakes the
tests use.

Checkpoint-storage semantics: `write` is an atomic full-object PUT — object
stores give atomic replace for free, which is exactly the property the
FsCheckpointStorage rename protocol emulates on POSIX.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from flink_tpu.core.fs import FileSystem, register_file_system

Transport = Callable[[str, str, Dict[str, str], Optional[bytes]],
                     Tuple[int, Dict[str, str], bytes]]


def urllib_transport(method: str, url: str, headers: Dict[str, str],
                     body: Optional[bytes]):
    req = urllib.request.Request(url, data=body, method=method)
    for k, v in headers.items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _split(path: str) -> Tuple[str, str]:
    u = urllib.parse.urlparse(path)
    return u.netloc, u.path.lstrip("/")


class S3FileSystem(FileSystem):
    """s3:// driver speaking the S3 REST API with AWS Signature V4."""

    scheme = "s3"

    def __init__(self, access_key: str, secret_key: str,
                 region: str = "us-east-1",
                 endpoint: Optional[str] = None,
                 transport: Transport = urllib_transport,
                 clock=None):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.endpoint = (endpoint or "https://s3.{region}.amazonaws.com").format(
            region=region)
        self.transport = transport
        self.clock = clock or (
            lambda: datetime.datetime.now(datetime.timezone.utc))

    # -- SigV4 ------------------------------------------------------------
    def _sign(self, method: str, bucket: str, key: str,
              query: Dict[str, str], body: bytes) -> Tuple[str, Dict[str, str]]:
        now = self.clock()
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = urllib.parse.urlparse(self.endpoint).netloc
        payload_hash = hashlib.sha256(body or b"").hexdigest()
        canonical_uri = "/" + urllib.parse.quote(f"{bucket}/{key}" if key else bucket)
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
            for k, v in sorted(query.items())
        )
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        signed = ";".join(sorted(headers))
        canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
        canonical_request = "\n".join([
            method, canonical_uri, canonical_query, canonical_headers,
            signed, payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ])

        def h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(h(h(h(b"AWS4" + self.secret_key.encode(), datestamp),
                  self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        url = self.endpoint + canonical_uri
        if canonical_query:
            url += "?" + canonical_query
        return url, headers

    def _req(self, method: str, bucket: str, key: str,
             query: Optional[Dict[str, str]] = None,
             body: Optional[bytes] = None) -> Tuple[int, Dict[str, str], bytes]:
        url, headers = self._sign(method, bucket, key, query or {}, body or b"")
        return self.transport(method, url, headers, body)

    # -- FileSystem SPI ---------------------------------------------------
    def read(self, path: str) -> bytes:
        bucket, key = _split(path)
        status, _h, body = self._req("GET", bucket, key)
        if status == 404:
            raise FileNotFoundError(path)
        if status != 200:
            raise OSError(f"s3 GET {path}: HTTP {status}: {body[:200]!r}")
        return body

    def write(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        status, _h, body = self._req("PUT", bucket, key, body=data)
        if status not in (200, 201):
            raise OSError(f"s3 PUT {path}: HTTP {status}: {body[:200]!r}")

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        status, _h, _b = self._req("HEAD", bucket, key)
        if status == 200:
            return True
        # a "directory" exists if any object lives under the prefix
        return bool(self._list_keys(bucket, key.rstrip("/") + "/", max_keys=1))

    page_size = 1000

    def _list_keys(self, bucket: str, prefix: str,
                   max_keys: Optional[int] = None) -> List[str]:
        import re

        keys: List[str] = []
        token: Optional[str] = None
        while True:
            query = {"list-type": "2", "prefix": prefix,
                     "max-keys": str(self.page_size)}
            if token:
                query["continuation-token"] = token
            status, _h, body = self._req("GET", bucket, "", query=query)
            if status != 200:
                raise OSError(f"s3 LIST {bucket}/{prefix}: HTTP {status}")
            text = body.decode()
            keys.extend(re.findall(r"<Key>([^<]+)</Key>", text))
            if max_keys is not None and len(keys) >= max_keys:
                return keys[:max_keys]
            m = re.search(r"<NextContinuationToken>([^<]+)"
                          r"</NextContinuationToken>", text)
            if not m:
                return keys
            token = m.group(1)

    def list(self, path: str) -> List[str]:
        bucket, key = _split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        return sorted(
            f"s3://{bucket}/{k}" for k in self._list_keys(bucket, prefix)
        )

    def delete(self, path: str, recursive: bool = False) -> None:
        bucket, key = _split(path)
        keys = [key]
        if recursive:
            keys += self._list_keys(bucket, key.rstrip("/") + "/")
        for k in keys:
            status, _h, body = self._req("DELETE", bucket, k)
            if status not in (200, 204, 404):
                raise OSError(f"s3 DELETE {bucket}/{k}: HTTP {status}")

    def mkdirs(self, path: str) -> None:
        pass  # object stores have no directories


class GcsFileSystem(FileSystem):
    """gs:// driver over the GCS JSON API with a bearer-token provider."""

    scheme = "gs"

    def __init__(self, token_provider: Callable[[], str],
                 endpoint: str = "https://storage.googleapis.com",
                 transport: Transport = urllib_transport):
        self.token = token_provider
        self.endpoint = endpoint.rstrip("/")
        self.transport = transport

    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self.token()}"}

    def read(self, path: str) -> bytes:
        bucket, key = _split(path)
        url = (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}?alt=media")
        status, _h, body = self.transport("GET", url, self._headers(), None)
        if status == 404:
            raise FileNotFoundError(path)
        if status != 200:
            raise OSError(f"gcs GET {path}: HTTP {status}: {body[:200]!r}")
        return body

    def write(self, path: str, data: bytes) -> None:
        bucket, key = _split(path)
        url = (f"{self.endpoint}/upload/storage/v1/b/{bucket}/o"
               f"?uploadType=media&name={urllib.parse.quote(key, safe='')}")
        headers = {**self._headers(),
                   "Content-Type": "application/octet-stream"}
        status, _h, body = self.transport("POST", url, headers, data)
        if status not in (200, 201):
            raise OSError(f"gcs PUT {path}: HTTP {status}: {body[:200]!r}")

    def exists(self, path: str) -> bool:
        bucket, key = _split(path)
        url = (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
               f"{urllib.parse.quote(key, safe='')}")
        status, _h, _b = self.transport("GET", url, self._headers(), None)
        if status == 200:
            return True
        return bool(self._list_keys(bucket, key.rstrip("/") + "/", max_results=1))

    page_size = 1000

    def _list_keys(self, bucket: str, prefix: str,
                   max_results: Optional[int] = None) -> List[str]:
        keys: List[str] = []
        token: Optional[str] = None
        while True:
            url = (f"{self.endpoint}/storage/v1/b/{bucket}/o"
                   f"?prefix={urllib.parse.quote(prefix, safe='')}"
                   f"&maxResults={self.page_size}")
            if token:
                url += f"&pageToken={urllib.parse.quote(token, safe='')}"
            status, _h, body = self.transport("GET", url, self._headers(), None)
            if status != 200:
                raise OSError(f"gcs LIST {bucket}/{prefix}: HTTP {status}")
            doc = json.loads(body or b"{}")
            keys.extend(o["name"] for o in doc.get("items", []))
            if max_results is not None and len(keys) >= max_results:
                return keys[:max_results]
            token = doc.get("nextPageToken")
            if not token:
                return keys

    def list(self, path: str) -> List[str]:
        bucket, key = _split(path)
        prefix = key.rstrip("/") + "/" if key else ""
        return sorted(
            f"gs://{bucket}/{k}" for k in self._list_keys(bucket, prefix)
        )

    def delete(self, path: str, recursive: bool = False) -> None:
        bucket, key = _split(path)
        keys = [key]
        if recursive:
            keys += self._list_keys(bucket, key.rstrip("/") + "/")
        for k in keys:
            url = (f"{self.endpoint}/storage/v1/b/{bucket}/o/"
                   f"{urllib.parse.quote(k, safe='')}")
            status, _h, _b = self.transport("DELETE", url, self._headers(), None)
            if status not in (200, 204, 404):
                raise OSError(f"gcs DELETE {bucket}/{k}: HTTP {status}")

    def mkdirs(self, path: str) -> None:
        pass


def register_s3(access_key: str, secret_key: str, *, region: str = "us-east-1",
                endpoint: Optional[str] = None,
                transport: Transport = urllib_transport) -> S3FileSystem:
    fs = S3FileSystem(access_key, secret_key, region=region,
                      endpoint=endpoint, transport=transport)
    register_file_system("s3", fs)
    return fs


def register_gcs(token_provider: Callable[[], str], *,
                 endpoint: str = "https://storage.googleapis.com",
                 transport: Transport = urllib_transport) -> GcsFileSystem:
    fs = GcsFileSystem(token_provider, endpoint=endpoint, transport=transport)
    register_file_system("gs", fs)
    return fs
