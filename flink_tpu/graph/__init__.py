"""Graph translation: Transformation DAG → StreamGraph → JobGraph."""
