"""Whole-graph fusion planner: classify chain steps as device-fusable.

The reference's StreamingJobGraphGenerator chains record-local operators
into one task so records flow by direct method calls instead of network
hops (StreamingJobGraphGenerator.java:1730 isChainable). The TPU-native
form goes one level further: an eligible chain — vectorized, jax-traceable
map/filter/map_ts prologue feeding a device-eligible keyed window
aggregate — compiles into ONE jitted multi-step device program
(`lax.scan` over T batches) with device-resident intermediates. The host
never materializes the post-transform columns, the key column, or the
value column: filter + projection + key/value extraction + window ingest +
fire + purge are a single XLA program per superbatch.

This module is the *planner* only: it walks a planned StepGraph and
decides, per keyed window step, whether the step (and the pure chain step
feeding it, if any) can take the fused device path. The decision is
returned as a `DeviceChainPlan` that the executor threads into a
`DeviceChainRunner` (runtime/executor.py); everything ineligible keeps
today's ChainRunner / WindowStepRunner path with unchanged semantics.

Layering: this module lives in `graph` and may import `ops`/`core`,
never `runtime` (ARCH001) — the plan is pure data about transformations.

Eligibility ("On the Semantic Overlap of Operators in Stream Processing
Engines" grounds which record-local operators collapse safely):

- the window terminal resolves to a DeviceAggregator whose fields all
  scatter-combine (add/min/max), on a sliceable event-time assigner, with
  no custom trigger/evictor/window function, zero allowed lateness and no
  late-data side output — the same bar as the fused superscan operator;
- the key selector (and value_fn, if any) is declared `traceable=True` at
  the API: a pure function of the value column using only jax-traceable
  array ops, returning non-negative int keys below the configured key
  capacity;
- every transform of the upstream chain (if one feeds the window step) is
  map/filter/map_ts declared `traceable=True`; flat_map changes
  cardinality dynamically and always falls back;
- the chain step feeds only this window step (a second consumer needs the
  host-side columns, so fusing would corrupt its input) and shares its
  slot-sharing group (a group boundary is a stage boundary).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from flink_tpu.graph.transformation import Step, StepGraph, Transformation
from flink_tpu.ops.aggregators import resolve

#: chain kinds with a traced device form; flat_map is excluded (dynamic
#: cardinality has no static-shape trace), map_batch is host-only by design
TRACEABLE_CHAIN_KINDS = {"map", "filter", "map_ts"}


@dataclasses.dataclass
class DeviceChainPlan:
    """One fused device chain: the traced prologue transformations (in
    application order, possibly empty), the window terminal, and the input
    edges the fused runner consumes (the absorbed chain step's inputs, or
    the window step's own when nothing was absorbed)."""

    transforms: List[Transformation]
    terminal: Transformation
    inputs: List            # (producer, ordinal, tag) edges, executor wiring
    absorbed: Optional[Step] = None   # the chain step folded into the program

    @property
    def name(self) -> str:
        parts = [t.name for t in self.transforms] + [self.terminal.name]
        return " => ".join(parts)


def window_is_device_fusable(t: Transformation) -> bool:
    """Does this window_aggregate terminal qualify for the traced path?"""
    if t.kind != "window_aggregate":
        return False
    cfg = t.config
    if not cfg.get("key_traceable"):
        return False
    agg = resolve(cfg.get("aggregate"))
    if agg is None or any(f.scatter not in ("add", "min", "max") for f in agg.fields):
        return False
    assigner = cfg.get("assigner")
    if assigner is None or assigner.slice_ms is None or not assigner.is_event_time:
        return False
    if cfg.get("trigger") is not None or cfg.get("evictor") is not None:
        return False
    if cfg.get("window_fn") is not None:
        return False
    if cfg.get("allowed_lateness", 0) != 0 or cfg.get("side_output_late"):
        return False
    if cfg.get("value_fn") is not None and not cfg.get("value_traceable"):
        return False
    return True


def chain_is_traceable(chain: List[Transformation]) -> bool:
    """Every transform of a pure chain step has a traced device form."""
    return all(
        t.kind in TRACEABLE_CHAIN_KINDS and t.config.get("traceable")
        for t in chain
    )


def _step_consumers(graph: StepGraph) -> Dict[int, int]:
    """id(step) -> number of consuming edges across the graph (main-channel
    and side-channel alike: any second consumer pins the step on host)."""
    counts: Dict[int, int] = {}
    for s in graph.steps:
        for edge in s.inputs:
            ent = edge[0]
            if isinstance(ent, Step):
                counts[id(ent)] = counts.get(id(ent), 0) + 1
    return counts


def plan_device_chains(
    graph: StepGraph,
) -> Tuple[Dict[int, DeviceChainPlan], Set[int]]:
    """Walk the StepGraph; return ({id(window_step): plan}, absorbed_ids).

    Steps in `absorbed_ids` (pure chain steps whose whole body was folded
    into a fused program) must not get a runner of their own; the window
    step's runner consumes the absorbed step's input edges instead."""
    plans: Dict[int, DeviceChainPlan] = {}
    absorbed: Set[int] = set()
    consumers = _step_consumers(graph)

    for step in graph.steps:
        t = step.terminal
        if t is None or not window_is_device_fusable(t):
            continue
        if step.partitioning != "key_group" or len(step.inputs) != 1:
            continue
        producer, _ordinal, tag = step.inputs[0][0], step.inputs[0][1], (
            step.inputs[0][2] if len(step.inputs[0]) > 2 else None)
        if tag is not None:
            # a side-output channel feeds this window: the producer's side
            # rows are host objects; keep the host path
            continue
        if (
            isinstance(producer, Step)
            and producer.terminal is None
            and chain_is_traceable(producer.chain)
            and consumers.get(id(producer), 0) == 1
            and producer.slot_group == step.slot_group
            and len(producer.inputs) == 1
        ):
            plans[id(step)] = DeviceChainPlan(
                transforms=list(producer.chain),
                terminal=t,
                inputs=list(producer.inputs),
                absorbed=producer,
            )
            absorbed.add(id(producer))
        else:
            # no absorbable chain: fuse key/value extraction + window alone
            plans[id(step)] = DeviceChainPlan(
                transforms=[], terminal=t, inputs=list(step.inputs),
            )
    return plans, absorbed


def describe(plans: Dict[int, DeviceChainPlan]) -> str:
    """Human-readable plan summary (mirrors StepGraph.describe)."""
    return "\n".join(
        f"device-chain[{i}]: {p.name}"
        + (f" (absorbs {p.absorbed.name})" if p.absorbed is not None else "")
        for i, p in enumerate(plans.values())
    )
