"""Transformation DAG: API calls record transformation nodes.

Capability parity with the reference's G1/G2/G3 pipeline
(flink-core .../api/dag/Transformation.java:110 →
StreamGraphGenerator.java:253 → StreamingJobGraphGenerator.java:134):
user API calls append `Transformation` nodes; the planner groups chainable
transformations into fused *steps* (the analogue of operator chains: a chain
compiles into ONE jitted device program) and cuts chains at keyBy
redistribution points (the analogue of a network shuffle — here a key-group
routed exchange feeding the next step).

The three reference layers collapse into two here because XLA replaces
runtime operator fusion: Transformation (logical) → StepGraph (physical,
already chained).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional

_ids = itertools.count(1)


@dataclasses.dataclass
class Transformation:
    """One logical node (Transformation.java:110): op kind + config + inputs."""

    kind: str                      # 'source'|'map'|'flat_map'|'filter'|'key_by'|
                                   # 'window_aggregate'|'reduce'|'process'|'sink'|'union'
    name: str
    inputs: List["Transformation"]
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    parallelism: Optional[int] = None
    max_parallelism: Optional[int] = None
    uid: Optional[str] = None      # stable id for state remapping (S10 savepoints)

    def __post_init__(self):
        self.id = next(_ids)
        if self.uid is None:
            self.uid = f"{self.kind}-{self.id}"

    def __hash__(self):
        return self.id

    def __repr__(self):
        return f"Transformation#{self.id}({self.kind}:{self.name})"


# chain-breaking kinds: a keyBy repartition or any stateful keyed op boundary
REDISTRIBUTING = {"key_by", "rebalance", "broadcast", "rescale", "global"}


@dataclasses.dataclass
class Step:
    """A fused pipeline stage (the reference's operator chain /
    StreamingJobGraphGenerator.isChainable:1730 analogue).

    `chain` is the list of record-local transformations (map/flatMap/filter)
    fused into one program; `terminal` is the stage's stateful/boundary op
    (window aggregate, sink) if any; `partitioning` describes how records
    enter this step ('forward' or 'key_group')."""

    chain: List[Transformation]
    terminal: Optional[Transformation]
    partitioning: str
    key_selector: Optional[Callable] = None
    upstream: Optional["Step"] = None

    @property
    def name(self) -> str:
        parts = [t.name for t in self.chain]
        if self.terminal is not None:
            parts.append(self.terminal.name)
        return " -> ".join(parts) or "empty-step"

    @property
    def uid(self) -> str:
        if self.terminal is not None:
            return self.terminal.uid
        return self.chain[-1].uid if self.chain else "step"


@dataclasses.dataclass
class StepGraph:
    """Physical plan: linear pipeline of steps (fan-in/fan-out beyond union
    is represented as multiple sources feeding one step)."""

    source: Transformation
    steps: List[Step]

    def describe(self) -> str:
        lines = [f"source: {self.source.name}"]
        for i, s in enumerate(self.steps):
            lines.append(f"step[{i}] ({s.partitioning}): {s.name}")
        return "\n".join(lines)


def plan(sink_transform: Transformation) -> StepGraph:
    """Translate the transformation DAG rooted at `sink_transform` into a
    StepGraph: walk source→sink, fusing chainable ops, cutting at keyBy.

    Mirrors StreamGraphGenerator.generate:253 + createJobGraph chaining in
    one pass (chains = fused steps; shuffles = key_group exchanges).
    """
    # linearize (v0 supports linear topologies + union at source side)
    order: List[Transformation] = []
    node = sink_transform
    while True:
        order.append(node)
        if not node.inputs:
            break
        if len(node.inputs) > 1:
            raise NotImplementedError("multi-input topologies arrive with connect/join support")
        node = node.inputs[0]
    order.reverse()
    if order[0].kind != "source":
        raise ValueError("pipeline must start at a source")

    # stabilize auto-generated uids by topological position so state restores
    # across identically-built pipelines (users set .uid() for evolving jobs,
    # the reference's operator-UID remapping contract, S10)
    for pos, t in enumerate(order):
        if t.uid == f"{t.kind}-{t.id}":
            t.uid = f"{t.kind}@{pos}"

    source = order[0]
    steps: List[Step] = []
    chain: List[Transformation] = []
    partitioning = "forward"
    key_selector = None

    def cut(terminal: Optional[Transformation]):
        nonlocal chain, partitioning, key_selector
        steps.append(
            Step(
                chain=chain,
                terminal=terminal,
                partitioning=partitioning,
                key_selector=key_selector,
                upstream=steps[-1] if steps else None,
            )
        )
        chain = []
        partitioning = "forward"
        key_selector = None

    for t in order[1:]:
        if t.kind in ("map", "map_ts", "map_batch", "flat_map", "filter", "process"):
            chain.append(t)
        elif t.kind == "key_by":
            # repartition point: close current chain as a stateless step if
            # nonempty, then start the keyed step
            if chain:
                cut(None)
            partitioning = "key_group"
            key_selector = t.config["key_selector"]
        elif t.kind in (
            "window_aggregate", "reduce", "sink", "process_keyed", "async_map", "cep",
        ):
            cut(t)
        elif t.kind in REDISTRIBUTING:
            if chain:
                cut(None)
            partitioning = "rebalance"
        else:
            raise NotImplementedError(f"transformation kind {t.kind}")
    if chain:
        cut(None)
    return StepGraph(source=source, steps=steps)
