"""Transformation DAG: API calls record transformation nodes.

Capability parity with the reference's G1/G2/G3 pipeline
(flink-core .../api/dag/Transformation.java:110 →
StreamGraphGenerator.java:253 → StreamingJobGraphGenerator.java:134):
user API calls append `Transformation` nodes; the planner groups chainable
transformations into fused *steps* (the analogue of operator chains: a chain
compiles into ONE jitted device program) and cuts chains at keyBy
redistribution points (the analogue of a network shuffle — here a key-group
routed exchange feeding the next step).

The three reference layers collapse into two here because XLA replaces
runtime operator fusion: Transformation (logical) → StepGraph (physical,
already chained).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Dict, List, Optional

_ids = itertools.count(1)


@dataclasses.dataclass
class Transformation:
    """One logical node (Transformation.java:110): op kind + config + inputs."""

    kind: str                      # 'source'|'map'|'flat_map'|'filter'|'key_by'|
                                   # 'window_aggregate'|'reduce'|'process'|'sink'|'union'
    name: str
    inputs: List["Transformation"]
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    parallelism: Optional[int] = None
    max_parallelism: Optional[int] = None
    uid: Optional[str] = None      # stable id for state remapping (S10 savepoints)

    def __post_init__(self):
        self.id = next(_ids)
        if self.uid is None:
            self.uid = f"{self.kind}-{self.id}"

    def __hash__(self):
        return self.id

    def __repr__(self):
        return f"Transformation#{self.id}({self.kind}:{self.name})"


# chain-breaking kinds: a keyBy repartition or any stateful keyed op boundary
REDISTRIBUTING = {"key_by", "rebalance", "broadcast", "rescale", "global", "shuffle", "forward"}


# record-local kinds fusable into one chain step
CHAINABLE = {"map", "map_ts", "map_batch", "flat_map", "filter", "process"}

# single-input stateful/boundary terminals
TERMINALS = {
    "window_aggregate", "reduce", "sink", "process_keyed", "async_map", "cep",
    "group_agg",
    # iteration feedback edges (StreamIterationHead/Tail analogue): the tail
    # references its head out-of-band via config["head"], so the
    # transformation DAG stays acyclic and the cycle exists only at runtime
    "iteration_head", "iteration_tail",
}

# multi-input terminals (DataStream.java:111 union/connect/join surface)
MULTI_TERMINALS = {"union", "co_map", "co_flat_map", "co_process", "window_join", "co_group", "broadcast_process", "regular_join"}


@dataclasses.dataclass
class Step:
    """A fused pipeline stage (the reference's operator chain /
    StreamingJobGraphGenerator.isChainable:1730 analogue).

    `chain` is the list of record-local transformations (map/flatMap/filter)
    fused into one program; `terminal` is the stage's stateful/boundary op
    (window aggregate, co-process, join, sink ...) if any; `partitioning`
    describes how records enter this step ('forward' or 'key_group');
    `inputs` lists (producer, ordinal) pairs, where a producer is either an
    upstream Step or a source Transformation and the ordinal selects the
    input gate at a multi-input operator (valves min-combine watermarks per
    gate, StatusWatermarkValve.java analogue)."""

    chain: List[Transformation]
    terminal: Optional[Transformation]
    partitioning: str
    key_selector: Optional[Callable] = None
    upstream: Optional["Step"] = None
    inputs: List = dataclasses.field(default_factory=list)
    # slot-sharing group (SlotSharingGroup analogue): steps of different
    # groups deploy as separate pipeline stages in their own slots
    # (runtime/stages.py); the default group keeps the whole slice in one
    # slot, the reference's default sharing behavior
    slot_group: str = "default"

    @property
    def name(self) -> str:
        parts = [t.name for t in self.chain]
        if self.terminal is not None:
            parts.append(self.terminal.name)
        return " -> ".join(parts) or "empty-step"

    @property
    def uid(self) -> str:
        if self.terminal is not None:
            return self.terminal.uid
        return self.chain[-1].uid if self.chain else "step"


@dataclasses.dataclass
class StepGraph:
    """Physical plan: a DAG of steps. `sources` are the source
    transformations feeding entry steps; `steps` is in topological order."""

    sources: List[Transformation]
    steps: List[Step]

    @property
    def source(self) -> Transformation:
        """Single-source view (legacy callers of linear pipelines)."""
        return self.sources[0]

    def describe(self) -> str:
        lines = [f"source: {s.name}" for s in self.sources]
        for i, s in enumerate(self.steps):
            ins = ",".join(
                (f"src:{e.name}" if isinstance(e, Transformation) else f"step:{e.name}")
                + (f"[{edge[2]}]" if len(edge) > 2 and edge[2] else "")
                + f"@{o}"
                for edge in s.inputs
                for e, o in [edge[:2]]
            )
            lines.append(f"step[{i}] ({s.partitioning}) [{ins}]: {s.name}")
        return "\n".join(lines)


def plan(sink_transforms) -> StepGraph:
    """Translate the transformation DAG rooted at the sink(s) into a
    StepGraph: topological walk fusing chainable runs, cutting at keyBy and
    at every multi-input or multi-consumer boundary.

    Mirrors StreamGraphGenerator.generate:253 + createJobGraph chaining in
    one pass (chains = fused steps; shuffles = key_group exchanges)."""
    sinks = ([sink_transforms] if isinstance(sink_transforms, Transformation)
             else list(sink_transforms))

    # collect nodes + per-edge consumer counts
    consumers: Dict[int, int] = {}
    nodes: Dict[int, Transformation] = {}
    stack = list(sinks)
    while stack:
        n = stack.pop()
        if n.id in nodes:
            continue
        nodes[n.id] = n
        for i in n.inputs:
            consumers[i.id] = consumers.get(i.id, 0) + 1
            stack.append(i)

    # topological order (sources first), deterministic by node id; explicit
    # stack so thousand-op chains don't hit the recursion limit
    order: List[Transformation] = []
    state: Dict[int, int] = {}
    for s in sorted(sinks, key=lambda t: t.id):
        work = [(s, False)]
        while work:
            n, expanded = work.pop()
            if expanded:
                state[n.id] = 2
                order.append(n)
                continue
            if state.get(n.id) == 2:
                continue
            if state.get(n.id) == 1:
                raise ValueError("transformation graph has a cycle")
            state[n.id] = 1
            work.append((n, True))
            # reversed: LIFO pop then visits inputs in declaration order,
            # matching the recursive traversal (source order is user-visible
            # through the run loop's round-robin)
            for i in reversed(n.inputs):
                if state.get(i.id) != 2:
                    work.append((i, False))

    # stabilize auto-generated uids by topological position so state restores
    # across identically-built pipelines (users set .uid() for evolving jobs,
    # the reference's operator-UID remapping contract, S10)
    for pos, t in enumerate(order):
        if t.uid == f"{t.kind}-{t.id}":
            t.uid = f"{t.kind}@{pos}"

    sources: List[Transformation] = []
    steps: List[Step] = []
    # producer[node.id] = source Transformation | Step whose output carries
    # the node's records; keyed[node.id] = key_by config for keyed views;
    # side_tag[node.id] = the producing step's side-output channel
    producer: Dict[int, Any] = {}
    keyed: Dict[int, Dict[str, Any]] = {}
    side_tag: Dict[int, str] = {}
    alias_of: Dict[int, int] = {}   # pass-through views -> effective node
    # slot-sharing group per node: explicit declaration wins, else inherited
    # from the first input (DataStream.slotSharingGroup semantics: operators
    # join their input's group unless told otherwise)
    group_of: Dict[int, str] = {}

    def new_step(**kw) -> Step:
        s = Step(**kw)
        steps.append(s)
        return s

    def input_of(t: Transformation, inp: Transformation, ordinal: int):
        """(producer, ordinal, tag, partitioning, key_selector) per edge."""
        ent = producer[inp.id]
        tag = side_tag.get(inp.id)
        if inp.id in keyed:
            k = keyed[inp.id]
            return ent, ordinal, tag, "key_group", k["key_selector"]
        return ent, ordinal, tag, "forward", None

    for t in order:
        g = t.config.get("slot_sharing_group")
        if g is None and t.inputs:
            g = group_of[t.inputs[0].id]
        group_of[t.id] = g or "default"
        if t.kind == "source":
            sources.append(t)
            producer[t.id] = t
        elif t.kind == "key_by":
            producer[t.id] = producer[t.inputs[0].id]
            if t.inputs[0].id in side_tag:
                side_tag[t.id] = side_tag[t.inputs[0].id]
            keyed[t.id] = t.config  # re-keying: the newest selector wins
        elif t.kind == "side_output":
            # a tagged view of the producing step's side channel
            # (OutputTag / SingleOutputStreamOperator.getSideOutput)
            producer[t.id] = producer[t.inputs[0].id]
            side_tag[t.id] = t.config["tag"].tag_id
        elif t.kind in CHAINABLE:
            inp = t.inputs[0]
            ent = producer[inp.id]
            eff_id = alias_of.get(inp.id, inp.id)
            if (
                isinstance(ent, Step)
                and ent.terminal is None
                and consumers.get(inp.id, 0) == 1
                # seeing through a forward alias must not hide the effective
                # node's OTHER consumers (fusing would corrupt their data)
                and (eff_id == inp.id or consumers.get(eff_id, 0) == 1)
                and inp.id not in keyed
                and inp.id not in side_tag
                and ent.chain
                and ent.chain[-1].id == eff_id
                # a different slot-sharing group breaks the chain (the
                # reference's isChainable group check)
                and group_of[t.id] == ent.slot_group
            ):
                ent.chain.append(t)          # fuse into the open chain
                producer[t.id] = ent
            else:
                ent2, _o, tag, part, ks = input_of(t, inp, 0)
                producer[t.id] = new_step(
                    chain=[t], terminal=None, partitioning=part,
                    key_selector=ks, inputs=[(ent2, 0, tag)],
                    slot_group=group_of[t.id],
                )
        elif t.kind in TERMINALS:
            inp = t.inputs[0]
            ent, _o, tag, part, ks = input_of(t, inp, 0)
            producer[t.id] = new_step(
                chain=[], terminal=t, partitioning=part,
                key_selector=ks, inputs=[(ent, 0, tag)],
                slot_group=group_of[t.id],
            )
        elif t.kind in MULTI_TERMINALS:
            ins = []
            part = "forward"
            ks = None
            for o, inp in enumerate(t.inputs):
                ent, _o, tag, p, k = input_of(t, inp, o)
                ins.append((ent, o, tag))
                if p == "key_group":
                    part, ks = p, (ks or k)
            producer[t.id] = new_step(
                chain=[], terminal=t, partitioning=part,
                key_selector=ks, inputs=ins,
                slot_group=group_of[t.id],
            )
        elif t.kind in REDISTRIBUTING:
            # explicit repartition hints; locally a pass-through view that
            # must keep the upstream's channel (side tag) and, for forward —
            # the one partitioner that PRESERVES chaining — its keyed view
            inp = t.inputs[0]
            producer[t.id] = producer[inp.id]
            if inp.id in side_tag:
                side_tag[t.id] = side_tag[inp.id]
            if t.kind == "forward":
                if inp.id in keyed:
                    keyed[t.id] = keyed[inp.id]
                # forward is chain-transparent: fusion sees through it
                alias_of[t.id] = alias_of.get(inp.id, inp.id)
        else:
            raise NotImplementedError(f"transformation kind {t.kind}")

    if not sources:
        raise ValueError("pipeline must start at a source")
    # co-location (CoLocationGroup analogue): an iteration tail always joins
    # its head's slot-sharing group — the runtime feedback cycle is local
    head_group = {
        s.terminal.id: s.slot_group for s in steps
        if s.terminal is not None and s.terminal.kind == "iteration_head"
    }
    for s in steps:
        if s.terminal is not None and s.terminal.kind == "iteration_tail":
            hid = s.terminal.config["head"].id
            if hid in head_group:
                s.slot_group = head_group[hid]
    return StepGraph(sources=sources, steps=steps)
