"""Sharing optimizer for correlated window aggregates (Factor Windows).

"Factor Windows: Cost-based Query Rewriting for Optimizing Correlated
Window Aggregates" (PAPERS.md, arXiv 2008.12379) observes that a job
computing several windows over the same stream — the 1m/5m/1h dashboard
shape — re-scans the stream once per window, although the windows are
*correlated*: every member can be derived from the partials of a common
finer "factor" window. The slice decomposition the device path already
uses (api/windowing/assigners.py, the pane/slice trick) IS that factor
window: slices at the gcd granule of the group. What was missing is the
optimizer that recognizes the shape — this module.

`plan_shared_windows` walks the fusion planner's per-step
`DeviceChainPlan`s (graph/fusion.py) and groups device-fusable
`window_aggregate` siblings that consume the SAME keyed stream (same
producer edge, same traceable key selector and value extractor, same
resolved aggregate, same slot-sharing group, one common window offset)
into `SharedWindowPlan`s. The executor then builds ONE shared-partial
runner per group: ingest lands gcd-granule partials once, each member
window fires its own slice-run from the shared ring
(runtime/fused_window_pipeline.SharedWindowPipeline,
`fire_spws` in ops/superscan.make_superscan_step), and emissions route to
each member's own downstream. Against N independent fused runs this
saves (N-1) full ingest scans — the `estimated_sharing_factor` below.

When the common producer is a pure traceable chain consumed ONLY by the
group, the chain is absorbed into the shared program too (the sibling
count blocked per-member absorption in graph/fusion.py; the group as a
whole un-blocks it).

Exactness: member decompositions onto the shared granule go through
`WindowAssigner.slices_on`, the validated exact-decomposition contract —
a slide that does not divide the size, and the size == slide tumbling
collapse, decompose exactly or the group is refused (each member then
keeps its own fused program; sharing is a perf switch, never a semantics
switch).

Layering: graph module — imports graph/ops only, never the runtime
(ARCH001; the plan is pure data the executor consumes).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Set, Tuple

from flink_tpu.graph.fusion import DeviceChainPlan, chain_is_traceable
from flink_tpu.graph.transformation import Step, StepGraph, Transformation

#: refuse groups whose shared ring would explode: a member needing more
#: slices per window than this on the shared granule (e.g. a 1-second
#: window grouped with a 1ms one) costs more in fire-time gathers than
#: sharing saves in ingest
MAX_SHARED_SPW = 4096


@dataclasses.dataclass
class SharedWindowPlan:
    """One shared-partial group: member window steps (spec order), their
    terminals/assigners, the shared traced chain (possibly empty), the
    input edges the shared runner consumes, and the cost-model estimate."""

    members: List[Step]                   # window steps; members[0] = leader
    terminals: List[Transformation]
    assigners: List
    transforms: List[Transformation]      # shared absorbed chain, app order
    inputs: List                          # executor wiring edges
    granule_ms: int
    member_spws: List[int]                # slices per window on the granule
    estimated_sharing_factor: float
    absorbed: Optional[Step] = None       # chain step folded into the program

    @property
    def name(self) -> str:
        parts = [t.name for t in self.transforms]
        parts.append(" | ".join(t.name for t in self.terminals))
        return " => ".join(parts)


def _group_signature(step: Step, plan: DeviceChainPlan):
    """Correlation key: the stream + extraction identity a group shares.

    Two window steps are correlated iff they consume the same producer
    edge, key with the same traceable selector, extract the same value
    column, fold with the same resolved aggregate, and share a slot
    group — then their scans are redundant and Factor-Windows sharing
    applies."""
    from flink_tpu.ops.aggregators import resolve

    cfg = plan.terminal.config
    edge = step.inputs[0]
    producer, ordinal = edge[0], edge[1]
    return (
        id(producer), ordinal,
        id(cfg["key_selector"]),
        id(cfg.get("value_fn")) if cfg.get("value_fn") is not None else None,
        id(resolve(cfg.get("aggregate"))),
        step.slot_group,
    )


def _shared_granule(assigners) -> Optional[Tuple[int, List[int], List[int]]]:
    """(granule_ms, member_spws, member_sls) — or None when the group has
    no exact, bounded shared decomposition (mixed offsets, a member whose
    decomposition is inexact, or a pathological granule ratio)."""
    if len({a.offset_ms for a in assigners}) != 1:
        return None
    g = 0
    for a in assigners:
        if a.slice_ms is None or not a.is_event_time:
            return None
        g = math.gcd(g, a.slice_ms)
    spws, sls = [], []
    for a in assigners:
        try:
            spw, sl = a.slices_on(g)
        except ValueError:
            return None
        if spw > MAX_SHARED_SPW:
            return None
        spws.append(spw)
        sls.append(sl)
    return g, spws, sls


def _sharing_factor(n: int, spws: List[int], sls: List[int]) -> float:
    """Factor-Windows cost estimate: independent plans pay one full
    ingest scan per member (the dominant, per-record cost); the shared
    plan pays ONE scan plus the fire-time slice gathers every member
    would have paid anyway. The estimate is the scan-count ratio damped
    by the relative fire overhead of the finer shared granule (a member
    whose own granule was coarser now gathers more slices per fire)."""
    # fire work per emitted window ~ spw slices; per slice of stream time a
    # member fires every sl slices, so fire cost density ~ spw / sl
    fire_density = sum(spw / max(sl, 1) for spw, sl in zip(spws, sls))
    return n / (1.0 + 0.01 * fire_density)


def plan_shared_windows(
    graph: StepGraph,
    chain_plans: Dict[int, DeviceChainPlan],
) -> List[SharedWindowPlan]:
    """Group correlated device-fusable window siblings into shared plans.

    `chain_plans` is plan_device_chains' output: only steps it classified
    device-fusable participate (the sharing bar equals the fusion bar —
    every member must already be able to run the traced device path).
    Members that absorbed a private chain are not grouped (their streams
    differ by construction); a COMMON pure traceable chain feeding only
    the group is lifted into the shared program instead."""
    groups: Dict[tuple, List[Step]] = {}
    for step in graph.steps:
        plan = chain_plans.get(id(step))
        if plan is None or plan.absorbed is not None:
            continue
        if len(step.inputs) != 1:
            continue
        tag = step.inputs[0][2] if len(step.inputs[0]) > 2 else None
        if tag is not None:
            continue
        groups.setdefault(_group_signature(step, plan), []).append(step)

    consumers: Dict[int, int] = {}
    for s in graph.steps:
        for edge in s.inputs:
            ent = edge[0]
            if isinstance(ent, Step):
                consumers[id(ent)] = consumers.get(id(ent), 0) + 1

    out: List[SharedWindowPlan] = []
    for sig, members in groups.items():
        if len(members) < 2:
            continue
        terminals = [s.terminal for s in members]
        assigners = [t.config["assigner"] for t in terminals]
        dec = _shared_granule(assigners)
        if dec is None:
            continue
        g, spws, sls = dec
        producer = members[0].inputs[0][0]
        transforms: List[Transformation] = []
        inputs = [members[0].inputs[0]]
        absorbed = None
        if (
            isinstance(producer, Step)
            and producer.terminal is None
            and chain_is_traceable(producer.chain)
            and consumers.get(id(producer), 0) == len(members)
            and producer.slot_group == members[0].slot_group
            and len(producer.inputs) == 1
        ):
            # the whole group is the chain's only consumer set: lift the
            # chain into the shared program (per-member absorption was
            # blocked exactly because the siblings shared it)
            transforms = list(producer.chain)
            inputs = list(producer.inputs)
            absorbed = producer
        out.append(SharedWindowPlan(
            members=list(members),
            terminals=terminals,
            assigners=assigners,
            transforms=transforms,
            inputs=inputs,
            granule_ms=g,
            member_spws=spws,
            estimated_sharing_factor=_sharing_factor(
                len(members), spws, sls),
            absorbed=absorbed,
        ))
    return out


def describe(plans: List[SharedWindowPlan]) -> str:
    """Human-readable summary (mirrors fusion.describe)."""
    return "\n".join(
        f"shared-windows[{i}] g={p.granule_ms}ms "
        f"x{len(p.members)} (est {p.estimated_sharing_factor:.2f}x): "
        f"{p.name}"
        for i, p in enumerate(plans)
    )
