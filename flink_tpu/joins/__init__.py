"""Device-side streaming joins: the two-input keyed join subsystem.

Layering (ARCH001): `joins` sits beside `ops`/`state` — it may import
core, ops, state, config, and the parallel mesh library, and must never
import runtime, api, table, or scheduler. The runtime's
`DeviceJoinRunner` drives these pipelines from behind the StepRunner
boundary; the SQL planner lowers window equi-joins onto them.
"""

from flink_tpu.joins.pipeline import FusedJoinPipeline, expand_pairs
from flink_tpu.joins.ring import BucketRing
from flink_tpu.joins.sharded import ShardedJoinPipeline
from flink_tpu.joins.spec import (
    JOIN_FALLBACK_CATALOG,
    JOIN_FALLBACK_CODES,
    JoinGeometry,
    JoinUnsupported,
    fallback_code,
    plan_join_geometry,
)

__all__ = [
    "BucketRing",
    "FusedJoinPipeline",
    "ShardedJoinPipeline",
    "JoinGeometry",
    "JoinUnsupported",
    "JOIN_FALLBACK_CATALOG",
    "JOIN_FALLBACK_CODES",
    "fallback_code",
    "plan_join_geometry",
    "expand_pairs",
]
