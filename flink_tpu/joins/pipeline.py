"""Two rings + the segment cross-match: the fused join pipeline.

`FusedJoinPipeline` owns the left and right `BucketRing`s of one keyed
join operator and turns a fired window (or interval frontier) into pairs
of ROW IDS: the match kernel gathers both sides' bucket runs into per-key
slot lanes on device, and the host expands the per-key cross product into
flat (left rowid, right rowid, key) arrays with pure vectorized index
arithmetic — no per-pair Python until the caller applies its join
function to the payload rows.

Both sides share one `ts_base` (set by the first ingested batch, floored
to the bucket grid) so relative timestamps are comparable across sides —
interval-join deltas are (right rel-ts − left rel-ts) directly.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from flink_tpu.joins.ring import BucketRing
from flink_tpu.joins.spec import JoinGeometry
from flink_tpu.ops.join_ring import build_join_match


def _excl_cumsum(a: np.ndarray) -> np.ndarray:
    out = np.zeros(len(a), dtype=np.int64)
    np.cumsum(a[:-1], out=out[1:])
    return out


def expand_pairs(lidx, lval, ridx, rval):
    """Per-key cross product of valid lanes -> (l_rowids, r_rowids, kids).

    All inputs are host [K, *] arrays read back from the match kernel; the
    expansion is vectorized end to end (the classic repeat/tile-by-group
    construction), so cost is O(pairs) numpy work, not Python."""
    lval = np.asarray(lval, dtype=bool)
    rval = np.asarray(rval, dtype=bool)
    lcnt = lval.sum(axis=1).astype(np.int64)
    rcnt = rval.sum(axis=1).astype(np.int64)
    pairs = lcnt * rcnt
    total = int(pairs.sum())
    empty = np.empty(0, dtype=np.int64)
    if total == 0:
        return empty, empty, empty
    lflat = np.asarray(lidx)[lval].astype(np.int64)
    rflat = np.asarray(ridx)[rval].astype(np.int64)
    out_l = np.repeat(lflat, np.repeat(rcnt, lcnt))
    kids = np.repeat(np.arange(len(pairs), dtype=np.int64), pairs)
    ordinal = np.arange(total, dtype=np.int64) \
        - np.repeat(_excl_cumsum(pairs), pairs)
    out_r = rflat[_excl_cumsum(rcnt)[kids] + ordinal % rcnt[kids]]
    return out_l, out_r, kids


class FusedJoinPipeline:
    """Single-chip orchestration of one device join operator's state."""

    def __init__(self, geom: JoinGeometry,
                 put=None):
        self.geom = geom
        self._put = put
        self.left = BucketRing(geom, put)
        self.right = BucketRing(geom, put)
        self.ts_base: Optional[int] = None

    def regrow(self, geom: JoinGeometry) -> None:
        """Swap to a larger geometry (more key lanes or record slots),
        carrying every resident record over — the rings start SMALL and
        double toward the configured caps, so an idle join never pins
        cap-sized HBM arrays (the key-capacity growth contract)."""
        snap = self.snapshot()
        self.geom = geom
        self.left = BucketRing(geom, self._put)
        self.right = BucketRing(geom, self._put)
        base = self.ts_base if self.ts_base is not None else 0
        self.left.restore(snap["left"], base)
        self.right.restore(snap["right"], base)

    # -- ingest ------------------------------------------------------------
    def ingest(self, side: int, kids: np.ndarray, ts: np.ndarray,
               rows) -> None:
        if len(kids) == 0:
            return
        if self.ts_base is None:
            g = self.geom
            self.ts_base = int(g.offset_ms
                               + g.bucket_of(int(np.min(ts))) * g.bucket_ms)
        ring = self.left if side == 0 else self.right
        ring.ingest(kids, ts, rows, self.ts_base)

    # -- fire --------------------------------------------------------------
    def _window_buckets(self, start: int, end: int) -> np.ndarray:
        g = self.geom
        b0 = (start - g.offset_ms) // g.bucket_ms
        return np.arange(b0, b0 + (end - start) // g.bucket_ms,
                         dtype=np.int64)

    def fire_window(self, start: int, end: int
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Inner window-join emission for [start, end): (left rowids,
        right rowids, dense key ids), per-key cross-product order."""
        buckets = self._window_buckets(start, end)
        rbs_l, cnt_l = self.left.run_counts(buckets)
        rbs_r, cnt_r = self.right.run_counts(buckets)
        if not cnt_l.any() or not cnt_r.any():
            e = np.empty(0, dtype=np.int64)
            return e, e, e
        g = self.geom
        kern = build_join_match(g.ring_buckets, g.key_capacity,
                                g.bucket_capacity, len(buckets),
                                len(buckets), False)
        lidx, _lts, lval, ridx, _rts, rval, _pairs = kern(
            self.left.idx_arr, self.left.ts_arr, cnt_l, rbs_l,
            self.right.idx_arr, self.right.ts_arr, cnt_r, rbs_r,
            np.int32(0), np.int32(0))
        return expand_pairs(np.asarray(lidx), np.asarray(lval),
                            np.asarray(ridx), np.asarray(rval))

    def match_interval(self, left_buckets, right_buckets, lo_ms: int,
                       hi_ms: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Interval-join emission: pairs over the given bucket runs whose
        (right ts − left ts) lies in [lo_ms, hi_ms]."""
        lb = np.asarray(left_buckets, dtype=np.int64)
        rb = np.asarray(right_buckets, dtype=np.int64)
        rbs_l, cnt_l = self.left.run_counts(lb)
        rbs_r, cnt_r = self.right.run_counts(rb)
        e = np.empty(0, dtype=np.int64)
        if not cnt_l.any() or not cnt_r.any():
            return e, e, e
        g = self.geom
        kern = build_join_match(g.ring_buckets, g.key_capacity,
                                g.bucket_capacity, len(lb), len(rb), True)
        lidx, _lts, _lv, ridx, _rts, _rv, _pairs, mask = kern(
            self.left.idx_arr, self.left.ts_arr, cnt_l, rbs_l,
            self.right.idx_arr, self.right.ts_arr, cnt_r, rbs_r,
            np.int32(lo_ms), np.int32(hi_ms))
        k, li, ri = np.nonzero(np.asarray(mask))
        if len(k) == 0:
            return e, e, e
        lidx, ridx = np.asarray(lidx), np.asarray(ridx)
        return (lidx[k, li].astype(np.int64),
                ridx[k, ri].astype(np.int64), k.astype(np.int64))

    # -- bookkeeping -------------------------------------------------------
    def purge_below_window(self, min_live_window_start: int) -> None:
        g = self.geom
        min_bucket = (min_live_window_start - g.offset_ms) // g.bucket_ms
        self.left.purge_below(min_bucket)
        self.right.purge_below(min_bucket)

    def occupancy(self) -> int:
        return self.left.occupancy() + self.right.occupancy()

    def occupied_buckets(self) -> list:
        return sorted(set(self.left.occupied_buckets())
                      | set(self.right.occupied_buckets()))

    def state_bytes(self) -> int:
        return self.left.state_bytes() + self.right.state_bytes()

    # -- checkpointing -----------------------------------------------------
    def snapshot(self) -> dict:
        return {"ts_base": self.ts_base,
                "left": self.left.snapshot(),
                "right": self.right.snapshot()}

    def restore(self, snap: dict) -> None:
        self.ts_base = snap["ts_base"]
        base = self.ts_base if self.ts_base is not None else 0
        self.left.restore(snap["left"], base)
        self.right.restore(snap["right"], base)
