"""One side's per-key time-bucketed ring: HBM arrays + host occupancy mirror.

The device holds two [NB, K, C] int32 arrays (row index and relative
timestamp); the host holds the ONLY mutable bookkeeping — per-(bucket,
key) occupancy counts, the absolute bucket id resident in each ring slot,
and the row payloads themselves (device state is row INDICES; payload
rows never cross the PCIe/ICI boundary). Every ingest batch is planned
entirely on the host first — ring slot, key lane, record slot — and every
overflow (a (key, bucket) past its record capacity, or event time running
so far ahead of the watermark that the ring would wrap onto a live
bucket) raises `JoinUnsupported` BEFORE any mirror or device mutation, so
the operator can degrade to the host join by replaying the live rows plus
the whole untouched batch: all-or-nothing per batch, which is what makes
degrade exactly-once.

Fire-time validity is derived from the host-shipped counts, never from
device state, so purging a bucket is pure host bookkeeping (counts to
zero, slot marked free) — no device-side zeroing dispatch exists at all.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.joins.spec import JoinGeometry, JoinUnsupported
from flink_tpu.ops.join_ring import build_join_ingest


def _pad_len(n: int) -> int:
    """Dispatch-length bucketing: next power of two, min 256 — one
    compiled ingest executable per (geometry, length bucket)."""
    return max(256, 1 << (max(n, 1) - 1).bit_length())


class BucketRing:
    """Host mirror + device arrays for one join side."""

    def __init__(self, geom: JoinGeometry,
                 put: Optional[Callable[[Any], Any]] = None):
        import jax.numpy as jnp

        self.geom = geom
        self._put = put or (lambda a: a)
        nb, k, c = geom.ring_buckets, geom.key_capacity, geom.bucket_capacity
        self.idx_arr = self._put(jnp.zeros((nb, k, c), dtype=jnp.int32))
        self.ts_arr = self._put(jnp.zeros((nb, k, c), dtype=jnp.int32))
        self._ingest = build_join_ingest(nb, k, c)
        # host mirror
        self.cnt = np.zeros((nb, k), dtype=np.int32)
        self.bucket_at = np.full(nb, -1, dtype=np.int64)
        # host row store: rowid -> payload; purged slots are None'd so the
        # payloads are collectable while rowids stay stable for the device
        self._rows: List[Any] = []
        self._row_ts: List[int] = []
        # ring slot -> [(kid, rowid), ...] in ingest order (slot order per
        # (bucket, key) is ingest order, so this is enough to rebuild the
        # device arrays exactly on restore — no device readback needed)
        self._staged: Dict[int, List[Tuple[int, int]]] = {}

    # -- ingest ------------------------------------------------------------
    def ingest(self, kids: np.ndarray, ts: np.ndarray, rows,
               ts_base: int) -> None:
        """Plan, validate, then scatter one batch. Raises JoinUnsupported
        ("join-ring-overflow") with NOTHING mutated on any overflow."""
        n = len(kids)
        if n == 0:
            return
        g = self.geom
        kids = np.asarray(kids, dtype=np.int64)
        ts = np.asarray(ts, dtype=np.int64)
        buckets = (ts - g.offset_ms) // g.bucket_ms
        rb = buckets % g.ring_buckets
        # ring-wrap conflicts: against resident buckets ...
        resident = self.bucket_at[rb]
        if np.any((resident >= 0) & (resident != buckets)):
            err = JoinUnsupported(
                "join-ring-overflow",
                f"event time ran {g.ring_buckets}+ buckets ahead of the "
                f"purge horizon; the ring would wrap onto a live bucket")
            err.overflow = "wrap"
            raise err
        # ... and within the batch itself
        order = np.argsort(rb, kind="stable")
        rbs, bks = rb[order], buckets[order]
        same = rbs[1:] == rbs[:-1]
        if np.any(same & (bks[1:] != bks[:-1])):
            err = JoinUnsupported(
                "join-ring-overflow",
                "one batch spans more event time than the whole ring")
            err.overflow = "wrap"
            raise err
        # slot = resident count + rank within this batch's (bucket, key)
        # group, in arrival order
        grp = rb * np.int64(g.key_capacity) + kids
        gorder = np.argsort(grp, kind="stable")
        gs = grp[gorder]
        starts = np.flatnonzero(np.r_[True, gs[1:] != gs[:-1]])
        run = np.zeros(n, dtype=np.int64)
        run[starts] = 1
        run = np.cumsum(run) - 1
        rank_sorted = np.arange(n, dtype=np.int64) - starts[run]
        rank = np.empty(n, dtype=np.int64)
        rank[gorder] = rank_sorted
        slot = self.cnt[rb, kids].astype(np.int64) + rank
        if np.any(slot >= g.bucket_capacity):
            worst = int(np.max(slot)) + 1
            err = JoinUnsupported(
                "join-ring-overflow",
                f"a (key, bucket) side needs {worst} record slots but "
                f"execution.join.bucket-capacity is {g.bucket_capacity}")
            err.overflow = "slots"
            err.required = worst
            raise err
        # -- validated: mutate mirror, store rows, dispatch the scatter --
        base = len(self._rows)
        self._rows.extend(rows)
        self._row_ts.extend(int(t) for t in ts)
        np.add.at(self.cnt, (rb, kids), 1)
        self.bucket_at[rb] = buckets
        for i in range(n):
            self._staged.setdefault(int(rb[i]), []).append(
                (int(kids[i]), base + i))
        m = _pad_len(n)
        def pad(a, dtype=np.int32):
            out = np.empty(m, dtype=dtype)
            out[:n] = a
            out[n:] = a[-1]          # idempotent re-write of the last lane
            return out
        rowids = np.arange(base, base + n, dtype=np.int64)
        self.idx_arr, self.ts_arr = self._ingest(
            self.idx_arr, self.ts_arr,
            pad(rb), pad(kids), pad(slot),
            pad(rowids), pad(ts - ts_base))

    # -- fire support ------------------------------------------------------
    def run_counts(self, buckets: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(ring slots, per-key counts) for one window's bucket run;
        buckets not resident (never filled, or purged) count zero."""
        buckets = np.asarray(buckets, dtype=np.int64)
        rbs = (buckets % self.geom.ring_buckets).astype(np.int32)
        live = self.bucket_at[rbs] == buckets
        cnt = np.where(live[:, None], self.cnt[rbs], 0).astype(np.int32)
        return rbs, cnt

    def row(self, rowid: int) -> Any:
        return self._rows[rowid]

    def take_rows(self, rowids: np.ndarray) -> List[Any]:
        rows = self._rows
        return [rows[i] for i in rowids]

    # -- purge / introspection --------------------------------------------
    def purge_below(self, min_bucket: int) -> None:
        dead = np.flatnonzero((self.bucket_at >= 0)
                              & (self.bucket_at < min_bucket))
        for rb in dead:
            self.cnt[rb] = 0
            self.bucket_at[rb] = -1
            for _kid, rid in self._staged.pop(int(rb), ()):
                self._rows[rid] = None
                self._row_ts[rid] = None

    def occupancy(self) -> int:
        return int(self.cnt.sum())

    def occupied_buckets(self) -> List[int]:
        return [int(b) for b in self.bucket_at[self.bucket_at >= 0]]

    def live_records(self) -> List[Tuple[int, Any, int]]:
        """(kid, row, ts) for every resident record, bucket order then
        ingest order — the degrade-to-host replay set."""
        out = []
        for rb in sorted(self._staged,
                         key=lambda r: int(self.bucket_at[r])):
            for kid, rid in self._staged[rb]:
                out.append((kid, self._rows[rid], self._row_ts[rid]))
        return out

    def state_bytes(self) -> int:
        g = self.geom
        return 2 * 4 * g.ring_buckets * g.key_capacity * g.bucket_capacity

    # -- checkpointing -----------------------------------------------------
    def snapshot(self) -> dict:
        buckets = []
        for rb, ents in self._staged.items():
            buckets.append((int(self.bucket_at[rb]),
                            [(kid, self._rows[rid], self._row_ts[rid])
                             for kid, rid in ents]))
        buckets.sort(key=lambda b: b[0])
        return {"buckets": buckets}

    def restore(self, snap: dict, ts_base: int) -> None:
        import jax.numpy as jnp

        g = self.geom
        self.idx_arr = self._put(jnp.zeros(
            (g.ring_buckets, g.key_capacity, g.bucket_capacity),
            dtype=jnp.int32))
        self.ts_arr = self._put(jnp.zeros(
            (g.ring_buckets, g.key_capacity, g.bucket_capacity),
            dtype=jnp.int32))
        self.cnt[:] = 0
        self.bucket_at[:] = -1
        self._rows, self._row_ts, self._staged = [], [], {}
        for _bucket, ents in snap["buckets"]:
            if not ents:
                continue
            kids = np.asarray([k for k, _r, _t in ents], dtype=np.int64)
            ts = np.asarray([t for _k, _r, t in ents], dtype=np.int64)
            self.ingest(kids, ts, [r for _k, r, _t in ents], ts_base)
