"""Mesh variant of the fused join pipeline: rings sharded over keys.

Both sides' ring arrays [NB, K, C] are laid out with the KEY axis
partitioned across the mesh (`NamedSharding` over the "shards" axis), so
each device owns the SAME contiguous key range for both inputs — the
two-sides-one-owner layout (arXiv 1904.03800's shared-state analysis:
co-partitioning both sides eliminates cross-worker match traffic). The
key exchange itself is implicit: ingest scatters replicated host-staged
coordinates into the key-sharded operand, and GSPMD keeps exactly the
writes whose key lane lands in each shard's range — the degenerate
all-to-all where every shard already holds the (replicated) updates. The
match kernel is per-key throughout, so it partitions with zero
collectives and the gathered lanes come back key-sharded.

The mesh size is clamped by `usable_mesh_size` (key capacity must divide
evenly), the same single-sourced clamp every other mesh consumer uses.
"""

from __future__ import annotations

from flink_tpu.joins.pipeline import FusedJoinPipeline
from flink_tpu.joins.spec import JoinGeometry
from flink_tpu.parallel.mesh import SHARD_AXIS, sharded


class ShardedJoinPipeline(FusedJoinPipeline):
    """FusedJoinPipeline with key-sharded ring placement on a mesh."""

    def __init__(self, geom: JoinGeometry, mesh):
        import jax

        if geom.key_capacity % mesh.shape[SHARD_AXIS] != 0:
            raise ValueError(
                f"key capacity {geom.key_capacity} does not divide over "
                f"{mesh.shape[SHARD_AXIS]} shards (usable_mesh_size must "
                f"clamp the mesh before building the join pipeline)")
        self.mesh = mesh
        spec = sharded(mesh, None, SHARD_AXIS, None)
        super().__init__(geom, put=lambda a: jax.device_put(a, spec))

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[SHARD_AXIS]
