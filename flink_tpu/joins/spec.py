"""Plan-time surface of the device join subsystem.

Two things live here, deliberately together so they can never drift:

  * the join fallback catalog — every reason a join shape refuses the
    device path (or refuses to execute at all), each a STRUCTURED code
    with prose, mirroring the SQL planner's FALLBACK_CATALOG discipline:
    a refusal is attributed, never a bare ValueError mid-construction;

  * the join geometry planner — the bucketed-ring decomposition one
    window/interval equi-join compiles onto: bucket granule = the
    window's slice granule (gcd of size and slide), a ring deep enough to
    hold every in-flight bucket, and a per-(key, bucket, side) record
    capacity from `execution.join.bucket-capacity`.

"On the Semantic Overlap of Operators" (arXiv 2303.00793) is the design
driver: window join, interval join, and windowed lookup-enrich collapse
onto one time-bucketed ring + segment cross-match core, so ONE geometry
plan (and one kernel pair, ops/join_ring.py) serves every variant — the
window join is the interval-mask-free special case.

Layering (ARCH001): joins may import core/ops/state/config (and the
parallel mesh library) — never runtime, api, table, or scheduler.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

#: why a join stays off the device path (or refuses outright). Codes are
#: stable: the joinFallbackReason gauge exports their index (0 = none)
#: and docs/joins.md renders this table.
JOIN_FALLBACK_CATALOG: Dict[str, str] = {
    "join-full-outer": "FULL OUTER joins need both sides' NULL paddings "
                       "retracted against each other's arrivals; neither "
                       "the host StreamingJoinRunner nor the device ring "
                       "implements that yet — the statement is refused "
                       "with this reason, never built",
    "join-unwindowed": "regular (unwindowed) joins keep unbounded "
                       "two-sided state with retraction output; they "
                       "execute on the host StreamingJoinRunner",
    "join-outer-windowed": "windowed LEFT/RIGHT OUTER joins need "
                           "per-window unmatched-row padding; the device "
                           "ring emits inner matches only",
    "join-cogroup": "coGroup applies a per-(key, window) list function "
                    "on the host; there is no device form for arbitrary "
                    "list UDFs",
    "join-session-window": "session windows are not sliceable; the "
                           "bucketed ring requires a fixed bucket "
                           "granule (gcd of size and slide)",
    "join-processing-time": "the device join is event-time only; "
                            "processing-time windows fire on the host",
    "join-ring-overflow": "a (key, bucket, side) exceeded "
                          "execution.join.bucket-capacity mid-stream; "
                          "the operator degraded to the host join with "
                          "state carried over (exactly-once preserved)",
    "join-key-capacity": "the stream's distinct keys exceeded "
                         "execution.state.key-capacity; the operator "
                         "degraded to the host join with state carried "
                         "over",
    "join-device-disabled": "execution.join.device-enabled is false; "
                            "window joins execute on the host operator",
}

#: stable small-int code per reason for the joinFallbackReason gauge
#: (0 = no fallback); insertion order IS the code assignment, so append
#: new reasons at the end of the catalog, never reorder
JOIN_FALLBACK_CODES: Dict[str, int] = {
    reason: i + 1 for i, reason in enumerate(JOIN_FALLBACK_CATALOG)
}


def fallback_code(reason: Optional[str]) -> int:
    return JOIN_FALLBACK_CODES.get(reason, 0) if reason else 0


class JoinUnsupported(Exception):
    """A join shape outside the device core — typed and attributed.

    Carries the catalogued reason code; callers route it the same way the
    planner routes `Unsupported`: the SQL front door attributes the
    fallback (or refuses the statement with the catalogued prose for
    shapes no path supports, e.g. full outer), and the runtime's device
    reroute falls back to the host operator."""

    def __init__(self, reason: str, detail: str = ""):
        assert reason in JOIN_FALLBACK_CATALOG, \
            f"uncatalogued join reason {reason!r}"
        self.reason = reason
        self.detail = detail or JOIN_FALLBACK_CATALOG[reason]
        super().__init__(f"{reason}: {self.detail}")


@dataclasses.dataclass(frozen=True)
class JoinGeometry:
    """The bucketed-ring decomposition of one windowed equi-join."""

    size_ms: int                 # window size
    slide_ms: int                # window slide (== size for tumbling)
    offset_ms: int               # window offset on the epoch grid
    bucket_ms: int               # ring granule = gcd(size, slide)
    buckets_per_window: int      # size / bucket
    slide_buckets: int           # slide / bucket
    ring_buckets: int            # NB: ring depth in bucket slots
    bucket_capacity: int         # C: record slots per (key, bucket, side)
    key_capacity: int            # K: dense key ids per shard set
    interval_lo_ms: Optional[int] = None   # interval join bound, else None
    interval_hi_ms: Optional[int] = None

    @property
    def is_interval(self) -> bool:
        return self.interval_lo_ms is not None

    def window_start(self, ts: int) -> int:
        """Start of the LAST window containing `ts` (the tumbling window
        for slide == size)."""
        return ((ts - self.offset_ms) // self.slide_ms) * self.slide_ms \
            + self.offset_ms

    def bucket_of(self, ts: int) -> int:
        return (ts - self.offset_ms) // self.bucket_ms


def plan_join_geometry(
    size_ms: int,
    slide_ms: int,
    offset_ms: int,
    *,
    key_capacity: int,
    bucket_capacity: int,
    ring_slack_buckets: int = 64,
    interval_lo_ms: Optional[int] = None,
    interval_hi_ms: Optional[int] = None,
) -> JoinGeometry:
    """Validate and plan the ring geometry for a windowed equi-join.

    The ring must hold every bucket between the purge horizon (oldest
    bucket a not-yet-fired window still covers) and the newest in-flight
    bucket; `ring_slack_buckets` bounds how far event time may run ahead
    of the watermark before the ring wraps onto a live bucket — which
    degrades to the host with `join-ring-overflow`, never corrupts."""
    if size_ms <= 0 or slide_ms <= 0:
        raise ValueError(
            f"join window needs size > 0 and slide > 0, got "
            f"size={size_ms} slide={slide_ms}")
    if key_capacity <= 0 or bucket_capacity <= 0:
        raise ValueError(
            f"join ring needs key_capacity > 0 and bucket_capacity > 0, "
            f"got K={key_capacity} C={bucket_capacity}")
    bucket_ms = math.gcd(int(size_ms), int(slide_ms))
    bpw = size_ms // bucket_ms
    nb = bpw + max(int(ring_slack_buckets), 1)
    return JoinGeometry(
        size_ms=int(size_ms),
        slide_ms=int(slide_ms),
        offset_ms=int(offset_ms),
        bucket_ms=bucket_ms,
        buckets_per_window=bpw,
        slide_buckets=slide_ms // bucket_ms,
        ring_buckets=nb,
        bucket_capacity=int(bucket_capacity),
        key_capacity=int(key_capacity),
        interval_lo_ms=interval_lo_ms,
        interval_hi_ms=interval_hi_ms,
    )
