"""flink_tpu.lint — ArchUnit-style static analysis for the runtime.

The reference project enforces its architectural invariants with
flink-architecture-tests: ArchUnit rules over the compiled classes, plus
frozen violation stores that let known debt live on explicitly while new
violations fail CI. This package is the same capability for flink_tpu,
built on the Python AST:

- ``index``     — parse every module once into a shared :class:`ModuleIndex`
- ``rule``      — :class:`Rule` base class, :class:`Violation`, the registry
- ``locks``     — per-class lock model (lock attrs, guarded regions,
                  nested acquisitions) consumed by the concurrency rules
- ``contracts`` — the exactly-once declaration vocabulary
                  (``@inflight_ring`` / ``@drains`` / ``@absorbs_faults``):
                  behavior-neutral runtime decorators plus the AST-side
                  extraction the analyzer reads them back with
- ``dataflow``  — interprocedural summary layer (self-call chains to
                  MAX_COMPOSE_DEPTH, jit-option inputs, cache sites,
                  fault-carrying fixpoint) shared by the EXON rules via
                  :meth:`DataflowIndex.shared`
- ``rules_concurrency`` / ``rules_device`` / ``rules_wire`` /
  ``rules_architecture`` / ``rules_exactly_once`` — the rule families
  (CONC/DEV/WIRE+ARCH+DOC/EXON), sixteen rules total
- ``baseline``  — frozen-violation store; every entry carries a written
                  justification or the engine refuses it, with stale-entry
                  auto-prune for retired rules and deleted files
- ``engine``    — runs the registry over an index, applies the baseline
- ``cli``       — ``python -m flink_tpu.lint`` with text/JSON/SARIF output

Rules are small classes over the shared index; violations carry
``file:line`` + rule id + fix hint, so CI output is directly actionable.
"""

from flink_tpu.lint.baseline import Baseline, BaselineEntry
from flink_tpu.lint.engine import LintReport, run_lint
from flink_tpu.lint.index import ModuleIndex, ModuleInfo
from flink_tpu.lint.rule import Rule, Violation, all_rules, get_rule

__all__ = [
    "Baseline",
    "BaselineEntry",
    "LintReport",
    "ModuleIndex",
    "ModuleInfo",
    "Rule",
    "Violation",
    "all_rules",
    "get_rule",
    "run_lint",
]
