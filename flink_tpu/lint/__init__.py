"""flink_tpu.lint — ArchUnit-style static analysis for the runtime.

The reference project enforces its architectural invariants with
flink-architecture-tests: ArchUnit rules over the compiled classes, plus
frozen violation stores that let known debt live on explicitly while new
violations fail CI. This package is the same capability for flink_tpu,
built on the Python AST:

- ``index``     — parse every module once into a shared :class:`ModuleIndex`
- ``rule``      — :class:`Rule` base class, :class:`Violation`, the registry
- ``locks``     — per-class lock model (lock attrs, guarded regions,
                  nested acquisitions) consumed by the concurrency rules
- ``rules_concurrency`` / ``rules_device`` / ``rules_wire`` /
  ``rules_architecture`` — the three rule families (CONC/DEV/WIRE+ARCH+DOC)
- ``baseline``  — frozen-violation store; every entry carries a written
                  justification or the engine refuses it
- ``engine``    — runs the registry over an index, applies the baseline
- ``cli``       — ``python -m flink_tpu.lint`` with text/JSON/SARIF output

Rules are small classes over the shared index; violations carry
``file:line`` + rule id + fix hint, so CI output is directly actionable.
"""

from flink_tpu.lint.baseline import Baseline, BaselineEntry
from flink_tpu.lint.engine import LintReport, run_lint
from flink_tpu.lint.index import ModuleIndex, ModuleInfo
from flink_tpu.lint.rule import Rule, Violation, all_rules, get_rule

__all__ = [
    "Baseline",
    "BaselineEntry",
    "LintReport",
    "ModuleIndex",
    "ModuleInfo",
    "Rule",
    "Violation",
    "all_rules",
    "get_rule",
    "run_lint",
]
