import sys

from flink_tpu.lint.cli import main

sys.exit(main())
