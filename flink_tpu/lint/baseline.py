"""Frozen-violation baseline: known debt lives here, explicitly and justified.

The ArchUnit freeze-store analogue (`FreezingArchRule` /
`archunit_store/*.txt` in the reference), with one deliberate tightening:
**every entry must carry a written justification**. An entry without one
is an engine error (exit 2), not a suppression — the file documents *why*
each violation is allowed to live, so a reviewer can challenge the reason
instead of archaeology-ing the commit history.

Matching is by fingerprint (rule id + project-relative path + enclosing
scope + rule-chosen symbol), never by line number, so a baseline survives
unrelated edits to the same file. Stale entries — fingerprints no rule
reports anymore — are also engine errors: debt that got fixed must leave
the ledger, otherwise the ledger rots into noise.

``python -m flink_tpu.lint --write-baseline`` seeds entries for all
current violations with a ``TODO`` justification that the engine refuses
until a human replaces it.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

BASELINE_VERSION = 1
TODO_MARKER = "TODO"


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    path: str
    scope: str
    symbol: str
    justification: str
    line: int = 0          # informational only; never used for matching

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}::{self.path}::{self.scope}::{self.symbol}"

    @property
    def justified(self) -> bool:
        j = self.justification.strip()
        return bool(j) and not j.upper().startswith(TODO_MARKER)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "scope": self.scope,
                "symbol": self.symbol, "line": self.line,
                "justification": self.justification}


class Baseline:
    def __init__(self, entries: Optional[Iterable[BaselineEntry]] = None,
                 path: Optional[pathlib.Path] = None):
        self.path = path
        self.entries: List[BaselineEntry] = list(entries or [])
        self._by_fp: Dict[str, BaselineEntry] = {}
        for e in self.entries:
            self._by_fp[e.fingerprint] = e
        self._matched: set = set()

    def __len__(self) -> int:
        return len(self.entries)

    @classmethod
    def load(cls, path) -> "Baseline":
        path = pathlib.Path(path)
        data = json.loads(path.read_text())
        if data.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"{path}: unsupported baseline version {data.get('version')!r}"
            )
        entries = [BaselineEntry(
            rule=e["rule"], path=e["path"], scope=e.get("scope", ""),
            symbol=e.get("symbol", ""), line=int(e.get("line", 0)),
            justification=e.get("justification", ""),
        ) for e in data.get("entries", [])]
        return cls(entries, path=path)

    def save(self, path=None) -> None:
        target = pathlib.Path(path or self.path)
        entries = sorted(self.entries,
                         key=lambda e: (e.rule, e.path, e.scope, e.symbol))
        doc = {"version": BASELINE_VERSION,
               "entries": [e.to_dict() for e in entries]}
        target.write_text(json.dumps(doc, indent=2, sort_keys=False) + "\n")

    # -- engine interface --------------------------------------------------
    def match(self, violation) -> Optional[BaselineEntry]:
        """The entry suppressing `violation`, marking it live; None when
        the violation is new (and must fail the run)."""
        entry = self._by_fp.get(violation.fingerprint)
        if entry is not None:
            self._matched.add(entry.fingerprint)
        return entry

    def prune_stale(self, project_root,
                    known_rule_ids: Sequence[str]
                    ) -> List[Tuple[BaselineEntry, str]]:
        """Drop entries that can never match again — their file is gone
        or their rule id is no longer registered — and return the pruned
        ``(entry, reason)`` pairs so the caller can warn (and rewrite the
        file with :meth:`save` under ``--prune-baseline``).

        Distinct from :meth:`stale_entries`: that catches *fixed* debt
        after a run (fingerprint reported by no rule), which is an engine
        error demanding human attention; this catches entries that
        structurally cannot match (deleted file, retired rule), which
        previously were carried forever because the engine error pointed
        at a file nobody could re-lint."""
        root = pathlib.Path(project_root)
        known = set(known_rule_ids)
        pruned: List[Tuple[BaselineEntry, str]] = []
        kept: List[BaselineEntry] = []
        for e in self.entries:
            if e.rule not in known:
                pruned.append((e, f"unknown rule {e.rule!r}"))
            elif not (root / e.path).exists():
                pruned.append((e, f"file {e.path} no longer exists"))
            else:
                kept.append(e)
        if pruned:
            self.entries = kept
            self._by_fp = {e.fingerprint: e for e in kept}
        return pruned

    def stale_entries(self) -> List[BaselineEntry]:
        """Entries whose violation no rule reports anymore — fixed debt
        that must be removed from the ledger."""
        return [e for e in self.entries
                if e.fingerprint not in self._matched]

    def add(self, violation, justification: str = "") -> BaselineEntry:
        entry = BaselineEntry(
            rule=violation.rule_id, path=violation.path,
            scope=violation.scope, symbol=violation.symbol,
            line=violation.line,
            justification=justification or
            f"{TODO_MARKER}: justify or fix (added by --write-baseline)")
        self._by_fp[entry.fingerprint] = entry
        self.entries.append(entry)
        return entry
