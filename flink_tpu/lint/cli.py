"""``python -m flink_tpu.lint`` — run the analyzer from the shell / CI.

Usage:
    python -m flink_tpu.lint                       # lint flink_tpu/ + baseline
    python -m flink_tpu.lint --format sarif        # SARIF 2.1.0 to stdout
    python -m flink_tpu.lint --rule CONC002        # one rule family member
    python -m flink_tpu.lint --list-rules          # registry catalog
    python -m flink_tpu.lint --write-baseline      # freeze current findings
    python -m flink_tpu.lint path/to/pkg --no-baseline

Exit codes: 0 clean, 1 violations, 2 baseline/config errors (see
engine.py). ``--write-baseline`` seeds entries with a TODO justification
the engine refuses until a human writes the real reason — freezing debt
is explicit, not a side effect.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import List, Optional

from flink_tpu.lint.baseline import Baseline
from flink_tpu.lint.engine import (
    EXIT_BASELINE_ERROR,
    EXIT_CLEAN,
    LintReport,
    run_lint,
)
from flink_tpu.lint.rule import all_rules, get_rule

DEFAULT_BASELINE_NAME = "lint_baseline.json"


def _default_root() -> pathlib.Path:
    import flink_tpu

    return pathlib.Path(flink_tpu.__file__).parent


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m flink_tpu.lint",
        description="ArchUnit-style static analysis for flink_tpu "
                    "(concurrency, device-discipline, wire-safety rules).")
    p.add_argument("root", nargs="?", default=None,
                   help="package directory to lint (default: the installed "
                        "flink_tpu package)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", help="output format (default: text)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="frozen-violation file (default: "
                        f"<project-root>/{DEFAULT_BASELINE_NAME} when it "
                        "exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline: report every violation")
    p.add_argument("--write-baseline", action="store_true",
                   help="add entries (justification=TODO) for all current "
                        "violations, then exit 0; the engine fails until "
                        "each TODO is replaced with a real justification")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline file without entries whose "
                        "file no longer exists or whose rule id is unknown "
                        "(pruning always happens in memory with a warning; "
                        "this flag persists it)")
    p.add_argument("--rule", action="append", default=None, metavar="ID",
                   help="run only this rule id/name (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _render_text(report: LintReport, baseline: Optional[Baseline]) -> str:
    lines: List[str] = []
    for v in report.violations:
        lines.append(v.render())
    for msg in report.baseline_errors:
        lines.append(f"baseline error: {msg}")
    n_rules = len(report.rules)
    summary = (f"{report.modules_scanned} modules, {n_rules} rules: "
               f"{len(report.violations)} violation"
               f"{'s' if len(report.violations) != 1 else ''}")
    if baseline is not None:
        summary += f", {len(report.suppressed)} baselined"
    if report.baseline_errors:
        summary += f", {len(report.baseline_errors)} baseline errors"
    lines.append(summary)
    return "\n".join(lines)


def _render_json(report: LintReport) -> str:
    doc = {
        "root": str(report.root),
        "modules_scanned": report.modules_scanned,
        "rules": [r.id for r in report.rules],
        "violations": [{
            "rule": v.rule_id, "path": v.path, "line": v.line,
            "message": v.message, "scope": v.scope, "symbol": v.symbol,
            "hint": v.hint, "fingerprint": v.fingerprint,
        } for v in report.violations],
        "suppressed": [{
            "rule": v.rule_id, "path": v.path, "line": v.line,
            "justification": e.justification,
        } for v, e in report.suppressed],
        "baseline_errors": report.baseline_errors,
        "exit_code": report.exit_code,
    }
    return json.dumps(doc, indent=2)


def render_sarif(report: LintReport) -> str:
    """SARIF 2.1.0 — the format CI annotation surfaces (GitHub code
    scanning et al.) ingest natively."""
    rules_meta = [{
        "id": r.id,
        "name": r.name,
        "shortDescription": {"text": r.name},
        "fullDescription": {"text": r.rationale},
        "help": {"text": r.hint},
        "properties": {"family": r.family},
    } for r in report.rules]
    results = [{
        "ruleId": v.rule_id,
        "level": "error",
        "message": {"text": v.message + (f" (hint: {v.hint})" if v.hint
                                         else "")},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": v.path},
                "region": {"startLine": max(v.line, 1)},
            },
        }],
        "partialFingerprints": {"flinkTpuLint/v1": v.fingerprint},
    } for v in report.violations]
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "flink-tpu-lint",
                "informationUri": "docs/lint.md",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}  {r.name:28s} [{r.family}]")
        return EXIT_CLEAN

    if args.no_baseline and (args.write_baseline or args.prune_baseline):
        # --write-baseline must MERGE into the existing file; with
        # --no-baseline it would rebuild from empty and overwrite every
        # human-written justification. --prune-baseline has nothing to
        # prune when the baseline is ignored.
        print("error: --no-baseline is mutually exclusive with "
              "--write-baseline / --prune-baseline", file=sys.stderr)
        return EXIT_BASELINE_ERROR

    root = pathlib.Path(args.root) if args.root else _default_root()
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return EXIT_BASELINE_ERROR

    rules = None
    if args.rule:
        try:
            rules = [get_rule(rid) for rid in args.rule]
        except KeyError as e:
            print(f"error: {e.args[0]}", file=sys.stderr)
            return EXIT_BASELINE_ERROR

    baseline: Optional[Baseline] = None
    baseline_path = pathlib.Path(args.baseline) if args.baseline else \
        root.parent / DEFAULT_BASELINE_NAME
    if not args.no_baseline:
        if baseline_path.exists():
            baseline = Baseline.load(baseline_path)
            # entries that can never match again (deleted file, retired
            # rule) are dropped up front — otherwise the engine's stale
            # check reports them forever against a file nobody can re-lint
            pruned = baseline.prune_stale(
                baseline_path.parent, [r.id for r in all_rules()])
            for entry, reason in pruned:
                print(f"baseline: pruned stale entry {entry.fingerprint} "
                      f"({reason})", file=sys.stderr)
            if args.prune_baseline:
                baseline.save(baseline_path)
                print(f"rewrote {baseline_path}: {len(pruned)} stale entr"
                      f"{'y' if len(pruned) == 1 else 'ies'} removed, "
                      f"{len(baseline)} kept", file=sys.stderr)
        elif args.write_baseline:
            baseline = Baseline(path=baseline_path)
        elif args.baseline:
            print(f"error: baseline {baseline_path} not found",
                  file=sys.stderr)
            return EXIT_BASELINE_ERROR

    report = run_lint(root, rules=rules, baseline=baseline)

    if args.write_baseline:
        if baseline is None:
            baseline = Baseline(path=baseline_path)
        for v in report.violations:
            baseline.add(v)
        baseline.save(baseline_path)
        print(f"wrote {len(report.violations)} new entr"
              f"{'y' if len(report.violations) == 1 else 'ies'} to "
              f"{baseline_path} — replace each TODO justification before "
              f"the engine will accept them")
        return EXIT_CLEAN

    if args.format == "text":
        print(_render_text(report, baseline))
    elif args.format == "json":
        print(_render_json(report))
    else:
        print(render_sarif(report))
    return report.exit_code


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
