"""Exactly-once contract annotations: declarations the analyzer reads.

The quiescence rule (EXON001) and the fault-transparency rule (EXON003)
are *declaration-driven*: operators declare their in-flight structures and
drain methods on the class itself, next to the code that owns them, and
the analysis enforces what was declared.  This keeps the rule free of a
hand-maintained operator list — adding a new operator with a dispatch
ring means adding one decorator line, not editing the lint package.

Three decorators form the vocabulary:

``@inflight_ring("_inflight", drained_by="_resolve_inflight")``
    Class decorator.  Declares that instances own an in-flight structure
    (a deque of un-resolved device dispatches, a pending-superspan list,
    a dispatch ring) stored in the named attribute, and that calling the
    named method empties it.  EXON001 then requires every checkpoint
    capture method on the class to dominate a call to the drain (directly
    or through a chain of self-calls) — anything still in flight at a
    capture point is state the snapshot silently lost.

``@drains("_inflight", ...)``
    Method decorator.  Marks a method as a drain for the named
    attributes; lets a helper that is *not* the canonical ``drained_by``
    method satisfy the quiescence obligation (``flush_all`` vs
    ``_resolve_inflight``).  The canonical drain named in
    ``@inflight_ring`` is implicitly a drain; ``@drains`` adds others.

``@absorbs_faults("reason")``
    Function/method decorator.  Allowlists a handler that deliberately
    absorbs injected faults (EXON003), with an attributed reason the
    rule refuses to accept empty.  Prefer re-raising; this is the escape
    hatch for handlers whose *job* is absorption (e.g. a server loop
    that models "crash severs the connection" by returning).

All three are runtime no-ops beyond attaching metadata attributes — the
analysis reads the *AST*, never imports the decorated module, so the same
vocabulary works on never-importable corpus fixtures.  This module must
stay dependency-free: it is imported by runtime/ and joins/ operators,
and pulling anything heavy in here would put it on the device hot path.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

#: metadata attribute names (shared by decorators and tests)
RING_ATTR = "__lint_inflight_rings__"
DRAINS_ATTR = "__lint_drains__"
ABSORBS_ATTR = "__lint_absorbs_faults__"


# ----------------------------------------------------------------------
# runtime decorators (no-ops beyond metadata)
# ----------------------------------------------------------------------
def inflight_ring(attr: str, *, drained_by: str):
    """Declare that the decorated class owns in-flight state in ``attr``
    which ``drained_by`` (a method name) empties."""
    if not attr or not drained_by:
        raise ValueError("inflight_ring requires attr and drained_by")

    def deco(cls):
        rings = list(getattr(cls, RING_ATTR, ()))
        rings.append((attr, drained_by))
        setattr(cls, RING_ATTR, tuple(rings))
        return cls

    return deco


def drains(*attrs: str):
    """Mark the decorated method as a drain for the named attributes."""
    if not attrs:
        raise ValueError("drains requires at least one attribute name")

    def deco(fn):
        setattr(fn, DRAINS_ATTR,
                tuple(getattr(fn, DRAINS_ATTR, ()) + tuple(attrs)))
        return fn

    return deco


def absorbs_faults(reason: str):
    """Allowlist the decorated function's handlers for EXON003, with an
    attributed reason (refused when empty)."""
    if not reason or not reason.strip():
        raise ValueError("absorbs_faults requires a non-empty reason")

    def deco(fn):
        setattr(fn, ABSORBS_ATTR, reason)
        return fn

    return deco


# ----------------------------------------------------------------------
# AST extraction — what the analyzer actually consumes
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class RingDecl:
    """One ``@inflight_ring`` declaration read off a ClassDef."""

    attr: str          # instance attribute holding in-flight state
    drained_by: str    # method that empties it
    line: int          # decorator line (violation anchor)


def _decorator_name(dec: ast.AST) -> Optional[str]:
    """Trailing name of a decorator expression: ``inflight_ring`` for
    ``@inflight_ring(...)``, ``@contracts.inflight_ring(...)`` and
    ``@_contracts.inflight_ring(...)`` alike."""
    target = dec.func if isinstance(dec, ast.Call) else dec
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return None


def _const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def ring_decls(cls: ast.ClassDef) -> List[RingDecl]:
    """``@inflight_ring`` declarations on a class, in source order.
    Malformed declarations (non-literal args) are skipped — the runtime
    decorator would have raised at import time anyway."""
    out: List[RingDecl] = []
    for dec in cls.decorator_list:
        if _decorator_name(dec) != "inflight_ring" or \
                not isinstance(dec, ast.Call):
            continue
        attr = _const_str(dec.args[0]) if dec.args else None
        drained_by = None
        for kw in dec.keywords:
            if kw.arg == "drained_by":
                drained_by = _const_str(kw.value)
        if len(dec.args) > 1 and drained_by is None:
            drained_by = _const_str(dec.args[1])
        if attr and drained_by:
            out.append(RingDecl(attr=attr, drained_by=drained_by,
                                line=dec.lineno))
    return out


def drain_decls(fn: ast.AST) -> Tuple[str, ...]:
    """Attributes a ``@drains(...)`` decorated method declares it empties
    (empty tuple when undecorated)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return ()
    attrs: List[str] = []
    for dec in fn.decorator_list:
        if _decorator_name(dec) != "drains" or not isinstance(dec, ast.Call):
            continue
        for arg in dec.args:
            s = _const_str(arg)
            if s:
                attrs.append(s)
    return tuple(attrs)


def absorbs_reason(fn: ast.AST) -> Optional[str]:
    """The attributed reason of an ``@absorbs_faults`` decorator, or None.
    An empty/whitespace reason returns "" so the caller can reject it
    (distinct from "not decorated")."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if _decorator_name(dec) != "absorbs_faults":
            continue
        if isinstance(dec, ast.Call) and dec.args:
            return _const_str(dec.args[0]) or ""
        return ""          # @absorbs_faults bare / non-literal: reject
    return None


def class_drain_map(cls: ast.ClassDef) -> Dict[str, List[str]]:
    """attr -> method names that drain it, combining the canonical
    ``drained_by`` methods with every ``@drains`` declaration."""
    out: Dict[str, List[str]] = {}
    for decl in ring_decls(cls):
        out.setdefault(decl.attr, []).append(decl.drained_by)
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for attr in drain_decls(stmt):
            methods = out.setdefault(attr, [])
            if stmt.name not in methods:
                methods.append(stmt.name)
    return out


__all__ = [
    "inflight_ring", "drains", "absorbs_faults",
    "RingDecl", "ring_decls", "drain_decls", "absorbs_reason",
    "class_drain_map",
    "RING_ATTR", "DRAINS_ATTR", "ABSORBS_ATTR",
]
