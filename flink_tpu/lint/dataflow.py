"""Call-graph + summary layer over :mod:`flink_tpu.lint.index`.

The exactly-once rules (EXON001–003) need more than lexical pattern
matching: "does ``snapshot`` drain the ring" is a property of the call
*chain* (``snapshot -> flush_all -> _resolve_inflight``), "is the cache
key complete" is a property of the *builder* the memo function calls, and
"is the fault re-raised" may happen inside a helper the handler delegates
to.  This module computes per-function summaries once per module and
composes them interprocedurally to a bounded depth.

Soundness limits (documented, deliberate — this is a linter, not a
verifier):

- **Depth**: self-call chains are followed to :data:`MAX_COMPOSE_DEPTH`
  hops with a cycle guard; deeper delegation is invisible.
- **Dominance** is approximated lexically: a call dominates the exit if
  it sits on the function's unconditional statement spine (top-level
  statements, ``with``/``try`` bodies, ``finally`` blocks), or inside an
  ``if``/``while`` whose test references *only* the attribute being
  drained (the ``if self._pending: self._resolve_pending()`` guard is a
  legal drain: an empty ring needs no draining).  An early-exit guard
  (``if not self._pending: return``) extends the guard over the rest of
  the spine.
- **Aliases** resolve one hop within a function (``phases =
  self.phase_counters`` makes ``phases`` in a cache key stand for
  ``self.phase_counters``); aliases of aliases do not.
- **Call targets** resolve to methods of the same class (``self.m()``)
  and module-level functions by name; anything else (cross-module calls,
  dynamic dispatch) contributes nothing to a summary.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from flink_tpu.lint import contracts
from flink_tpu.lint.index import ModuleIndex, ModuleInfo

#: interprocedural composition depth (self-call hops followed)
MAX_COMPOSE_DEPTH = 4

#: jit/pjit option keywords whose inputs must appear in an executable
#: cache key — anything here changes the compiled bytes or the calling
#: convention of the cached callable
JIT_OPTION_KWARGS = frozenset({
    "donate_argnums", "donate_argnames", "static_argnums",
    "static_argnames", "backend", "device", "in_shardings",
    "out_shardings", "keep_unused", "readback_steps",
})

#: sentinel guard element for conditions the analysis cannot prove are
#: pure ring-emptiness tests — a drain under such a guard is conditional
OPAQUE_GUARD = "<opaque>"


# ----------------------------------------------------------------------
# small AST utilities
# ----------------------------------------------------------------------
def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` / ``self.x`` / ``name`` for a Name/Attribute chain rooted
    at a Name; None for anything else (calls, subscripts)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def dotted_names(expr: ast.AST, *, skip_callees: bool = True) -> Set[str]:
    """Every maximal dotted name referenced in ``expr``.  Names in callee
    position (``self._use_pallas()``'s func) are skipped by default —
    calling a method is not *using its value* as data."""
    out: Set[str] = set()
    skip: Set[int] = set()
    if skip_callees:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                f = node.func
                while isinstance(f, ast.Attribute):
                    skip.add(id(f))
                    f = f.value
                skip.add(id(f))
    # collect maximal chains only: mark inner nodes of each chain
    inner: Set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute):
            inner.add(id(node.value))
    for node in ast.walk(expr):
        if id(node) in skip or id(node) in inner:
            continue
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted(node)
            if d is not None:
                out.add(d)
    return out


def _is_jit_callable(fn: ast.AST) -> bool:
    """jax.jit / bare jit / pjit / jax.pjit expressions."""
    if isinstance(fn, ast.Attribute) and fn.attr in ("jit", "pjit"):
        return True
    return isinstance(fn, ast.Name) and fn.id in ("jit", "pjit")


def jit_calls(root: ast.AST) -> Iterator[ast.Call]:
    """Calls that configure a compiled executable: ``jax.jit(...)``,
    ``pjit(...)``, and ``partial(jax.jit, ...)``."""
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        if _is_jit_callable(node.func):
            yield node
        else:
            f = node.func
            is_partial = (isinstance(f, ast.Name) and f.id == "partial") or \
                (isinstance(f, ast.Attribute) and f.attr == "partial")
            if is_partial and node.args and _is_jit_callable(node.args[0]):
                yield node


def _jit_option_kwargs(call: ast.Call) -> List[ast.keyword]:
    return [kw for kw in call.keywords if kw.arg in JIT_OPTION_KWARGS]


def _container_ctor(expr: ast.AST) -> bool:
    """deque()/list()/[]/{}  — the shapes an in-flight structure is born
    with in ``__init__``."""
    if isinstance(expr, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(expr, ast.Call):
        d = dotted(expr.func)
        return d is not None and d.split(".")[-1] in (
            "deque", "list", "dict", "OrderedDict", "defaultdict")
    return False


# ----------------------------------------------------------------------
# summaries
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class DominantCall:
    """A call on the function's unconditional spine.  ``guard_attrs`` is
    empty for truly unconditional calls; ``{"_pending"}`` for a call
    guarded by a pure emptiness test of that attribute; contains
    :data:`OPAQUE_GUARD` when the guard tests anything else."""

    name: str                      # "self.flush_all" / "helper" (dotted)
    guard_attrs: FrozenSet[str]
    line: int


@dataclasses.dataclass
class CacheKeySite:
    """A dict-memo lookup: ``key = (...)`` then ``CACHE.get(key)`` /
    ``key in CACHE`` / ``CACHE[key]`` in the same function."""

    cache_name: str                # "self._fn_cache" / "_CHAINED_CACHE"
    key_var: str
    line: int                      # line of the key assignment
    components: Set[str]           # alias-resolved dotted names in the key
    opaque: bool = False           # key expression was not a plain tuple


@dataclasses.dataclass
class HandlerInfo:
    """One ``except`` clause."""

    type_names: Tuple[str, ...]    # trailing names; () for a bare except
    line: int
    node: ast.ExceptHandler
    try_node: ast.Try


@dataclasses.dataclass
class FunctionSummary:
    name: str
    qualname: str                  # "Class.method" or "func"
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    line: int
    params: Tuple[str, ...]
    self_calls: Set[str]           # method names called on self, anywhere
    calls: Set[str]                # dotted names of all calls, anywhere
    call_nodes: List[ast.Call]     # every call site (argument mapping)
    attrs_written: Set[str]        # self.X assigned/augassigned
    attrs_read: Set[str]           # self.X read
    handlers: List[HandlerInfo]
    dominant_calls: List[DominantCall]
    jit_option_inputs: Set[str]    # dotted names flowing into jit options
    cache_sites: List[CacheKeySite]
    reraise_params: Set[str]       # params re-raised alongside an
                                   # InjectedCrash/InjectedFault reference
    drains_decl: Tuple[str, ...]   # @drains(...) attributes
    absorbs_reason: Optional[str]  # @absorbs_faults reason (None: absent)
    has_lru_cache: bool            # functools.lru_cache / functools.cache
    has_seam_call: bool            # calls the chaos HOOK directly


@dataclasses.dataclass
class ClassSummary:
    name: str
    node: ast.ClassDef
    line: int
    bases: Tuple[str, ...]                # base-class expressions (dotted)
    rings: List[contracts.RingDecl]
    drain_map: Dict[str, List[str]]       # attr -> draining method names
    methods: Dict[str, FunctionSummary]
    init_container_attrs: Dict[str, int]  # self.X = deque()/[] in __init__

    @property
    def has_bases(self) -> bool:
        """True when the class inherits from anything but object — its
        methods/attrs may live on the base, outside this summary."""
        return any(b != "object" for b in self.bases)


@dataclasses.dataclass
class ModuleSummary:
    mod: ModuleInfo
    classes: Dict[str, ClassSummary]
    functions: Dict[str, FunctionSummary]  # module-level defs


# ----------------------------------------------------------------------
# per-function summarization
# ----------------------------------------------------------------------
def _guard_attrs(test: ast.AST) -> FrozenSet[str]:
    """Attributes a guard condition tests.  Pure emptiness tests of
    ``self.X`` (optionally through ``not``/``len``/comparisons against
    constants) yield ``{X}``; anything else contributes
    :data:`OPAQUE_GUARD` so the caller treats the branch as conditional."""
    attrs: Set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                attrs.add(node.attr)
            elif not isinstance(node.value, ast.Attribute):
                attrs.add(OPAQUE_GUARD)
        elif isinstance(node, ast.Name):
            if node.id not in ("self", "len"):
                attrs.add(OPAQUE_GUARD)
        elif isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Name) and
                    node.func.id == "len"):
                attrs.add(OPAQUE_GUARD)
        elif not isinstance(node, (ast.UnaryOp, ast.BoolOp, ast.Compare,
                                   ast.Constant, ast.Load, ast.Not,
                                   ast.USub, ast.And, ast.Or, ast.Eq,
                                   ast.NotEq, ast.Gt, ast.GtE, ast.Lt,
                                   ast.LtE, ast.Is, ast.IsNot, ast.In,
                                   ast.NotIn)):
            attrs.add(OPAQUE_GUARD)
    return frozenset(attrs)


def _only_exits(body: Sequence[ast.stmt]) -> bool:
    return all(isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                              ast.Pass)) for s in body)


def _spine_calls(body: Sequence[ast.stmt],
                 guards: FrozenSet[str]) -> Iterator[DominantCall]:
    """Calls on the unconditional spine of ``body`` (see module
    docstring for the dominance approximation)."""
    guards = frozenset(guards)
    for stmt in body:
        if isinstance(stmt, (ast.Expr, ast.Assign, ast.AugAssign,
                             ast.AnnAssign, ast.Return)):
            value = getattr(stmt, "value", None)
            if value is not None:
                for node in ast.walk(value):
                    if isinstance(node, ast.Call):
                        d = dotted(node.func)
                        if d:
                            yield DominantCall(d, guards, node.lineno)
        elif isinstance(stmt, ast.With):
            yield from _spine_calls(stmt.body, guards)
        elif isinstance(stmt, ast.Try):
            yield from _spine_calls(stmt.body, guards)
            yield from _spine_calls(stmt.finalbody, guards)
        elif isinstance(stmt, (ast.If, ast.While)):
            g = _guard_attrs(stmt.test)
            if isinstance(stmt, ast.If) and _only_exits(stmt.body) and \
                    not stmt.orelse:
                # early-exit guard: the REST of the spine runs under it
                guards = guards | g
                continue
            yield from _spine_calls(stmt.body, guards | g)
            if isinstance(stmt, ast.If) and stmt.orelse:
                yield from _spine_calls(stmt.orelse, guards | g)
        # For loops / nested defs: never dominant
        if isinstance(stmt, (ast.Return, ast.Raise)):
            return


def _alias_map(fn: ast.AST) -> Dict[str, str]:
    """One-hop local aliases: ``phases = self.phase_counters`` lets a
    cache-key component named ``phases`` resolve to the attribute."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            src = dotted(node.value)
            if src is not None:
                aliases[node.targets[0].id] = src
    return aliases


def _derivation_map(fn: ast.AST) -> Dict[str, Set[str]]:
    """One-hop local *derivations*: ``donate_args = (0, 1) if donate
    else ()`` maps ``donate_args`` to ``{donate}`` — the dotted names its
    value was computed from.  Lets an option input expressed through a
    local stand for its roots."""
    out: Dict[str, Set[str]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            names = dotted_names(node.value)
            if names:
                out[node.targets[0].id] = names
    return out


def _cache_sites(fn: ast.AST) -> List[CacheKeySite]:
    aliases = _alias_map(fn)
    # key-var candidates: name = (tuple ...) assignments
    key_assigns: Dict[str, ast.Assign] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Tuple):
            key_assigns[node.targets[0].id] = node
    if not key_assigns:
        return []
    sites: List[CacheKeySite] = []
    seen: Set[Tuple[str, str]] = set()
    for node in ast.walk(fn):
        cache = keyvar = None
        if isinstance(node, ast.Subscript) and \
                isinstance(node.slice, ast.Name):
            cache, keyvar = dotted(node.value), node.slice.id
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("get", "setdefault") and node.args and \
                isinstance(node.args[0], ast.Name):
            cache, keyvar = dotted(node.func.value), node.args[0].id
        elif isinstance(node, ast.Compare) and \
                isinstance(node.left, ast.Name) and len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)):
            cache, keyvar = dotted(node.comparators[0]), node.left.id
        if cache is None or keyvar not in key_assigns:
            continue
        if (cache, keyvar) in seen:
            continue
        seen.add((cache, keyvar))
        assign = key_assigns[keyvar]
        components: Set[str] = set()
        for name in dotted_names(assign.value):
            components.add(aliases.get(name, name))
        sites.append(CacheKeySite(cache_name=cache, key_var=keyvar,
                                  line=assign.lineno,
                                  components=components))
    return sites


def _jit_option_inputs(fn: ast.AST) -> Set[str]:
    """Dotted names that influence jit/pjit options inside ``fn``: names
    in option-kwarg values, plus the tests of any ``if``/conditional
    expression that selects between jit configurations."""
    calls = list(jit_calls(fn))
    if not calls:
        return set()
    call_ids = {id(c) for c in calls}
    inputs: Set[str] = set()
    has_options = False
    for c in calls:
        for kw in _jit_option_kwargs(c):
            has_options = True
            inputs |= dotted_names(kw.value)
    if not has_options:
        return inputs
    # any branch that contains a jit call makes its test an input
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.IfExp)):
            subtree_calls = {id(n) for n in ast.walk(node)
                             if isinstance(n, ast.Call)}
            if subtree_calls & call_ids:
                inputs |= dotted_names(node.test)
    # resolve locals to their roots (one hop): donate_args derived from
    # the `donate` parameter IS the parameter, as far as the key cares
    derived = _derivation_map(fn)
    resolved: Set[str] = set()
    for name in inputs:
        if "." not in name and name in derived:
            resolved |= derived[name]
        else:
            resolved.add(name)
    return resolved


_INJECTED = ("InjectedCrash", "InjectedFault")


def _reraise_params(fn: ast.AST, params: Sequence[str]) -> Set[str]:
    """Params the function re-raises while referencing the injected fault
    types — the ``coordinator._failed`` transparency-helper pattern."""
    mentions_injected = any(
        isinstance(n, (ast.Name, ast.Attribute)) and
        (dotted(n) or "").split(".")[-1] in _INJECTED
        for n in ast.walk(fn))
    if not mentions_injected:
        return set()
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Raise) and isinstance(node.exc, ast.Name) \
                and node.exc.id in params:
            out.add(node.exc.id)
    return out


def _handler_type_names(h: ast.ExceptHandler) -> Tuple[str, ...]:
    if h.type is None:
        return ()
    types = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    names = []
    for t in types:
        d = dotted(t)
        names.append(d.split(".")[-1] if d else "<expr>")
    return tuple(names)


def seam_calls(root: ast.AST,
               aliases: Optional[Dict[str, str]] = None) -> List[ast.Call]:
    """Chaos-seam invocations inside ``root``: calls through a local
    alias of ``*.HOOK`` (the ``hook = _chaos.HOOK; hook(scope, site)``
    idiom) or directly on a ``*.HOOK`` attribute.  These are the ONLY
    program points where an InjectedFault/InjectedCrash originates."""
    if aliases is None:
        aliases = _alias_map(root)
    hook_names = {name for name, src in aliases.items()
                  if src.endswith(".HOOK") or src == "HOOK"}
    out: List[ast.Call] = []
    for node in ast.walk(root):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in hook_names:
            out.append(node)
        elif isinstance(f, ast.Attribute) and f.attr == "HOOK":
            out.append(node)
    return out


def _has_lru_cache(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        d = dotted(target)
        if d and d.split(".")[-1] in ("lru_cache", "cache"):
            return True
    return False


def summarize_function(fn: ast.AST, qualname: str) -> FunctionSummary:
    params = tuple(a.arg for a in
                   fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs)
    self_calls: Set[str] = set()
    calls: Set[str] = set()
    call_nodes: List[ast.Call] = []
    attrs_written: Set[str] = set()
    attrs_read: Set[str] = set()
    handlers: List[HandlerInfo] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            call_nodes.append(node)
            d = dotted(node.func)
            if d:
                calls.add(d)
                if d.startswith("self.") and d.count(".") == 1:
                    self_calls.add(d.split(".", 1)[1])
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                attrs_written.add(node.attr)
            else:
                attrs_read.add(node.attr)
        elif isinstance(node, ast.Try):
            for h in node.handlers:
                handlers.append(HandlerInfo(_handler_type_names(h),
                                            h.lineno, h, node))
    return FunctionSummary(
        name=fn.name, qualname=qualname, node=fn, line=fn.lineno,
        params=params, self_calls=self_calls, calls=calls,
        call_nodes=call_nodes, attrs_written=attrs_written,
        attrs_read=attrs_read, handlers=handlers,
        dominant_calls=list(_spine_calls(fn.body, frozenset())),
        jit_option_inputs=_jit_option_inputs(fn),
        cache_sites=_cache_sites(fn),
        reraise_params=_reraise_params(fn, params),
        drains_decl=contracts.drain_decls(fn),
        absorbs_reason=contracts.absorbs_reason(fn),
        has_lru_cache=_has_lru_cache(fn),
        has_seam_call=bool(seam_calls(fn)),
    )


def summarize_class(cls: ast.ClassDef) -> ClassSummary:
    methods: Dict[str, FunctionSummary] = {}
    init_containers: Dict[str, int] = {}
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        methods[stmt.name] = summarize_function(
            stmt, f"{cls.name}.{stmt.name}")
        if stmt.name == "__init__":
            for node in ast.walk(stmt):
                targets: List[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if value is None or not _container_ctor(value):
                    continue
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        init_containers.setdefault(t.attr, t.lineno)
    return ClassSummary(
        name=cls.name, node=cls, line=cls.lineno,
        bases=tuple(dotted(b) or "<expr>" for b in cls.bases),
        rings=contracts.ring_decls(cls),
        drain_map=contracts.class_drain_map(cls),
        methods=methods, init_container_attrs=init_containers)


def summarize_module(mod: ModuleInfo) -> ModuleSummary:
    classes: Dict[str, ClassSummary] = {}
    functions: Dict[str, FunctionSummary] = {}
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.ClassDef):
            classes[stmt.name] = summarize_class(stmt)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            functions[stmt.name] = summarize_function(stmt, stmt.name)
    return ModuleSummary(mod=mod, classes=classes, functions=functions)


# ----------------------------------------------------------------------
# the index: one summary set per module, plus composed queries
# ----------------------------------------------------------------------
class DataflowIndex:
    """Summaries for every module in a :class:`ModuleIndex`, computed
    lazily and cached, plus the interprocedural queries the EXON rules
    ask."""

    def __init__(self, index: ModuleIndex):
        self.index = index
        self._cache: Dict[str, ModuleSummary] = {}
        self._carrying: Optional[Set[str]] = None

    @classmethod
    def shared(cls, index: ModuleIndex) -> "DataflowIndex":
        """One DataflowIndex per ModuleIndex, cached on the index itself:
        the three EXON rules each need the same per-module summaries and
        the same fault-carrying fixpoint, and rebuilding them tripled the
        full-registry wall clock (the test_lint_full budget)."""
        dfi = getattr(index, "_dataflow_index", None)
        if dfi is None or dfi.index is not index:
            dfi = cls(index)
            index._dataflow_index = dfi
        return dfi

    def module(self, mod: ModuleInfo) -> ModuleSummary:
        ms = self._cache.get(mod.rel)
        if ms is None:
            ms = self._cache[mod.rel] = summarize_module(mod)
        return ms

    # -- EXON001: quiescence ------------------------------------------
    def drains_attr(self, cls: ClassSummary, method: str, attr: str,
                    depth: int = MAX_COMPOSE_DEPTH,
                    _visited: Optional[Set[str]] = None) -> bool:
        """True when calling ``method`` on an instance of ``cls``
        dominates a drain of ``attr``: the method is a declared drain, or
        its unconditional spine (allowing the pure ``if self.<attr>:``
        guard) calls one, transitively to ``depth`` hops."""
        if method in cls.drain_map.get(attr, ()):
            return True
        fs = cls.methods.get(method)
        if fs is None:
            return False
        if attr in fs.drains_decl:
            return True
        if depth <= 0:
            return False
        visited = _visited if _visited is not None else set()
        if method in visited:
            return False
        visited.add(method)
        for dc in fs.dominant_calls:
            if not dc.name.startswith("self."):
                continue
            if not dc.guard_attrs <= {attr}:
                continue          # guarded by something other than the ring
            callee = dc.name.split(".", 1)[1]
            if self.drains_attr(cls, callee, attr, depth - 1, visited):
                return True
        return False

    # -- EXON002: cache-key completeness ------------------------------
    def required_key_inputs(self, msum: ModuleSummary,
                            cls: Optional[ClassSummary],
                            fs: FunctionSummary,
                            depth: int = MAX_COMPOSE_DEPTH,
                            _visited: Optional[Set[str]] = None) -> Set[str]:
        """Dotted names (caller's frame) that flow into jit/pjit options
        reachable from ``fs`` — the set a memo key must cover.  ``self.X``
        inputs of same-class callees propagate unchanged (same instance);
        parameter inputs map through the call-site arguments."""
        required = set(fs.jit_option_inputs)
        if depth <= 0:
            return required
        visited = _visited if _visited is not None else set()
        if fs.qualname in visited:
            return required
        visited.add(fs.qualname)
        for call in fs.call_nodes:
            d = dotted(call.func)
            if d is None:
                continue
            callee: Optional[FunctionSummary] = None
            if d.startswith("self.") and d.count(".") == 1 and \
                    cls is not None:
                callee = cls.methods.get(d.split(".", 1)[1])
            elif "." not in d:
                callee = msum.functions.get(d)
            if callee is None:
                continue
            sub = self.required_key_inputs(msum, cls, callee, depth - 1,
                                           visited)
            for name in sub:
                if name.startswith("self."):
                    if d.startswith("self."):
                        required.add(name)       # same instance
                elif name in callee.params:
                    mapped = self._map_param(callee, call, name,
                                             skip_self=d.startswith("self."))
                    if mapped:
                        required.add(mapped)
        return required

    @staticmethod
    def _map_param(callee: FunctionSummary, call: ast.Call, param: str,
                   *, skip_self: bool) -> Optional[str]:
        """Dotted name of the call-site argument bound to ``param``."""
        for kw in call.keywords:
            if kw.arg == param:
                names = dotted_names(kw.value)
                return next(iter(names)) if len(names) == 1 else None
        params = list(callee.params)
        if skip_self and params and params[0] in ("self", "cls"):
            params = params[1:]
        try:
            pos = params.index(param)
        except ValueError:
            return None
        if pos < len(call.args):
            names = dotted_names(call.args[pos])
            return next(iter(names)) if len(names) == 1 else None
        return None

    # -- EXON003: fault transparency ----------------------------------
    def fault_carrying_names(self) -> Set[str]:
        """Trailing names of functions through which an injected fault
        can propagate: functions containing a direct seam call, plus
        (fixpoint, :data:`MAX_COMPOSE_DEPTH` rounds) functions that call
        one by trailing name.  Name-based matching across modules is a
        deliberate over-approximation — dynamic dispatch (RPC proxies,
        thread targets) breaks the chain, which is the documented
        soundness limit."""
        if self._carrying is not None:
            return self._carrying
        summaries: List[FunctionSummary] = []
        for mod in self.index.modules:
            msum = self.module(mod)
            summaries.extend(msum.functions.values())
            for cls in msum.classes.values():
                summaries.extend(cls.methods.values())
        carrying: Set[str] = {fs.name for fs in summaries
                              if fs.has_seam_call}
        trailing = [(fs.name, {d.split(".")[-1] for d in fs.calls})
                    for fs in summaries]
        for _ in range(MAX_COMPOSE_DEPTH):
            added = False
            for name, called in trailing:
                if name not in carrying and called & carrying:
                    carrying.add(name)
                    added = True
            if not added:
                break
        self._carrying = carrying
        return carrying

    def try_body_carries_fault(self, try_node: ast.Try,
                               fn_node: Optional[ast.AST] = None) -> bool:
        """True when the try BODY (not the handlers) can raise an
        injected fault: it makes a seam call directly, or calls a
        fault-carrying function by trailing name.  ``fn_node`` supplies
        the alias scope for the ``hook = _chaos.HOOK`` idiom."""
        aliases = _alias_map(fn_node if fn_node is not None else try_node)
        carrying = self.fault_carrying_names()
        for stmt in try_node.body:
            if seam_calls(stmt, aliases):
                return True
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    if d and d.split(".")[-1] in carrying:
                        return True
        return False

    def call_reraises(self, msum: ModuleSummary, cls: Optional[ClassSummary],
                      calls: Sequence[ast.Call], caught: str) -> bool:
        """True when one of ``calls`` (normally the calls inside an
        ``except`` body) passes the caught exception ``caught`` to a
        helper that re-raises the param alongside an injected-fault
        reference (``coordinator._failed(cid, exc)`` pattern)."""
        for call in calls:
            passes = any(isinstance(a, ast.Name) and a.id == caught
                         for a in call.args) or \
                any(isinstance(kw.value, ast.Name) and kw.value.id == caught
                    for kw in call.keywords)
            if not passes:
                continue
            d = dotted(call.func)
            if d is None:
                continue
            callee: Optional[FunctionSummary] = None
            skip_self = False
            if d.startswith("self.") and d.count(".") == 1 and \
                    cls is not None:
                callee = cls.methods.get(d.split(".", 1)[1])
                skip_self = True
            elif "." not in d:
                callee = msum.functions.get(d)
            if callee is None or not callee.reraise_params:
                continue
            # which callee param receives `caught`?
            for kw in call.keywords:
                if isinstance(kw.value, ast.Name) and kw.value.id == caught \
                        and kw.arg in callee.reraise_params:
                    return True
            params = list(callee.params)
            if skip_self and params and params[0] in ("self", "cls"):
                params = params[1:]
            for i, a in enumerate(call.args):
                if isinstance(a, ast.Name) and a.id == caught and \
                        i < len(params) and params[i] in callee.reraise_params:
                    return True
        return False


__all__ = [
    "MAX_COMPOSE_DEPTH", "JIT_OPTION_KWARGS", "OPAQUE_GUARD",
    "dotted", "dotted_names", "jit_calls",
    "DominantCall", "CacheKeySite", "HandlerInfo",
    "FunctionSummary", "ClassSummary", "ModuleSummary",
    "summarize_function", "summarize_class", "summarize_module",
    "DataflowIndex",
]
