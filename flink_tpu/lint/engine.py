"""The lint engine: run the registry over an index, apply the baseline.

Exit-code contract (CI-friendly, mirrors the CLI):

- 0 — clean: no unbaselined violations, no baseline errors
- 1 — violations: at least one finding not covered by a justified entry
- 2 — baseline/config errors: an entry without a written justification,
  a stale entry (its violation no longer exists), or an unparseable
  source file — states where the *ledger* is wrong, which must not be
  conflated with (or masked by) code findings
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import List, Optional, Sequence, Tuple

from flink_tpu.lint.baseline import Baseline, BaselineEntry
from flink_tpu.lint.index import ModuleIndex
from flink_tpu.lint.rule import Rule, Violation, all_rules

EXIT_CLEAN = 0
EXIT_VIOLATIONS = 1
EXIT_BASELINE_ERROR = 2


@dataclasses.dataclass
class LintReport:
    root: pathlib.Path
    rules: List[Rule]
    violations: List[Violation]                      # active (fail the run)
    suppressed: List[Tuple[Violation, BaselineEntry]]
    baseline_errors: List[str]
    modules_scanned: int

    @property
    def exit_code(self) -> int:
        if self.baseline_errors:
            return EXIT_BASELINE_ERROR
        if self.violations:
            return EXIT_VIOLATIONS
        return EXIT_CLEAN

    def by_rule(self, rule_id: str) -> List[Violation]:
        return [v for v in self.violations if v.rule_id == rule_id]


def run_lint(root, package: Optional[str] = None,
             rules: Optional[Sequence[Rule]] = None,
             baseline: Optional[Baseline] = None,
             index: Optional[ModuleIndex] = None) -> LintReport:
    """Run `rules` (default: the full registry) over the package at
    `root`, suppressing findings matched by justified baseline entries."""
    index = index or ModuleIndex(pathlib.Path(root), package=package)
    rules = list(rules) if rules is not None else all_rules()
    baseline_errors: List[str] = []
    for fail in index.parse_failures:
        baseline_errors.append(
            f"{fail.rel}:{fail.line}: cannot parse: {fail.error}")

    active: List[Violation] = []
    suppressed: List[Tuple[Violation, BaselineEntry]] = []
    for rule in rules:
        for violation in rule.check(index):
            entry = baseline.match(violation) if baseline is not None else None
            if entry is None:
                active.append(violation)
            elif not entry.justified:
                baseline_errors.append(
                    f"baseline entry {entry.fingerprint} has no written "
                    f"justification — justify it or fix the violation")
                suppressed.append((violation, entry))
            else:
                suppressed.append((violation, entry))

    if baseline is not None:
        # stale detection is only meaningful against the full registry —
        # a filtered run would call every other rule's entries stale
        full_run = {r.id for r in rules} >= {r.id for r in all_rules()}
        if full_run:
            for entry in baseline.stale_entries():
                baseline_errors.append(
                    f"stale baseline entry {entry.fingerprint}: the "
                    f"violation no longer exists — remove it from the "
                    f"baseline")

    active.sort(key=lambda v: (v.path, v.line, v.rule_id))
    return LintReport(root=index.root, rules=rules, violations=active,
                      suppressed=suppressed, baseline_errors=baseline_errors,
                      modules_scanned=len(index.modules))
