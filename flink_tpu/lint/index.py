"""Shared module index: every ``*.py`` under a package root, parsed once.

Rules never touch the filesystem themselves — they iterate
:class:`ModuleIndex.modules` and reuse the cached ASTs, so a full lint run
parses each file exactly once no matter how many rules inspect it (the
ArchUnit "imported classes" analogue).

The index is package-relative on purpose: rules address modules by their
path relative to the package root (``runtime/rpc.py``) and by dotted name
(``<package>.runtime.rpc``), never by absolute path, so the same rules run
unchanged over the real ``flink_tpu`` package and over the tiny fixture
packages the rule tests synthesize in ``tmp_path``.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
from typing import Dict, Iterator, List, Optional, Tuple


@dataclasses.dataclass
class ModuleInfo:
    """One parsed module."""

    path: pathlib.Path        # absolute file path
    rel: str                  # posix path relative to the package root
    module: str               # dotted module name, package-qualified
    source: str
    tree: ast.Module

    @property
    def rel_to_project(self) -> str:
        """Path relative to the PROJECT root (package dir's parent) — what
        violations and baselines record, e.g. ``flink_tpu/runtime/rpc.py``."""
        return f"{self.module.split('.')[0]}/{self.rel}"


@dataclasses.dataclass
class ParseFailure:
    path: pathlib.Path
    rel: str
    error: str
    line: int


class ModuleIndex:
    """Parses every module under ``root`` once; shared by all rules.

    ``root`` is the package directory (e.g. ``.../flink_tpu``);
    ``package`` defaults to the directory name and prefixes every dotted
    module name, so import-matching rules compare against
    ``f"{index.package}.runtime"`` instead of a hardcoded ``flink_tpu``.
    """

    def __init__(self, root: pathlib.Path, package: Optional[str] = None):
        self.root = pathlib.Path(root).resolve()
        if not self.root.is_dir():
            raise NotADirectoryError(f"lint root {self.root} is not a directory")
        self.package = package or self.root.name
        self.modules: List[ModuleInfo] = []
        self.parse_failures: List[ParseFailure] = []
        self._by_rel: Dict[str, ModuleInfo] = {}
        for path in sorted(self.root.rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(self.root).as_posix()
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except SyntaxError as e:
                self.parse_failures.append(
                    ParseFailure(path, rel, str(e), e.lineno or 0))
                continue
            except (UnicodeDecodeError, ValueError) as e:
                # undecodable bytes: report like a syntax error (exit 2)
                # instead of killing the whole run with a traceback
                self.parse_failures.append(ParseFailure(path, rel, str(e), 0))
                continue
            mod = ModuleInfo(path=path, rel=rel,
                             module=self._dotted(rel), source=source,
                             tree=tree)
            self.modules.append(mod)
            self._by_rel[rel] = mod

    @property
    def project_root(self) -> pathlib.Path:
        """Directory holding the package (where ``docs/`` and the baseline
        live)."""
        return self.root.parent

    def _dotted(self, rel: str) -> str:
        parts = rel[:-3].split("/")          # strip ".py"
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join([self.package, *parts]) if parts else self.package

    def get(self, rel: str) -> Optional[ModuleInfo]:
        return self._by_rel.get(rel)

    def in_subtree(self, prefix: str) -> Iterator[ModuleInfo]:
        """Modules whose package-relative path starts with ``prefix + '/'``
        (or equals ``prefix`` for a single file)."""
        for mod in self.modules:
            if mod.rel == prefix or mod.rel.startswith(prefix.rstrip("/") + "/"):
                yield mod

    # ------------------------------------------------------------------
    # import extraction (shared by the architecture/device/wire families)
    # ------------------------------------------------------------------
    def resolve_import_from(self, mod: ModuleInfo,
                            node: ast.ImportFrom) -> Optional[str]:
        """Absolute dotted module an ``ImportFrom`` targets, resolving
        relative imports (``from ..runtime import x``) against the module's
        own location; None for unresolvable over-deep relatives."""
        if node.level == 0:
            return node.module
        base = mod.module.split(".")
        # "from . import x" (level 1) in pkg/sub/mod.py resolves against
        # pkg.sub; in pkg/sub/__init__.py the dotted name ALREADY names the
        # package (``_dotted`` strips __init__), so one less level drops
        drop = node.level - 1 if mod.rel.endswith("__init__.py") \
            else node.level
        if drop >= len(base):
            return None           # escapes above the indexed package
        anchor = base[:-drop] if drop else base
        return ".".join([*anchor, node.module]) if node.module else \
            ".".join(anchor) or None

    def _import_from_names(self, mod: ModuleInfo,
                           node: ast.ImportFrom) -> List[str]:
        """Dotted names an ImportFrom can bind: the base module AND
        base.<alias> for each imported name — `from flink_tpu import
        runtime` must resolve to flink_tpu.runtime, or the ordinary
        spelling of a layering violation bypasses every banned-prefix
        check. base.<alias> for a non-module symbol (a class, a function)
        is harmless over-approximation: it never prefix-matches a banned
        MODULE unless the module itself does."""
        target = self.resolve_import_from(mod, node)
        if not target:
            return []
        names = [f"{target}.{a.name}" for a in node.names if a.name != "*"]
        # base alone only for `import *` — otherwise it is a prefix of
        # every alias name and would double-report each statement
        return names or [target]

    def module_level_imports(
            self, mod: ModuleInfo) -> List[Tuple[str, int]]:
        """Imports executed at import time: module body + class bodies, but
        NOT function bodies (lazy imports are the sanctioned layering
        escape hatch — execution entry points import the executor when
        called, so importing the API layer never drags in the runtime)."""
        found: List[Tuple[str, int]] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Import):
                    found.extend((a.name, child.lineno) for a in child.names)
                elif isinstance(child, ast.ImportFrom):
                    found.extend((name, child.lineno) for name in
                                 self._import_from_names(mod, child))
                else:
                    walk(child)

        walk(mod.tree)
        return found

    def all_imports(self, mod: ModuleInfo) -> List[Tuple[str, int]]:
        """EVERY import in the file, function bodies included — for rules
        where even a lazy import is a violation."""
        found: List[Tuple[str, int]] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                found.extend((a.name, node.lineno) for a in node.names)
            elif isinstance(node, ast.ImportFrom):
                found.extend((name, node.lineno) for name in
                             self._import_from_names(mod, node))
        return found


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    """child -> parent for every node; rules use it to answer "is this call
    inside a loop / a locked region / a jitted function" lexically."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing_scope(parents: Dict[ast.AST, ast.AST], node: ast.AST) -> str:
    """Dotted qualname of the classes/functions enclosing ``node`` — the
    stable part of a violation fingerprint (survives line churn)."""
    names: List[str] = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            names.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(names))
