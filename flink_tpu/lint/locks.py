"""Per-class lock model for the concurrency rule family.

For every class (and for module-level functions, treated as a pseudo-class
guarding ``global`` state), the model records:

- **lock attributes** — ``self._x = threading.Lock()/RLock()/Condition()``
  (module level: ``NAME = threading.Lock()``), each with its kind;
- **locked regions** — ``with self._x:`` blocks, tracked lexically while
  walking each method, so every attribute write, call, and nested
  acquisition knows exactly which locks are held around it;
- **attribute writes** — plain assigns, augmented assigns, tuple unpacks,
  subscript stores/deletes, and mutating container method calls
  (``.append()``/``.pop()``/...) on ``self.<attr>`` receivers;
- **nested acquisitions** — ``with a: ... with b:`` edges feeding the
  cross-module lock-order graph (static deadlock detection);
- **self-call propagation (one hop)** — a helper that is *only* invoked
  from regions holding lock L is treated as running under L
  (``RpcGateway._close_locked`` / ``Meter._trim`` pattern: the lock-held
  private helper). No fixpoint — one hop keeps the model predictable.

Lexicality is a feature: aliases (``task = self`` captured by a nested
class) make both the region and the write invisible *symmetrically*, so
the guarded-by rule never produces evidence it cannot defend.
"""

from __future__ import annotations

import ast
import dataclasses
import weakref
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from flink_tpu.lint.index import ModuleIndex, ModuleInfo

LOCK_FACTORIES = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}

# container-mutating method names treated as writes to the receiver attr
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "popitem",
    "remove", "clear", "update", "setdefault", "add", "discard",
}

#: methods whose unguarded writes are construction, not racing state
CONSTRUCTION_METHODS = {"__init__", "__new__", "__init_subclass__"}


@dataclasses.dataclass(frozen=True)
class LockAttr:
    name: str          # attribute (or module-global) name
    kind: str          # "Lock" | "RLock" | "Condition"
    line: int


@dataclasses.dataclass
class AttrWrite:
    attr: str
    line: int
    method: str
    held: FrozenSet[str]      # lock names held lexically (post-propagation)
    nested: bool              # inside a nested def (deferred execution)
    scope: str


@dataclasses.dataclass
class TrackedCall:
    """Every call in a lock-declaring class, with the lock set held around
    it (post-propagation) — CONC003 filters for blocking calls whose held
    set is non-empty."""

    func_repr: str            # dotted best-effort, e.g. "time.sleep" or ".accept"
    line: int
    method: str
    held: FrozenSet[str]
    scope: str


@dataclasses.dataclass
class ClassLockModel:
    mod: ModuleInfo
    qualname: str             # "" for the module-level pseudo-class
    locks: Dict[str, LockAttr]
    writes: List[AttrWrite]
    calls: List[TrackedCall]
    #: (outer_lock, inner_lock, line, method) — lock names are local here;
    #: the graph qualifies them with module + class. Includes one-hop
    #: call-mediated edges: a self-method invoked while holding A
    #: contributes A -> each lock it acquires.
    acquisition_edges: List[Tuple[str, str, int, str]]
    #: every lock acquisition per method: method -> [(lock, line)]
    method_acquisitions: Dict[str, List[Tuple[str, int]]] = \
        dataclasses.field(default_factory=dict)

    def lock_node(self, lock_name: str) -> str:
        """Graph-global node id for one of this model's locks."""
        owner = self.qualname or "<module>"
        return f"{self.mod.rel_to_project}:{owner}.{lock_name}"


def _receiver_names(func: Optional[ast.AST]) -> Set[str]:
    names = {"self"}
    if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = func.args.posonlyargs + func.args.args
        if args and not any(
                isinstance(d, ast.Name) and d.id == "staticmethod"
                for d in func.decorator_list):
            names.add(args[0].arg)
    return names


def _lock_factory_kind(value: ast.AST) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when `value` is a call to a threading
    lock factory (``threading.Lock()`` or a bare imported ``Lock()``)."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        return LOCK_FACTORIES.get(fn.attr)
    if isinstance(fn, ast.Name):
        return LOCK_FACTORIES.get(fn.id)
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted repr of a call target: ``time.sleep``,
    ``.accept`` (unknown receiver), ``sleep`` (bare name)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base and "." not in base else f".{node.attr}"
    return ""


class _MethodWalker:
    """Walks one method (or module-level function) body, tracking the
    lexically-held lock set."""

    def __init__(self, model: ClassLockModel, method_name: str, scope: str,
                 receivers: Set[str], global_names: Set[str],
                 module_names: Set[str] = frozenset()):
        self.model = model
        self.method = method_name
        self.scope = scope
        self.receivers = receivers
        self.global_names = global_names
        # module-level assigned names: in-place mutation (`_CACHE[k] = v`,
        # `_CACHE.pop(k)`) hits the module object WITHOUT a `global`
        # declaration, so these count as writes for mutations only —
        # direct `name = ...` without `global` rebinds a local instead
        self.module_names = module_names
        #: (method, held) for every self.<meth>() call — propagation input
        self.self_calls: List[Tuple[str, FrozenSet[str], int]] = []

    # -- helpers -----------------------------------------------------------
    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """Lock name when `expr` is `self.<lockattr>` or a module lock."""
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id in self.receivers and not self.model.qualname == "":
            if expr.attr in self.model.locks:
                return expr.attr
        if isinstance(expr, ast.Name) and self.model.qualname == "" \
                and expr.id in self.model.locks:
            return expr.id
        return None

    def _self_attr(self, expr: ast.AST,
                   mutation: bool = False) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id in self.receivers and self.model.qualname != "":
            return expr.attr
        if isinstance(expr, ast.Name) and self.model.qualname == "" \
                and (expr.id in self.global_names
                     or (mutation and expr.id in self.module_names)):
            return expr.id
        return None

    def _record_write(self, attr: str, line: int, held: FrozenSet[str],
                      nested: bool) -> None:
        self.model.writes.append(AttrWrite(
            attr=attr, line=line, method=self.method, held=held,
            nested=nested, scope=self.scope))

    def _write_targets(self, target: ast.AST, line: int,
                       held: FrozenSet[str], nested: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._write_targets(elt, line, held, nested)
            return
        if isinstance(target, ast.Starred):
            self._write_targets(target.value, line, held, nested)
            return
        attr = self._self_attr(target)
        if attr is not None:
            self._record_write(attr, line, held, nested)
            return
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            # self._d[k] = v / self._obj.field = v: mutation of self._d/_obj
            inner = self._self_attr(target.value, mutation=True)
            if inner is not None:
                self._record_write(inner, line, held, nested)

    # -- the walk ----------------------------------------------------------
    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._visit(stmt, frozenset(), nested=False)

    def _visit(self, node: ast.AST, held: FrozenSet[str], nested: bool) -> None:
        if isinstance(node, ast.ClassDef):
            return                      # different `self`; out of scope
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # deferred execution: the held set at def time means nothing
            for stmt in node.body:
                self._visit(stmt, frozenset(), nested=True)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, frozenset(), nested=True)
            return

        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                name = self._lock_name(item.context_expr)
                if name is not None:
                    self.model.method_acquisitions.setdefault(
                        self.method, []).append((name, node.lineno))
                    for outer in held:
                        self.model.acquisition_edges.append(
                            (outer, name, node.lineno, self.method))
                    for prev in acquired:   # `with a, b:` orders a before b
                        self.model.acquisition_edges.append(
                            (prev, name, node.lineno, self.method))
                    acquired.append(name)
                else:
                    self._visit(item.context_expr, held, nested)
                if item.optional_vars is not None:
                    self._write_targets(item.optional_vars, node.lineno,
                                        held, nested)
            inner_held = held | frozenset(acquired)
            for stmt in node.body:
                self._visit(stmt, inner_held, nested)
            return

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                self._write_targets(t, node.lineno, held, nested)
            value = getattr(node, "value", None)
            if value is not None:
                self._visit(value, held, nested)
            return

        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._write_targets(t, node.lineno, held, nested)
            return

        if isinstance(node, ast.Call):
            fn = node.func
            # mutating container call: self._ring.append(x)
            if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
                owner = self._self_attr(fn.value, mutation=True)
                if owner is not None:
                    self._record_write(owner, node.lineno, held, nested)
            # self-method call (propagation input)
            if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
                    and fn.value.id in self.receivers:
                self.self_calls.append((fn.attr, held, node.lineno))
            self.model.calls.append(TrackedCall(
                func_repr=_dotted(fn), line=node.lineno,
                method=self.method, held=held, scope=self.scope))
            for child in ast.iter_child_nodes(node):
                self._visit(child, held, nested)
            return

        for child in ast.iter_child_nodes(node):
            self._visit(child, held, nested)


def _class_lock_attrs(cls: ast.ClassDef) -> Dict[str, LockAttr]:
    locks: Dict[str, LockAttr] = {}
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        receivers = _receiver_names(meth)
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign):
                kind = _lock_factory_kind(node.value)
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in receivers:
                        locks.setdefault(t.attr, LockAttr(t.attr, kind,
                                                          node.lineno))
    return locks


def _module_lock_attrs(tree: ast.Module) -> Dict[str, LockAttr]:
    locks: Dict[str, LockAttr] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            kind = _lock_factory_kind(node.value)
            if kind is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    locks.setdefault(t.id, LockAttr(t.id, kind, node.lineno))
    return locks


def _propagate_helper_locks(model: ClassLockModel,
                            call_ctx: Dict[str, List[FrozenSet[str]]]) -> None:
    """One-hop: a method invoked ONLY while holding a common lock set is
    treated as running under that set (the `_locked`-helper pattern)."""
    for method, contexts in call_ctx.items():
        if not contexts or any(not c for c in contexts):
            continue                     # some caller holds nothing: no help
        common = frozenset.intersection(*contexts)
        if not common:
            continue
        for w in model.writes:
            if w.method == method:
                w.held = w.held | common
        for c in model.calls:
            if c.method == method:
                c.held = c.held | common


_MODEL_CACHE: "weakref.WeakKeyDictionary[ModuleIndex, List[ClassLockModel]]" \
    = weakref.WeakKeyDictionary()


def build_lock_models(index: ModuleIndex) -> List[ClassLockModel]:
    """One model per class declaring at least one lock (plus one per
    module with module-level locks); cached per index — CONC001/002/003
    all consume the same models, and the models are read-only after
    construction."""
    cached = _MODEL_CACHE.get(index)
    if cached is None:
        cached = list(_build_lock_models(index))
        _MODEL_CACHE[index] = cached
    return cached


def _build_lock_models(index: ModuleIndex) -> Iterator[ClassLockModel]:
    for mod in index.modules:
        # module-level pseudo-class
        mod_locks = _module_lock_attrs(mod.tree)
        if mod_locks:
            model = ClassLockModel(mod=mod, qualname="", locks=mod_locks,
                                   writes=[], calls=[], acquisition_edges=[])
            module_names: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    module_names.update(t.id for t in node.targets
                                        if isinstance(t, ast.Name))
                elif isinstance(node, ast.AnnAssign) and \
                        isinstance(node.target, ast.Name):
                    module_names.add(node.target.id)
            module_names -= set(mod_locks)
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    globals_declared = {
                        name for sub in ast.walk(node)
                        if isinstance(sub, ast.Global) for name in sub.names}
                    walker = _MethodWalker(model, node.name, node.name,
                                           set(), globals_declared,
                                           module_names)
                    walker.walk(node.body)
            yield model

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            locks = _class_lock_attrs(node)
            if not locks:
                continue
            model = ClassLockModel(mod=mod, qualname=node.name, locks=locks,
                                   writes=[], calls=[], acquisition_edges=[])
            call_ctx: Dict[str, List[FrozenSet[str]]] = {}
            lock_held_calls: List[Tuple[str, FrozenSet[str], int, str]] = []
            method_names = {m.name for m in node.body
                            if isinstance(m, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))}
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                walker = _MethodWalker(
                    model, meth.name, f"{node.name}.{meth.name}",
                    _receiver_names(meth), set())
                walker.walk(meth.body)
                for callee, held, line in walker.self_calls:
                    if callee in method_names:
                        call_ctx.setdefault(callee, []).append(held)
                        if held:
                            lock_held_calls.append(
                                (callee, held, line, meth.name))
            _propagate_helper_locks(model, call_ctx)
            # one-hop call-mediated lock-order edges (ANY-site semantics —
            # a single call path that can deadlock is enough, unlike the
            # guarded-by propagation above which needs ALL sites locked):
            # calling a method that acquires B while holding A orders A->B
            for callee, held, line, caller in lock_held_calls:
                for inner, _ in model.method_acquisitions.get(callee, ()):
                    for outer in held:
                        model.acquisition_edges.append(
                            (outer, inner, line, caller))
            yield model
