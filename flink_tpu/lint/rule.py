"""Rule base class, violation record, and the rule registry.

A rule is a small class over the shared :class:`ModuleIndex`; its
``check`` yields :class:`Violation` records carrying ``file:line``, the
rule id, and a fix hint. The registry is the single source of truth for
what CI enforces: ``tests/test_architecture.py`` generates one test per
registered rule, the CLI lists/filters by rule id, and SARIF output
publishes each rule's rationale as its help text.

Fingerprints (rule id + project-relative path + enclosing scope + a
rule-chosen stable symbol) intentionally exclude line numbers, so a
baseline entry keeps matching its violation across unrelated edits to the
same file.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Dict, Iterator, List, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - typing only
    from flink_tpu.lint.index import ModuleIndex


@dataclasses.dataclass(frozen=True)
class Violation:
    """One finding: where, what, and how to fix it."""

    rule_id: str
    path: str                 # project-relative, e.g. "flink_tpu/runtime/rpc.py"
    line: int
    message: str
    scope: str = ""           # dotted enclosing qualname (Class.method)
    symbol: str = ""          # rule-chosen stable id within the scope
    hint: str = ""

    @property
    def fingerprint(self) -> str:
        """Line-independent identity used for baseline matching."""
        return f"{self.rule_id}::{self.path}::{self.scope}::{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: [{self.rule_id}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class Rule:
    """Base class. Subclasses set the class attributes and implement
    :meth:`check`; decorating with :func:`register` adds an instance to
    the registry."""

    id: str = ""
    name: str = ""            # short kebab-case slug
    family: str = ""          # "concurrency" | "device" | "wire" | "architecture"
    rationale: str = ""       # why the invariant matters (docs + SARIF help)
    hint: str = ""            # default fix hint attached to violations

    def check(self, index: "ModuleIndex") -> Iterator[Violation]:
        raise NotImplementedError

    def violation(self, mod, line: int, message: str, *, scope: str = "",
                  symbol: str = "", hint: str = "") -> Violation:
        return Violation(rule_id=self.id, path=mod.rel_to_project, line=line,
                         message=message, scope=scope, symbol=symbol,
                         hint=hint or self.hint)


_REGISTRY: Dict[str, Rule] = {}

# Modules that register rules on import; extended here when a new family
# module is added.
_RULE_MODULES = (
    "flink_tpu.lint.rules_concurrency",
    "flink_tpu.lint.rules_device",
    "flink_tpu.lint.rules_wire",
    "flink_tpu.lint.rules_architecture",
    "flink_tpu.lint.rules_exactly_once",
)


def register(cls):
    """Class decorator: instantiate and add to the registry (id must be
    unique — a duplicate id means two rules would fight over one baseline
    namespace, so it fails loudly)."""
    rule = cls()
    if not rule.id or not rule.name:
        raise ValueError(f"rule {cls.__name__} must set `id` and `name`")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return cls


def all_rules() -> List[Rule]:
    """Every registered rule, sorted by id (imports the rule modules on
    first use so the registry is complete without import-order games)."""
    for mod in _RULE_MODULES:
        importlib.import_module(mod)
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_rule(rule_id: str) -> Rule:
    for r in all_rules():
        if r.id == rule_id or r.name == rule_id:
            return r
    raise KeyError(f"no lint rule {rule_id!r} (known: "
                   f"{', '.join(sorted(_REGISTRY))})")
