"""Architecture & completeness rule family (ARCH/DOC).

The original tests/test_architecture.py checks, re-homed as registry
rules (the reference keeps the same rules in flink-architecture-tests as
ArchUnit layer definitions with frozen stores):

- ARCH001 layer-dag — foundation layers must not import upward at module
  level (lazy, function-scoped imports are the sanctioned escape hatch).
- ARCH002 checkpoint-below-runtime — flink_tpu/checkpoint must not import
  flink_tpu.runtime anywhere, lazy imports included.
- DOC001 config-docs-complete — every declared ConfigOption key must
  appear in docs/configuration.md.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from flink_tpu.lint.index import ModuleIndex
from flink_tpu.lint.rule import Rule, Violation, register  # noqa: F401 — Violation used in annotations

#: layer dir -> package-relative module prefixes it must NOT import at
#: module level ("{pkg}" is substituted with the indexed package name)
LAYER_FORBIDDEN: Dict[str, List[str]] = {
    "core": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
             "{pkg}.ops", "{pkg}.state", "{pkg}.scheduler"],
    "utils": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
              "{pkg}.scheduler"],
    "ops": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
            "{pkg}.scheduler"],
    # the state plane (columnar/heap backends, vocab, tier manager,
    # changelog) is composed BY the runtime: operators hand device
    # accessors in as callables; a runtime import here would invert that
    # and drag the executor into every state-backend import
    "state": ["{pkg}.api", "{pkg}.table", "{pkg}.cep", "{pkg}.scheduler",
              "{pkg}.runtime"],
    # the mesh/shard-map library sits below the runtime like ops/state: it
    # may import core/ops/state/config, never the runtime (the sharded
    # pipeline's planner handle is a function-scoped lazy import), api, or
    # the table/cep layers above — the runtime composes parallel, not the
    # other way around
    "parallel": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
                 "{pkg}.scheduler"],
    # the join subsystem (geometry/catalog, bucket rings, the fused match
    # pipeline) sits beside parallel: it may import core/ops/state/config
    # (and parallel, for the sharded pipeline's mesh handles) — never the
    # runtime (DeviceJoinRunner composes the pipeline, not the reverse),
    # api, table, cep, or the scheduler
    "joins": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
              "{pkg}.scheduler"],
    # job translation: step planning, the fusion planner (fusion.py) and
    # the Factor-Windows sharing optimizer (window_sharing.py) — all emit
    # pure plan data the executor consumes; a runtime import would invert
    # the translation DAG
    "graph": ["{pkg}.table", "{pkg}.cep", "{pkg}.runtime"],
    # the SQL planner translates table plans into graph transformations:
    # it may import table (parsed Query shapes), graph, core, and config —
    # never the runtime (it emits plans, the executor runs them), the api
    # (assigner construction is a function-scoped lazy import), the
    # scheduler, or cep
    "planner": ["{pkg}.runtime", "{pkg}.api", "{pkg}.scheduler",
                "{pkg}.cep"],
    "api": ["{pkg}.table", "{pkg}.runtime"],
    # the autoscaler consumes metric-snapshot/state/config shapes and is
    # driven by the runtime through injected callables — it may import
    # metrics/state/config, never the runtime (or anything above it); and
    # the layers it consumes must not import it back
    "metrics": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep",
                "{pkg}.scheduler"],
    "scheduler": ["{pkg}.runtime", "{pkg}.api", "{pkg}.table", "{pkg}.cep"],
}


@register
class LayerDagRule(Rule):
    id = "ARCH001"
    name = "layer-dag"
    family = "architecture"
    rationale = (
        "The layer DAG — core/utils at the bottom, ops above them, "
        "state/graph next, api on top, runtime/table/cep reachable only "
        "lazily — keeps `import flink_tpu.api` from dragging in the whole "
        "runtime (and a TPU backend) at import time. Function-scoped "
        "imports are the sanctioned escape hatch, playing the role of "
        "ArchUnit's frozen store but enforced structurally: execution "
        "entry points import the executor when called."
    )
    hint = ("import lazily inside the function that needs it, or move the "
            "code to the layer it actually belongs to")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for layer, banned_tpl in LAYER_FORBIDDEN.items():
            banned = [b.format(pkg=index.package) for b in banned_tpl]
            for mod in index.in_subtree(layer):
                for imp, line in index.module_level_imports(mod):
                    for b in banned:
                        if imp == b or imp.startswith(b + "."):
                            yield self.violation(
                                mod, line,
                                (f"layer {layer!r} imports {imp} at module "
                                 f"level (must not depend on {b})"),
                                symbol=f"{layer}->{imp}")


@register
class CheckpointBelowRuntimeRule(Rule):
    id = "ARCH002"
    name = "checkpoint-below-runtime"
    family = "architecture"
    rationale = (
        "flink_tpu/checkpoint must not import flink_tpu.runtime — "
        "anywhere, lazy imports included. Checkpoint/failure/recovery "
        "statistics flow OUTWARD: the coordinator reports into trackers "
        "the runtime hands it (metrics/checkpoint_stats.py stats + "
        "state_bytes_fn callbacks); it never reaches into the scheduler "
        "or executor. A runtime import here inverts the dependency and "
        "lets coordinator changes drag in the whole cluster stack (and, "
        "on TPU hosts, risk backend init from a checkpoint utility)."
    )
    hint = "pass data outward via callbacks/trackers instead"

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        banned = f"{index.package}.runtime"
        for mod in index.in_subtree("checkpoint"):
            seen: Dict[str, int] = {}
            for imp, line in index.all_imports(mod):
                if imp == banned or imp.startswith(banned + "."):
                    base = f"import:{imp}"
                    n = seen[base] = seen.get(base, 0) + 1
                    yield self.violation(
                        mod, line,
                        (f"checkpoint layer imports {imp} (must stay below "
                         f"the runtime, lazy imports included)"),
                        symbol=base if n == 1 else f"{base}#{n}")


def _declared_config_keys(index: ModuleIndex) -> List[Tuple[str, int, str]]:
    """(key, line, holder_scope) for every ConfigOptions.key("...") call in
    the package's config.py — the AST-level equivalent of
    docs.generate.collect_options, so the rule also runs on fixture
    packages that are never importable."""
    mod = index.get("config.py")
    if mod is None:
        return []
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "key" and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "ConfigOptions" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno, "config.py"))
    return out


@register
class ConfigDocsCompleteRule(Rule):
    id = "DOC001"
    name = "config-docs-complete"
    family = "architecture"
    rationale = (
        "Every ConfigOption declared in config.py must appear in "
        "docs/configuration.md (regenerate with `python -m "
        "flink_tpu.docs.generate`). The reference gates its docs the same "
        "way (ConfigOptionsDocsCompletenessITCase): an undocumented "
        "option fails CI before it ships, so the generated reference can "
        "be trusted to be the full surface."
    )
    hint = "run `python -m flink_tpu.docs.generate` and commit the result"

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        keys = _declared_config_keys(index)
        if not keys:
            return
        doc_path = index.project_root / "docs" / "configuration.md"
        doc = doc_path.read_text() if doc_path.exists() else ""
        mod = index.get("config.py")
        for key, line, _holder in keys:
            if f"`{key}`" not in doc:
                yield self.violation(
                    mod, line,
                    f"config option `{key}` missing from "
                    f"docs/configuration.md",
                    symbol=f"option:{key}")
