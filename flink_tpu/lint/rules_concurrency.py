"""Concurrency rule family (CONC): lock discipline over the threaded runtime.

The runtime spawns 18+ threads across cluster/dataplane/rpc/heartbeat/
metrics; "Towards Concurrent Stateful Stream Processing on Multicore
Processors" (PAPERS.md) identifies shared-state races and lock-ordering
bugs as the dominant failure mode of multicore streaming engines. These
rules turn the informally-held invariants into CI:

- CONC001 inconsistent-guard — a field written both under its lock and
  bare is a race by construction.
- CONC002 lock-order-cycle — a cycle in the static acquisition graph is a
  deadlock waiting for the right interleaving.
- CONC003 blocking-under-lock — sleeping/accepting under a lock turns
  every contender into a convoy.
- CONC004 thread-hygiene — unnamed/non-daemon threads are invisible in
  the dashboard's thread attribution and can wedge interpreter shutdown.
- CONC005 no-silent-swallow — `except Exception: pass` in the runtime/
  checkpoint subtrees hides the exact transient faults the chaos plane
  exists to surface.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from flink_tpu.lint.index import ModuleIndex, enclosing_scope, parent_map
from flink_tpu.lint.locks import CONSTRUCTION_METHODS, build_lock_models
from flink_tpu.lint.rule import Rule, Violation, register


@register
class InconsistentGuardRule(Rule):
    id = "CONC001"
    name = "inconsistent-guard"
    family = "concurrency"
    rationale = (
        "For each class owning a threading.Lock/RLock/Condition, the lock "
        "model infers which lock guards each mutable `self._*` attribute "
        "from the `with self._lock:` regions that write it. An attribute "
        "written both inside such a region and outside any (excluding "
        "__init__, whose writes happen before publication) has no "
        "consistent guard: one of the two writers races the other."
    )
    hint = ("move the bare write under the guarding lock, or extract a "
            "`_locked` helper called only while holding it (the model "
            "propagates caller-held locks one call hop)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for model in build_lock_models(index):
            per_attr: Dict[str, Dict[str, List]] = {}
            for w in model.writes:
                if w.attr in model.locks:
                    continue              # the lock attrs themselves
                slot = per_attr.setdefault(w.attr, {"locked": [], "bare": []})
                if w.held:
                    slot["locked"].append(w)
                elif not w.nested and w.method not in CONSTRUCTION_METHODS:
                    slot["bare"].append(w)
            for attr, slot in sorted(per_attr.items()):
                if not slot["locked"] or not slot["bare"]:
                    continue
                guards = sorted({lk for w in slot["locked"] for lk in w.held})
                first_bare = min(slot["bare"], key=lambda w: w.line)
                locked_lines = sorted({w.line for w in slot["locked"]})
                owner = model.qualname or "<module>"
                yield Violation(
                    rule_id=self.id, path=model.mod.rel_to_project,
                    line=first_bare.line,
                    message=(
                        f"{owner}.{attr} is written under "
                        f"{'/'.join(guards)} (line"
                        f"{'s' if len(locked_lines) > 1 else ''} "
                        f"{', '.join(map(str, locked_lines))}) but bare in "
                        f"{first_bare.method}() — inconsistent guard"
                    ),
                    scope=f"{owner}", symbol=attr, hint=self.hint)


@register
class LockOrderCycleRule(Rule):
    id = "CONC002"
    name = "lock-order-cycle"
    family = "concurrency"
    rationale = (
        "Nested `with` acquisitions define a static lock-order graph "
        "across all runtime modules (one self-call hop deep: a method "
        "called while holding A contributes A -> each lock it acquires). "
        "A cycle means two threads can interleave into a deadlock; a "
        "self-edge on a non-reentrant Lock/Condition deadlocks a single "
        "thread on its own."
    )
    hint = ("acquire the locks in one global order everywhere, or collapse "
            "the two locks into one; for an intentional re-entry use an "
            "RLock")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        graph: Dict[str, Set[str]] = {}
        edge_info: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self_edge_kind: Dict[str, str] = {}
        for model in build_lock_models(index):
            for outer, inner, line, method in model.acquisition_edges:
                a, b = model.lock_node(outer), model.lock_node(inner)
                if a == b:
                    kind = model.locks[outer].kind
                    if kind == "RLock":
                        continue          # reentrant: legal by design
                    scope = f"{model.qualname or '<module>'}.{method}"
                    yield Violation(
                        rule_id=self.id, path=model.mod.rel_to_project,
                        line=line,
                        message=(f"{a.split(':', 1)[1]} ({kind}) is "
                                 f"re-acquired while already held in "
                                 f"{method}() — single-thread deadlock"),
                        scope=scope, symbol=f"{outer}->{outer}",
                        hint=self.hint)
                    continue
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
                edge_info.setdefault(
                    (a, b),
                    (model.mod.rel_to_project, line,
                     f"{model.qualname or '<module>'}.{method}"))
        for cycle in _find_cycles(graph):
            a, b = cycle[0], cycle[1]
            path, line, scope = edge_info.get((a, b), ("", 0, ""))
            pretty = " -> ".join(n.split(":", 1)[1] for n in [*cycle, cycle[0]])
            yield Violation(
                rule_id=self.id, path=path or cycle[0].split(":", 1)[0],
                line=line,
                message=f"lock-order cycle: {pretty}",
                scope=scope, symbol="|".join(sorted(cycle)), hint=self.hint)


def _find_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Simple cycles via DFS; each cycle reported once, rotated to start at
    its smallest node so the violation fingerprint is stable."""
    cycles: List[List[str]] = []
    seen_keys: Set[str] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> None:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GRAY:
                i = stack.index(m)
                cyc = stack[i:]
                k = min(range(len(cyc)), key=lambda j: cyc[j])
                rot = cyc[k:] + cyc[:k]
                key = "|".join(rot)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(rot)
            elif color.get(m, WHITE) == WHITE:
                dfs(m)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color[n] == WHITE:
            dfs(n)
    return cycles


#: dotted call targets that block the calling thread
BLOCKING_CALLS = {
    "time.sleep": "time.sleep()",
    "socket.create_connection": "socket.create_connection()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_call": "subprocess.check_call()",
    "subprocess.check_output": "subprocess.check_output()",
}
#: attribute calls on an unknown receiver that are blocking socket ops
BLOCKING_ATTRS = {".accept", ".connect", ".recv", ".recv_into", ".sendall",
                  ".sendmsg", ".makefile"}


@register
class BlockingUnderLockRule(Rule):
    id = "CONC003"
    name = "blocking-under-lock"
    family = "concurrency"
    rationale = (
        "time.sleep, blocking socket calls, and subprocess waits inside a "
        "`with lock:` region hold every contending thread hostage for the "
        "full wait — on the control plane that turns one slow peer into a "
        "cluster-wide convoy (and, combined with CONC002 edges, into "
        "distributed deadlock)."
    )
    hint = ("move the wait outside the region (copy state under the lock, "
            "block after releasing it), or use Condition.wait with a "
            "timeout so the lock is released while waiting")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for model in build_lock_models(index):
            seen_in_scope: Dict[Tuple[str, str], int] = {}
            for call in model.calls:
                if not call.held:
                    continue
                label = None
                if call.func_repr in BLOCKING_CALLS:
                    label = BLOCKING_CALLS[call.func_repr]
                elif "." in call.func_repr:
                    # match on the method name whatever the receiver
                    # spelling: `.accept` (unknown chain), `sock.accept`
                    # (local variable), `self._sock.accept` (collapsed to
                    # `._sock.accept` by _dotted)
                    suffix = "." + call.func_repr.rsplit(".", 1)[1]
                    if suffix in BLOCKING_ATTRS:
                        label = f"{call.func_repr}()"
                if label is None:
                    continue
                held = "/".join(sorted(call.held))
                scope = call.scope or call.method
                # occurrence-indexed symbol: the 2nd/3rd/... blocking call
                # in one scope must NOT share the 1st one's fingerprint, or
                # a single baseline entry silently suppresses all of them
                # (the index stays line-independent: it only shifts when
                # sites are added/removed within the same scope)
                base = f"{call.func_repr}@{call.method}"
                n = seen_in_scope[(scope, base)] = \
                    seen_in_scope.get((scope, base), 0) + 1
                yield Violation(
                    rule_id=self.id, path=model.mod.rel_to_project,
                    line=call.line,
                    message=(f"{label} while holding {held} in "
                             f"{scope}() — blocks every "
                             f"contender for the full wait"),
                    scope=scope,
                    symbol=base if n == 1 else f"{base}#{n}",
                    hint=self.hint)


#: package-relative subtrees where a silent broad swallow is a violation:
#: the runtime's control/data planes and the checkpoint layer — exactly
#: where a swallowed transient fault becomes an undiagnosable hang or a
#: silently-lost checkpoint (chaos-plane hardening, ISSUE-10)
SWALLOW_SCOPED_SUBTREES = ("runtime", "checkpoint")
_BROAD_EXC_NAMES = {"Exception", "BaseException"}


def _is_broad_handler(type_node) -> bool:
    """Bare `except:`, `except Exception/BaseException:`, or a tuple
    containing one of those. Narrow handlers (OSError, KeyError, ...) are
    deliberate per-fault decisions and stay legal."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD_EXC_NAMES
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad_handler(e) for e in type_node.elts)
    return False


@register
class NoSilentSwallowRule(Rule):
    id = "CONC005"
    name = "no-silent-swallow"
    family = "concurrency"
    rationale = (
        "An `except Exception: pass` (or bare `except: pass`) on the "
        "runtime/checkpoint planes erases the one signal that "
        "distinguishes a transient fault from a real failure: the "
        "heartbeat manager silently eating ping errors is exactly how a "
        "partitioned TM stays 'alive' until the timeout, and a swallowed "
        "checkpoint error is a lost recovery point nobody hears about. "
        "Best-effort calls may survive peer failures, but they must LOG "
        "or COUNT what they swallowed (missedPings, _swallow(site, e)) — "
        "or carry a written justification in lint_baseline.json. Narrow "
        "except types (OSError on a socket close) remain legal: they are "
        "per-fault decisions, not blanket blindness."
    )
    hint = ("log/count the swallowed exception (see cluster._swallow, "
            "heartbeat.missed_pings), narrow the except type, or justify "
            "the entry in lint_baseline.json")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for layer in SWALLOW_SCOPED_SUBTREES:
            for mod in index.in_subtree(layer):
                parents = None
                seen_in_scope: Dict[str, int] = {}
                for node in ast.walk(mod.tree):
                    if not isinstance(node, ast.ExceptHandler):
                        continue
                    if not _is_broad_handler(node.type):
                        continue
                    if not all(isinstance(s, ast.Pass) for s in node.body):
                        continue
                    if parents is None:
                        parents = parent_map(mod.tree)
                    scope = enclosing_scope(parents, node)
                    # occurrence-indexed symbol (see CONC003): one baseline
                    # entry must not cover every swallow in the scope
                    n = seen_in_scope[scope] = seen_in_scope.get(scope, 0) + 1
                    caught = ("bare except" if node.type is None
                              else f"except {ast.unparse(node.type)}")
                    yield Violation(
                        rule_id=self.id, path=mod.rel_to_project,
                        line=node.lineno,
                        message=(f"{caught}: pass in "
                                 f"{scope or '<module>'} silently swallows "
                                 "every failure, transient or fatal"),
                        scope=scope,
                        symbol=(f"swallow@{scope}" if n == 1
                                else f"swallow@{scope}#{n}"),
                        hint=self.hint)


@register
class ThreadHygieneRule(Rule):
    id = "CONC004"
    name = "thread-hygiene"
    family = "concurrency"
    rationale = (
        "Every threading.Thread(...) must pass BOTH daemon= and name=: an "
        "unnamed thread is invisible in the dashboard's thread attribution "
        "and the flamegraph's per-thread folding, and an accidental "
        "non-daemon thread wedges interpreter shutdown (the reference "
        "names every executor thread for the same reason — "
        "ExecutorThreadFactory)."
    )
    hint = ("pass name=\"<subsystem>-<what>\" and an explicit daemon= to "
            "the Thread constructor")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for mod in index.modules:
            parents = None
            seen_in_scope: Dict[str, int] = {}
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                is_thread = (
                    isinstance(fn, ast.Attribute) and fn.attr == "Thread"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "threading"
                ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
                if not is_thread:
                    continue
                kwargs = {k.arg for k in node.keywords if k.arg}
                missing = sorted({"daemon", "name"} - kwargs)
                if not missing:
                    continue
                if parents is None:
                    parents = parent_map(mod.tree)
                scope = enclosing_scope(parents, node)
                # occurrence-indexed symbol (see CONC003): one baseline
                # entry must not cover every unnamed Thread in the scope
                n = seen_in_scope[scope] = seen_in_scope.get(scope, 0) + 1
                symbol = f"Thread@{scope}" if n == 1 else \
                    f"Thread@{scope}#{n}"
                yield Violation(
                    rule_id=self.id, path=mod.rel_to_project,
                    line=node.lineno,
                    message=(f"threading.Thread(...) missing "
                             f"{' and '.join(f'{m}=' for m in missing)} "
                             f"in {scope or '<module>'}"),
                    scope=scope, symbol=symbol, hint=self.hint)
