"""Device-discipline rule family (DEV): keep the TPU hot path hot.

- DEV001 host-sync-in-jit — host-synchronizing operations on traced
  values inside a jitted function either fail at trace time or (worse)
  silently force a device->host round trip per call.
- DEV002 jit-in-loop — `jax.jit(...)` invoked inside a loop body builds a
  fresh compiled callable per iteration: a recompilation (or at best
  cache-lookup) hazard on the hot path. Builders cache their jitted fn
  (lru_cache / instance dict) outside the loop.
- DEV003 jax-free-control-plane — the cluster control plane must not
  import jax at module level: an oracle-path worker must never claim a
  TPU chip just by starting up.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from flink_tpu.lint.index import ModuleIndex, ModuleInfo, enclosing_scope, parent_map
from flink_tpu.lint.rule import Rule, Violation, register

#: modules (package-relative) that form the cluster control plane
CONTROL_PLANE = (
    "runtime/cluster.py",
    "runtime/rpc.py",
    "runtime/blob.py",
    "runtime/heartbeat.py",
    "runtime/ha.py",
    "runtime/ha_kubernetes.py",
    "runtime/rest.py",
    "runtime/dataplane.py",
    "security/framing.py",
    "security/transport.py",
    # the history/doctor plane consumes plain-data snapshots and span
    # dicts handed to it — a jax import here would drag backend init
    # into every REST reader and JM schedule tick
    "metrics/history.py",
    "metrics/doctor.py",
)


def _numpy_aliases(mod: ModuleInfo) -> Set[str]:
    aliases = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


def _is_jax_jit(fn: ast.AST) -> bool:
    """True for `jax.jit` or bare `jit` expressions."""
    if isinstance(fn, ast.Attribute) and fn.attr == "jit" and \
            isinstance(fn.value, ast.Name) and fn.value.id == "jax":
        return True
    return isinstance(fn, ast.Name) and fn.id == "jit"


def _jit_decorated(func: ast.AST) -> bool:
    if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in func.decorator_list:
        if _is_jax_jit(dec):
            return True
        # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
        if isinstance(dec, ast.Call):
            if _is_jax_jit(dec.func):
                return True
            f = dec.func
            is_partial = (isinstance(f, ast.Name) and f.id == "partial") or (
                isinstance(f, ast.Attribute) and f.attr == "partial")
            if is_partial and dec.args and _is_jax_jit(dec.args[0]):
                return True
    return False


def _jitted_functions(mod: ModuleInfo,
                      parents: Dict[ast.AST, ast.AST]) -> List[ast.AST]:
    """FunctionDefs compiled by jax.jit: decorated ones, plus plain defs
    passed to a `jax.jit(name)` call in the same enclosing scope."""
    jitted: List[ast.AST] = []
    for node in ast.walk(mod.tree):
        if _jit_decorated(node):
            jitted.append(node)
        if isinstance(node, ast.Call) and _is_jax_jit(node.func) and \
                node.args and isinstance(node.args[0], ast.Name):
            target = _resolve_local_def(node, node.args[0].id, parents)
            if target is not None and target not in jitted:
                jitted.append(target)
        # jax.jit(lambda ...: ...)
        if isinstance(node, ast.Call) and _is_jax_jit(node.func) and \
                node.args and isinstance(node.args[0], ast.Lambda):
            jitted.append(node.args[0])
    return jitted


def _resolve_local_def(site: ast.AST, name: str,
                       parents: Dict[ast.AST, ast.AST]) -> Optional[ast.AST]:
    """Nearest enclosing scope's `def <name>` for a `jax.jit(name)` call."""
    cur: Optional[ast.AST] = site
    while cur is not None:
        cur = parents.get(cur)
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Module)):
            for stmt in ast.walk(cur):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and stmt.name == name and stmt is not site:
                    return stmt
    return None


#: attribute calls that synchronize device -> host
HOST_SYNC_ATTRS = {"item", "block_until_ready", "tolist"}


def _contains_static_marker(expr: ast.AST) -> bool:
    """float()/int() on shapes and sizes is static metadata, not a host
    sync — skip literal args and args mentioning .shape/.ndim/.size/len().
    A nested literal (an index like x[-1]) does NOT make the arg static."""
    if isinstance(expr, ast.Constant):
        return True
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim",
                                                             "size", "dtype"):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "len":
            return True
    return False


@register
class HostSyncInJitRule(Rule):
    id = "DEV001"
    name = "host-sync-in-jit"
    family = "device"
    rationale = (
        "Inside a function compiled with @jax.jit / jax.jit(fn), calling "
        ".item()/.tolist()/.block_until_ready(), np.asarray/np.array, "
        "jax.device_get, or float()/int()/bool() on a traced value either "
        "raises a ConcretizationTypeError at trace time or forces a "
        "device->host readback on every call — the exact sync the jitted "
        "hot path exists to avoid. Host conversions belong at the step "
        "boundary (the runner's readback section), never inside the "
        "compiled body."
    )
    hint = ("keep the jitted body pure jnp; do host conversion on the "
            "result at the step boundary (where DeviceTimer attributes it)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for mod in index.modules:
            parents = parent_map(mod.tree)
            np_names = _numpy_aliases(mod)
            # occurrence-indexed symbols: the 2nd .item() in one function
            # must not share the 1st one's fingerprint, or a single
            # baseline entry suppresses every current and future host sync
            # of that label in the scope
            seen: Dict[Tuple[str, str], int] = {}
            for func in _jitted_functions(mod, parents):
                fname = getattr(func, "name", "<lambda>")
                body = func.body if isinstance(func.body, list) else [func.body]
                for stmt in body:
                    yield from self._scan(stmt, mod, fname, np_names,
                                          parents, seen)

    def _scan(self, root: ast.AST, mod: ModuleInfo, fname: str,
              np_names: Set[str], parents: Dict[ast.AST, ast.AST],
              seen: Dict[Tuple[str, str], int]) -> Iterator[Violation]:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            label = None
            if isinstance(fn, ast.Attribute):
                if fn.attr in HOST_SYNC_ATTRS:
                    label = f".{fn.attr}()"
                elif fn.attr in ("asarray", "array") and \
                        isinstance(fn.value, ast.Name) and \
                        fn.value.id in (np_names or {"np"}) and \
                        fn.value.id != "jnp":
                    label = f"{fn.value.id}.{fn.attr}()"
                elif fn.attr == "device_get" and \
                        isinstance(fn.value, ast.Name) and fn.value.id == "jax":
                    label = "jax.device_get()"
            elif isinstance(fn, ast.Name) and fn.id in ("float", "int",
                                                        "bool"):
                if node.args and not _contains_static_marker(node.args[0]):
                    label = f"{fn.id}()"
            if label is None:
                continue
            scope = enclosing_scope(parents, node) or fname
            base = f"{label}@{fname}"
            n = seen[(scope, base)] = seen.get((scope, base), 0) + 1
            yield self.violation(
                mod, node.lineno,
                f"host-sync {label} inside jitted function {fname}()",
                scope=scope, symbol=base if n == 1 else f"{base}#{n}")


@register
class JitInLoopRule(Rule):
    id = "DEV002"
    name = "jit-in-loop"
    family = "device"
    rationale = (
        "jax.jit(...) invoked inside a for/while body constructs a new "
        "compiled callable every iteration — at best a cache lookup per "
        "record batch, at worst a recompilation storm when the closure "
        "captures loop state. Every builder in this codebase caches its "
        "jitted fn outside the loop (functools.lru_cache or an instance "
        "dict); new code must do the same."
    )
    hint = ("hoist the jax.jit call out of the loop (cache per geometry "
            "with functools.lru_cache or a dict keyed on static shapes)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for mod in index.modules:
            parents = None
            seen: Dict[str, int] = {}
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and _is_jax_jit(node.func)):
                    continue
                if parents is None:
                    parents = parent_map(mod.tree)
                loop = self._enclosing_loop(node, parents)
                if loop is None:
                    continue
                scope = enclosing_scope(parents, node)
                n = seen[scope] = seen.get(scope, 0) + 1
                yield self.violation(
                    mod, node.lineno,
                    (f"jax.jit(...) inside a "
                     f"{'for' if isinstance(loop, ast.For) else 'while'} "
                     f"loop body in {scope or '<module>'} — per-iteration "
                     f"(re)compilation hazard"),
                    scope=scope,
                    symbol=(f"jit-in-loop@{scope}" if n == 1 else
                            f"jit-in-loop@{scope}#{n}"))

    @staticmethod
    def _enclosing_loop(node: ast.AST,
                        parents: Dict[ast.AST, ast.AST]) -> Optional[ast.AST]:
        cur = parents.get(node)
        child = node
        while cur is not None:
            # stop at function boundaries: a def inside a loop runs later
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)) and \
                    child in getattr(cur, "body", []) + getattr(cur, "orelse", []):
                return cur
            child = cur
            cur = parents.get(cur)
        return None


@register
class JaxFreeControlPlaneRule(Rule):
    id = "DEV003"
    name = "jax-free-control-plane"
    family = "device"
    rationale = (
        "The cluster control plane (JM/TM endpoints, RPC, blob, "
        "heartbeats, HA, REST, dataplane, security) must not import jax "
        "at module level: backend init claims the TPU chip, so an "
        "oracle-path worker process would seize the accelerator just by "
        "starting up. Device-path code imports jax lazily inside the "
        "functions that actually run on device (_make_operator pattern)."
    )
    hint = ("move the jax import inside the function that needs it "
            "(device path only)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for rel in CONTROL_PLANE:
            mod = index.get(rel)
            if mod is None:
                continue
            for imp, line in index.module_level_imports(mod):
                if imp == "jax" or imp.startswith("jax."):
                    yield self.violation(
                        mod, line,
                        f"control-plane module imports {imp} at module "
                        f"level (TPU backend init claims the chip)",
                        scope="", symbol=f"import:{imp}")
