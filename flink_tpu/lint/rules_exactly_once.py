"""Exactly-once contract rule family (EXON): machine-check the invariants
that keep checkpoints exactly-once on the device path.

- EXON001 quiescence-before-capture — a snapshot that does not dominate a
  drain of every in-flight structure its class owns silently loses
  whatever was still in flight: the checkpoint claims a consistent cut it
  does not contain (arXiv 1904.03800's capture-overlap model).  Classes
  declare their rings with ``@inflight_ring`` (:mod:`lint.contracts`);
  the rule verifies every capture method reaches a drain through the
  call chain, and that no *undeclared* ``_inflight``/``_pending``
  container hides on a class that captures.
- EXON002 executable-cache-key-completeness — a memoized jit executable
  whose cache key omits a parameter that changes the compiled bytes (or
  the calling convention: donation!) serves a stale executable when that
  parameter flips.  PR 17 fixed exactly this by hand for
  ``donate_carry``; this rule finds the class.
- EXON003 fault-transparency — an ``except`` wide enough to catch
  ``InjectedCrash`` that neither re-raises it nor carries an attributed
  allowlist reason silently eats chaos coverage: every fault the suite
  injects through that seam looks survived when it was swallowed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from flink_tpu.lint import dataflow
from flink_tpu.lint.contracts import absorbs_reason as _contracts_absorbs
from flink_tpu.lint.index import ModuleIndex, ModuleInfo, enclosing_scope, \
    parent_map
from flink_tpu.lint.rule import Rule, Violation, register

#: package-relative subtrees whose classes are on the capture path
CAPTURE_SUBTREES = ("runtime", "parallel", "joins")

#: method names that capture checkpoint state
CAPTURE_METHODS = ("snapshot", "capture", "checkpoint")

#: instance attributes that look like in-flight dispatch state; a class
#: with a capture method must declare these via @inflight_ring (held
#: record buffers that RIDE the snapshot should not use these names)
INFLIGHT_NAME_RE = re.compile(r"^_(inflight|pending)(_|$|[a-z0-9])")

#: exception types wide enough to catch InjectedCrash
#: (InjectedCrash < InjectedFault < ConnectionError < OSError < Exception)
WIDE_TYPES = frozenset({
    "BaseException", "Exception", "ConnectionError", "OSError",
    "IOError", "EnvironmentError", "InjectedFault",
})

_INJECTED = ("InjectedCrash", "InjectedFault")


@register
class QuiescenceBeforeCaptureRule(Rule):
    id = "EXON001"
    name = "quiescence-before-capture"
    family = "exactly_once"
    rationale = (
        "Anything still in flight at a capture point is part of the state "
        "the checkpoint claims to contain: a snapshot() that does not "
        "dominate a drain of every @inflight_ring its class declares "
        "produces a cut that silently drops un-resolved device "
        "dispatches, so replay after restore loses records — the "
        "exactly-once hole PRs 14-18 kept re-finding by hand. Drains are "
        "verified through the call chain (snapshot -> flush_all -> "
        "_resolve_inflight) on the unconditional statement spine; a "
        "guard that tests only the ring itself (`if self._pending: "
        "self._resolve_pending()`) counts, any other condition does not."
    )
    hint = ("call the declared drain (or a @drains helper) "
            "unconditionally before capturing; declare new dispatch "
            "buffers with @inflight_ring so the analyzer sees them")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        dfi = dataflow.DataflowIndex.shared(index)
        for subtree in CAPTURE_SUBTREES:
            for mod in index.in_subtree(subtree):
                msum = dfi.module(mod)
                for cls in msum.classes.values():
                    yield from self._check_class(dfi, mod, cls)

    def _check_class(self, dfi: dataflow.DataflowIndex, mod: ModuleInfo,
                     cls: dataflow.ClassSummary) -> Iterator[Violation]:
        captures = [m for m in CAPTURE_METHODS if m in cls.methods]
        for decl in cls.rings:
            drainers = cls.drain_map.get(decl.attr, [])
            if decl.drained_by not in cls.methods and not cls.has_bases:
                yield self.violation(
                    mod, decl.line,
                    f"{cls.name} declares @inflight_ring({decl.attr!r}) "
                    f"drained by {decl.drained_by!r}, but no such method "
                    f"exists on the class",
                    scope=cls.name, symbol=f"missing-drain:{decl.attr}")
                continue
            touched = any(decl.attr in fs.attrs_written or
                          decl.attr in fs.attrs_read
                          for fs in cls.methods.values())
            if not touched and not cls.has_bases:
                yield self.violation(
                    mod, decl.line,
                    f"{cls.name} declares @inflight_ring({decl.attr!r}) "
                    f"but no method reads or writes self.{decl.attr} — "
                    f"stale declaration",
                    scope=cls.name, symbol=f"stale-ring:{decl.attr}")
                continue
            for capture in captures:
                if not dfi.drains_attr(cls, capture, decl.attr):
                    fs = cls.methods[capture]
                    yield self.violation(
                        mod, fs.line,
                        f"{cls.name}.{capture}() does not dominate a "
                        f"drain of in-flight ring self.{decl.attr} "
                        f"(declared drained by {decl.drained_by}()) — "
                        f"records in flight at capture are lost from the "
                        f"checkpoint",
                        scope=f"{cls.name}.{capture}",
                        symbol=f"undrained:{decl.attr}")
        if captures:
            declared = set(cls.drain_map)
            for attr, line in sorted(cls.init_container_attrs.items()):
                if attr in declared or not INFLIGHT_NAME_RE.match(attr):
                    continue
                yield self.violation(
                    mod, line,
                    f"{cls.name} captures checkpoint state "
                    f"({'/'.join(captures)}) but owns an undeclared "
                    f"in-flight container self.{attr} — declare it with "
                    f"@inflight_ring(..., drained_by=...) or rename it if "
                    f"it legitimately rides the snapshot",
                    scope=cls.name, symbol=f"undeclared:{attr}")


@register
class CacheKeyCompletenessRule(Rule):
    id = "EXON002"
    name = "executable-cache-key-completeness"
    family = "exactly_once"
    rationale = (
        "A memoized jit executable is only as correct as its cache key: "
        "any value that flows into jax.jit/pjit options (donate_argnums, "
        "static shapes, backend, shardings) changes the compiled bytes "
        "or the calling convention, so a key that omits it serves a "
        "stale executable when the value flips — with donation that "
        "means operating on freed buffers. PR 17 fixed this by hand for "
        "donate_carry; the analyzer follows the memo function into its "
        "builder (bounded depth) and requires every option input to "
        "appear in the key tuple (one-hop local aliases resolve)."
    )
    hint = ("add the missing value to the cache-key tuple (or to the "
            "memoized function's parameters for functools caches)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        dfi = dataflow.DataflowIndex.shared(index)
        for mod in index.modules:
            msum = dfi.module(mod)
            scopes: List[Tuple[Optional[dataflow.ClassSummary],
                               dataflow.FunctionSummary]] = \
                [(None, fs) for fs in msum.functions.values()]
            for cls in msum.classes.values():
                scopes.extend((cls, fs) for fs in cls.methods.values())
            for cls, fs in scopes:
                yield from self._check_function(dfi, msum, mod, cls, fs)

    def _check_function(self, dfi: dataflow.DataflowIndex,
                        msum: dataflow.ModuleSummary, mod: ModuleInfo,
                        cls: Optional[dataflow.ClassSummary],
                        fs: dataflow.FunctionSummary) -> Iterator[Violation]:
        # functools.lru_cache/cache builders: the parameters ARE the key
        if fs.has_lru_cache and fs.jit_option_inputs:
            has_self = fs.params[:1] in (("self",), ("cls",))
            missing = sorted(
                name for name in fs.jit_option_inputs
                if name not in fs.params and
                not (has_self and name.startswith("self.")))
            if missing:
                yield self.violation(
                    mod, fs.line,
                    f"functools-cached builder {fs.qualname}() configures "
                    f"jit options from {', '.join(missing)} which are not "
                    f"parameters — the cache key cannot see them",
                    scope=fs.qualname, symbol="lru-key-incomplete")
        # dict-memo sites: key tuple must cover every option input
        if not fs.cache_sites:
            return
        required = dfi.required_key_inputs(msum, cls, fs)
        required = {r for r in required if r != "self"}
        if not required:
            return
        for site in fs.cache_sites:
            missing = sorted(required - site.components -
                             set(fs.params))
            if missing:
                yield self.violation(
                    mod, site.line,
                    f"executable cache {site.cache_name} in "
                    f"{fs.qualname}() is keyed on {site.key_var!r} which "
                    f"omits jit-option input(s) {', '.join(missing)} — a "
                    f"flip of any of these serves a stale executable",
                    scope=fs.qualname,
                    symbol=f"key-incomplete:{site.cache_name}")


@register
class FaultTransparencyRule(Rule):
    id = "EXON003"
    name = "fault-transparency"
    family = "exactly_once"
    rationale = (
        "Chaos coverage is only real if injected faults actually "
        "propagate: on modules that import the chaos plane (the fault "
        "seams), an except clause wide enough to catch InjectedCrash "
        "(bare, BaseException, Exception, ConnectionError, OSError, or "
        "InjectedFault) that neither re-raises it nor carries an "
        "attributed @absorbs_faults reason silently converts an injected "
        "process death into business-as-usual — every chaos test behind "
        "that seam then passes vacuously. Recognized transparent shapes: "
        "an earlier `except InjectedCrash: raise` clause, a bare raise, "
        "re-raising the caught name, an isinstance(InjectedCrash) guard "
        "with a raise, or delegating the exception to a helper that "
        "re-raises it (coordinator._failed)."
    )
    hint = ("add `except _chaos.InjectedCrash: raise` above the broad "
            "handler, or decorate the function with "
            "@absorbs_faults(\"<why absorption is the contract here>\")")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        dfi = dataflow.DataflowIndex.shared(index)
        chaos_prefix = f"{index.package}.chaos"
        for mod in index.modules:
            if mod.rel.startswith("chaos/") or mod.rel == "chaos.py":
                continue
            imports = {name for name, _ in index.all_imports(mod)}
            if not any(i == chaos_prefix or i.startswith(chaos_prefix + ".")
                       for i in imports):
                continue
            yield from self._check_module(dfi, index, mod)

    def _check_module(self, dfi: dataflow.DataflowIndex, index: ModuleIndex,
                      mod: ModuleInfo) -> Iterator[Violation]:
        msum = dfi.module(mod)
        parents = parent_map(mod.tree)
        seen: Dict[str, int] = {}
        scopes: List[Tuple[Optional[dataflow.ClassSummary],
                           dataflow.FunctionSummary]] = \
            [(None, fs) for fs in msum.functions.values()]
        for cls in msum.classes.values():
            scopes.extend((cls, fs) for fs in cls.methods.values())
        for cls, fs in scopes:
            for h in fs.handlers:
                yield from self._check_handler(dfi, msum, mod, parents,
                                               cls, fs, h, seen)

    def _check_handler(self, dfi: dataflow.DataflowIndex,
                       msum: dataflow.ModuleSummary, mod: ModuleInfo,
                       parents, cls, fs: dataflow.FunctionSummary,
                       h: dataflow.HandlerInfo,
                       seen: Dict[str, int]) -> Iterator[Violation]:
        types = h.type_names
        if "InjectedCrash" in types:
            return                    # explicit chaos handler: deliberate
        wide = not types or any(t in WIDE_TYPES for t in types)
        if not wide:
            return
        # a handler can only eat a fault its try body can raise: the body
        # must reach a chaos seam (directly, or through fault-carrying
        # calls) — `except OSError` around sock.close() is mere cleanup
        if not dfi.try_body_carries_fault(h.try_node, fs.node):
            return
        # (a) an earlier clause in the same try intercepts the fault
        for other in h.try_node.handlers:
            if other is h.node:
                break
            if any(t in _INJECTED
                   for t in dataflow._handler_type_names(other)):
                return
        body_nodes = [n for s in h.node.body for n in ast.walk(s)]
        raises = [n for n in body_nodes if isinstance(n, ast.Raise)]
        # (b) bare raise
        if any(r.exc is None for r in raises):
            return
        # (c) re-raises the caught name, or wraps it loudly: `raise
        # Typed(...) from e` chains the injected fault as __cause__ — the
        # failure propagates attributed, nothing is silently eaten
        caught = h.node.name
        if caught and any(
                (isinstance(r.exc, ast.Name) and r.exc.id == caught) or
                (isinstance(r.cause, ast.Name) and r.cause.id == caught)
                for r in raises):
            return
        # (d) isinstance-guard: references the injected types AND raises
        mentions = any(
            isinstance(n, (ast.Name, ast.Attribute)) and
            (dataflow.dotted(n) or "").split(".")[-1] in _INJECTED
            for n in body_nodes)
        if mentions and raises:
            return
        # (e) delegates the caught exception to a re-raising helper
        if caught:
            calls = [n for n in body_nodes if isinstance(n, ast.Call)]
            if dfi.call_reraises(msum, cls, calls, caught):
                return
        # (f) attributed allowlist on ANY enclosing function (handlers in
        # nested defs honor the nearest decorated ancestor)
        reason = None
        cur = h.node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                r = _contracts_absorbs(cur)
                if r is not None:
                    reason = r
                    break
            cur = parents.get(cur)
        if reason is not None and reason.strip():
            return
        scope = enclosing_scope(parents, h.node)
        label = ",".join(types) if types else "bare"
        base = f"except:{label}"
        n = seen[(scope, base)] = seen.get((scope, base), 0) + 1
        extra = (" (@absorbs_faults has an empty reason — attribute it)"
                 if reason is not None else "")
        yield self.violation(
            mod, h.line,
            f"except {label or '<bare>'} on a chaos seam can absorb "
            f"InjectedCrash without re-raising it — injected process "
            f"death becomes business-as-usual and chaos coverage goes "
            f"vacuous{extra}",
            scope=scope, symbol=base if n == 1 else f"{base}#{n}")
