"""Wire-safety rule family (WIRE): deserialization and data-path hygiene.

Migrated from the ad-hoc functions in tests/test_architecture.py so one
engine owns them (the tests are now thin wrappers over the registry):

- WIRE001 no-bare-pickle — modules handling socket-originated bytes must
  deserialize through flink_tpu.security only (the ISSUE-1 invariant:
  MAC-verify BEFORE deserialize, allowlisted unpickler).
- WIRE002 serialization-free-dataplane — runtime/dataplane.py must not
  serialize batch payloads itself (the ISSUE-3 zero-copy invariant).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from flink_tpu.lint.index import ModuleIndex, ModuleInfo
from flink_tpu.lint.rule import Rule, Violation, register  # noqa: F401 — Violation used in annotations

#: package-relative subtrees whose bytes can originate from a socket
NETWORK_PLANES = ("runtime", "fs")


def _pickle_load_sites(mod: ModuleInfo) -> List[Tuple[str, str, int]]:
    """Every way raw deserialization can be spelled, anywhere in the file
    (function bodies included — lazy code paths must be seen too):
    `pickle.loads/load(...)`, `pickle.Unpickler` references, and
    `from pickle import loads/load/Unpickler` (which would make later
    bare-name calls invisible to attribute matching — the import itself
    is the violation)."""
    found: List[Tuple[str, str, int]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "pickle", "cloudpickle"):
            for a in node.names:
                if a.name in ("loads", "load", "Unpickler", "*"):
                    found.append((node.module, f"import {a.name}",
                                  node.lineno))
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id in ("pickle", "cloudpickle"):
            if node.attr in ("loads", "load", "Unpickler"):
                found.append((node.value.id, node.attr, node.lineno))
    return found


@register
class NoBarePickleRule(Rule):
    id = "WIRE001"
    name = "no-bare-pickle"
    family = "wire"
    rationale = (
        "Everything under runtime/ and fs/ handles bytes that can "
        "originate from a socket (RPC frames, exchange batches, blob "
        "payloads, object-store reads), so no module there may "
        "deserialize with pickle directly — loads/load calls, Unpickler "
        "subclassing, and `from pickle import loads` are all banned. "
        "Deserialization goes through flink_tpu.security "
        "(restricted_loads after MAC verification; trusted_loads for "
        "post-auth job specs). A new raw-pickle path on a network plane "
        "must fail CI before it fails an incident review."
    )
    hint = ("route it through flink_tpu.security.framing "
            "(restricted_loads/trusted_loads)")

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        for layer in NETWORK_PLANES:
            for mod in index.in_subtree(layer):
                # occurrence index, NOT the line number: fingerprints must
                # survive unrelated edits to the file (baseline contract)
                seen: Dict[str, int] = {}
                for pkg, what, line in _pickle_load_sites(mod):
                    base = f"{pkg}.{what}"
                    n = seen[base] = seen.get(base, 0) + 1
                    yield self.violation(
                        mod, line,
                        f"uses {pkg}.{what} on a network plane",
                        scope="",
                        symbol=base if n == 1 else f"{base}#{n}")


@register
class SerializationFreeDataplaneRule(Rule):
    id = "WIRE002"
    name = "serialization-free-dataplane"
    family = "wire"
    rationale = (
        "runtime/dataplane.py may not serialize batch payloads itself — "
        "no pickle/cloudpickle import, no dumps(/loads( call anywhere in "
        "the module. Batch bytes cross the process boundary only through "
        "flink_tpu.security: the zero-copy binary columnar wire "
        "(security/wire.py via transport.send_data_frame/recv_msg) or the "
        "legacy restricted-pickle codec (transport.send_obj/recv_obj). A "
        "convenience dumps(batch) creeping back into the data path "
        "reintroduces the full-copy serialization tax (and a "
        "deserialize-before-MAC hazard on the receive side) that the "
        "binary wire exists to remove."
    )
    hint = "route batches through security.transport / security.wire"

    DATAPLANE = "runtime/dataplane.py"

    def check(self, index: ModuleIndex) -> Iterator[Violation]:
        mod = index.get(self.DATAPLANE)
        if mod is None:
            return
        seen: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in ("pickle", "cloudpickle"):
                        yield self.violation(
                            mod, node.lineno, f"import {a.name}",
                            symbol=f"import:{a.name}")
            elif isinstance(node, ast.ImportFrom):
                if node.module in ("pickle", "cloudpickle"):
                    yield self.violation(
                        mod, node.lineno,
                        f"from {node.module} import ...",
                        symbol=f"from:{node.module}")
                elif node.module and any(
                        a.name in ("dumps", "loads", "dump", "load")
                        for a in node.names):
                    yield self.violation(
                        mod, node.lineno,
                        f"from {node.module} imports a serializer name",
                        symbol=f"from-serializer:{node.module}")
            elif isinstance(node, ast.Call):
                fn = node.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if name in ("dumps", "loads", "dump", "load"):
                    base = f"call:{name}"
                    n = seen[base] = seen.get(base, 0) + 1
                    yield self.violation(
                        mod, node.lineno,
                        f"call to {name}(...) on the data path",
                        symbol=base if n == 1 else f"{base}#{n}")
