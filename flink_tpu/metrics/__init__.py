"""Observability: metrics, traces, latency tracking (reference layers O1-O4)."""

from flink_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Meter,
    MetricGroup,
    MetricRegistry,
)
from flink_tpu.metrics.traces import Span, SpanBuilder, TraceReporter, LoggingTraceReporter
