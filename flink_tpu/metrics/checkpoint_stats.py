"""Checkpoint, failure and recovery statistics (control-plane observability).

The reference tracks every checkpoint's lifecycle in
CheckpointStatsTracker/DefaultCheckpointStatsTracker (pending → completed/
failed records in a bounded CheckpointStatsHistory, lifetime
CheckpointStatsCounts, and the standard gauges lastCheckpointDuration /
lastCheckpointSize / numberOfCompletedCheckpoints / ... registered by
CheckpointStatsTracker.registerMetrics), keeps a bounded exception history
per job (ExceptionHistoryEntry served by JobExceptionsHandler), and derives
restart cost from RestartTimeGauge/DownTimeGauge. This module is the
stepped-runtime analogue for BOTH execution paths:

- the in-process MiniCluster feeds a tracker from
  checkpoint/coordinator.py (capture = sync phase, persist = async phase)
  and records exception/recovery entries around each attempt;
- the distributed JobManager feeds one tracker per job from the
  trigger/ack/decline RPCs (per-task ack latency, state bytes from the
  shipped stateBytes gauges) and attributes failures to task/TaskManager.

Everything here is plain data + plain callables: no imports from
flink_tpu.runtime (stats flow OUTWARD via these trackers — enforced by
tests/test_architecture.py), and every payload() is restricted-pickle- and
JSON-safe so it ships over the authenticated RPC plane and REST unchanged.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

# chaos-plane attribution: failures whose cause carries this marker were
# INJECTED by an installed FaultPlan (flink_tpu/chaos — a stdlib-only leaf
# module), and the exception history tags them `injected: true` so chaos
# scenarios can assert exactly where the runtime blamed each fault. The
# marker is a plain substring because the distributed path ships failures
# as repr() strings over RPC.
from flink_tpu.chaos.plan import INJECTED_MARKER

# checkpoint lifecycle states (CheckpointStatsStatus analogue)
PENDING = "PENDING"
COMPLETED = "COMPLETED"
FAILED = "FAILED"

_OPERATOR_PREFIX = "job.operator."
_STATE_BYTES_LEAF = ".stateBytes"


def snapshot_bytes_estimate(obj: Any) -> int:
    """Recursive size estimate of a snapshot payload: numpy arrays count
    their buffer (`nbytes`), bytes-likes their length, containers recurse.
    Used for per-task state sizes in the distributed path, where the
    snapshot is in hand but the persisted artifact lives on the JM."""
    if hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, str):
        return len(obj)
    if isinstance(obj, dict):
        return sum(
            snapshot_bytes_estimate(k) + snapshot_bytes_estimate(v)
            for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple, set, frozenset)):
        return sum(snapshot_bytes_estimate(v) for v in obj)
    if obj is None or isinstance(obj, (int, float, bool)):
        return 8
    try:
        return sys.getsizeof(obj)
    except TypeError:
        return 8


def operator_bytes_from_snapshot(metric_snapshot: Dict[str, Any],
                                 into: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """Fold one task's plain-data metric snapshot into a per-operator state
    byte map: `job.operator.<uid>.stateBytes` keys sum per uid (shards of
    the same operator add up). This is how the JM builds the per-operator
    breakdown of a completed checkpoint from gauges the TMs already ship."""
    out: Dict[str, int] = into if into is not None else {}
    for key, val in metric_snapshot.items():
        if (key.startswith(_OPERATOR_PREFIX) and key.endswith(_STATE_BYTES_LEAF)
                and isinstance(val, (int, float))):
            uid = key[len(_OPERATOR_PREFIX):-len(_STATE_BYTES_LEAF)]
            out[uid] = out.get(uid, 0) + int(val)
    return out


def root_cause_chain(exc: Optional[BaseException], limit: int = 8) -> List[str]:
    """`repr`-level cause chain of an exception, outermost first — the
    ExceptionHistoryEntry root-cause view (explicit `raise ... from` causes
    preferred, falling back to implicit context the way traceback does)."""
    chain: List[str] = []
    seen = set()
    while exc is not None and id(exc) not in seen and len(chain) < limit:
        seen.add(id(exc))
        chain.append(f"{type(exc).__name__}: {exc}")
        exc = exc.__cause__ or (
            exc.__context__ if not exc.__suppress_context__ else None)
    return chain


def failing_task(exc: Optional[BaseException]) -> Optional[str]:
    """Best-effort task attribution for an in-process failure: the uid of
    the innermost traceback frame whose `self` is a runner/operator with a
    `uid` attribute — i.e. which operator the exception escaped from."""
    if exc is None:
        return None
    uid = None
    tb = exc.__traceback__
    while tb is not None:
        owner = tb.tb_frame.f_locals.get("self")
        got = getattr(owner, "uid", None)
        if isinstance(got, str):
            uid = got
        tb = tb.tb_next
    return uid


class CheckpointStats:
    """One checkpoint's lifecycle record (AbstractCheckpointStats analogue).

    Plain mutable holder; the tracker owns all mutation under its lock."""

    __slots__ = (
        "checkpoint_id", "status", "is_savepoint", "trigger_ts_ms",
        "sync_duration_ms", "async_duration_ms", "end_to_end_duration_ms",
        "state_size_bytes", "operator_bytes", "task_acks", "failure_cause",
        "completion_ts_ms",
    )

    def __init__(self, checkpoint_id: int, trigger_ts_ms: float,
                 is_savepoint: bool = False):
        self.checkpoint_id = checkpoint_id
        self.status = PENDING
        self.is_savepoint = is_savepoint
        self.trigger_ts_ms = trigger_ts_ms
        self.sync_duration_ms: Optional[float] = None    # capture phase
        self.async_duration_ms: Optional[float] = None   # persist phase
        self.end_to_end_duration_ms: Optional[float] = None
        self.state_size_bytes: int = 0
        self.operator_bytes: Dict[str, int] = {}
        # task -> {"ack_latency_ms", "state_size_bytes"} (distributed path)
        self.task_acks: Dict[str, Dict[str, float]] = {}
        self.failure_cause: Optional[str] = None
        self.completion_ts_ms: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.checkpoint_id,
            "status": self.status,
            "is_savepoint": self.is_savepoint,
            "trigger_timestamp_ms": self.trigger_ts_ms,
            "sync_duration_ms": self.sync_duration_ms,
            "async_duration_ms": self.async_duration_ms,
            "end_to_end_duration_ms": self.end_to_end_duration_ms,
            "state_size_bytes": self.state_size_bytes,
            "operators": dict(self.operator_bytes),
            "tasks": {t: dict(a) for t, a in self.task_acks.items()},
            "num_acknowledged": len(self.task_acks),
            "failure_cause": self.failure_cause,
            "completion_timestamp_ms": self.completion_ts_ms,
        }


class CheckpointStatsTracker:
    """Bounded per-checkpoint history + lifetime counters + the standard
    gauges (CheckpointStatsTracker/CheckpointStatsHistory analogue).

    Thread-safe: the JM main thread / job thread report, REST and metric
    reporters read concurrently."""

    def __init__(self, history_size: int = 10, clock: Callable[[], float] = time.time):
        self._clock = clock           # wall seconds
        self._history_size = max(int(history_size), 1)
        self._records: Dict[int, CheckpointStats] = {}
        self._order: deque = deque()  # checkpoint ids, oldest first
        self._lock = threading.Lock()
        self.num_completed = 0
        self.num_failed = 0
        # failed-or-declined records since the last completion — the gauge
        # behind execution.checkpointing.tolerable-failed-checkpoints
        # dashboards (the enforcing counters live on the coordinator/JM,
        # which distinguish real failures from benign savepoint declines)
        self.consecutive_failed = 0
        self._last_completed: Optional[CheckpointStats] = None
        self._last_failed: Optional[CheckpointStats] = None
        # {"checkpoint_id", "restore_timestamp_ms", "restore_duration_ms"}
        self.last_restore: Optional[Dict[str, Any]] = None

    # -- reporting ---------------------------------------------------------
    def report_pending(self, checkpoint_id: int, *, is_savepoint: bool = False,
                       trigger_ts_ms: Optional[float] = None) -> CheckpointStats:
        rec = CheckpointStats(
            checkpoint_id,
            self._clock() * 1000.0 if trigger_ts_ms is None else trigger_ts_ms,
            is_savepoint,
        )
        with self._lock:
            if checkpoint_id not in self._records:
                # a failed trigger's id is re-used by the next attempt —
                # replace the record, never duplicate the ring slot
                self._order.append(checkpoint_id)
            self._records[checkpoint_id] = rec
            while len(self._order) > self._history_size:
                self._records.pop(self._order.popleft(), None)
        return rec

    def report_ack(self, checkpoint_id: int, task: str,
                   state_size_bytes: int = 0) -> None:
        """One task acknowledged (distributed path): latency is measured
        from the trigger timestamp — the aligned-barrier + capture + RPC
        cost as seen by the coordinator."""
        now_ms = self._clock() * 1000.0
        with self._lock:
            rec = self._records.get(checkpoint_id)
            if rec is None:
                return
            rec.task_acks[str(task)] = {
                "ack_latency_ms": max(now_ms - rec.trigger_ts_ms, 0.0),
                "state_size_bytes": int(state_size_bytes),
            }

    def report_completed(self, checkpoint_id: int, *,
                         sync_duration_ms: Optional[float] = None,
                         async_duration_ms: Optional[float] = None,
                         state_size_bytes: Optional[int] = None,
                         operator_bytes: Optional[Dict[str, int]] = None) -> None:
        now_ms = self._clock() * 1000.0
        with self._lock:
            rec = self._records.get(checkpoint_id)
            if rec is None:       # evicted from the ring: still count it
                rec = CheckpointStats(checkpoint_id, now_ms)
            if rec.status == FAILED:
                # a straggler ack completing the set after the job already
                # failed the checkpoint must not resurrect the record (and
                # double-count it in both tallies); a re-trigger of the id
                # goes through report_pending, which resets the record
                return
            rec.status = COMPLETED
            rec.completion_ts_ms = now_ms
            rec.sync_duration_ms = sync_duration_ms
            rec.async_duration_ms = async_duration_ms
            rec.end_to_end_duration_ms = max(now_ms - rec.trigger_ts_ms, 0.0)
            if state_size_bytes is not None:
                rec.state_size_bytes = int(state_size_bytes)
            elif rec.task_acks:
                rec.state_size_bytes = int(sum(
                    a.get("state_size_bytes", 0) for a in rec.task_acks.values()))
            if operator_bytes:
                rec.operator_bytes = {k: int(v) for k, v in operator_bytes.items()}
            self.num_completed += 1
            self.consecutive_failed = 0
            self._last_completed = rec

    def report_failed(self, checkpoint_id: int, failure_cause: str,
                      benign: bool = False) -> None:
        """`benign` marks failures that are NOT storage/capture faults —
        savepoint outrun declines (which retry by design) and the sweeps
        that fail in-flight records when a job restarts or rescales. They
        count in num_failed (the record IS failed) but never in the
        consecutiveFailedCheckpoints gauge, which must mirror what
        tolerable-failed-checkpoints enforcement counts — a gauge
        climbing on benign declines would page operators on healthy jobs."""
        now_ms = self._clock() * 1000.0
        with self._lock:
            rec = self._records.get(checkpoint_id)
            if rec is None:
                rec = CheckpointStats(checkpoint_id, now_ms)
            if rec.status == COMPLETED:
                return            # late decline must not un-complete
            rec.status = FAILED
            rec.completion_ts_ms = now_ms
            rec.end_to_end_duration_ms = max(now_ms - rec.trigger_ts_ms, 0.0)
            rec.failure_cause = str(failure_cause)
            self.num_failed += 1
            if not benign:
                self.consecutive_failed += 1
            self._last_failed = rec

    def report_restore(self, checkpoint_id: Optional[int],
                       restore_duration_ms: float) -> None:
        """A (re)start restored from `checkpoint_id` — feeds the
        lastCheckpointRestoreTimestamp gauge and the latest.restored view."""
        with self._lock:
            self.last_restore = {
                "checkpoint_id": checkpoint_id,
                "restore_timestamp_ms": self._clock() * 1000.0,
                "restore_duration_ms": float(restore_duration_ms),
            }

    # -- reading -----------------------------------------------------------
    def _pending_count(self) -> int:
        """PENDING records in the ring; call with the lock held."""
        return sum(1 for r in self._records.values() if r.status == PENDING)

    def checkpoint(self, checkpoint_id: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._records.get(checkpoint_id)
            return rec.to_dict() if rec is not None else None

    def gauge_values(self, prefix: str = "") -> Dict[str, float]:
        """The standard checkpoint gauges as a plain dict (the names the
        reference registers on the job metric group)."""
        with self._lock:
            last = self._last_completed
            restore_ts = (self.last_restore or {}).get("restore_timestamp_ms", 0)
            return {
                prefix + "numberOfCompletedCheckpoints": self.num_completed,
                prefix + "numberOfFailedCheckpoints": self.num_failed,
                prefix + "consecutiveFailedCheckpoints": self.consecutive_failed,
                prefix + "numberOfInProgressCheckpoints": self._pending_count(),
                prefix + "lastCheckpointDuration": (
                    last.end_to_end_duration_ms if last is not None else 0),
                prefix + "lastCheckpointSize": (
                    last.state_size_bytes if last is not None else 0),
                prefix + "lastCheckpointRestoreTimestamp": restore_ts,
            }

    def register_metrics(self, group) -> None:
        """Register the standard gauges on a metric group (names per the
        reference's CheckpointStatsTracker.registerMetrics)."""
        # fold/kind declarations (ISSUE-19): completed/failed totals are
        # monotone (kind="counter" -> the history plane records checkpoint
        # RATES); the last* family are point-in-time facts that fold MAX
        # (the newest checkpoint wins — summing a duration across shards
        # reporting the same checkpoint would multiply it)
        for name, fold, kind in (
                ("numberOfCompletedCheckpoints", "sum", "counter"),
                ("numberOfFailedCheckpoints", "sum", "counter"),
                ("consecutiveFailedCheckpoints", "max", None),
                ("numberOfInProgressCheckpoints", "sum", None),
                ("lastCheckpointDuration", "max", None),
                ("lastCheckpointSize", "max", None),
                ("lastCheckpointRestoreTimestamp", "max", None)):
            group.gauge(name, lambda n=name: self.gauge_values()[n],
                        fold=fold, kind=kind)

    def payload(self) -> Dict[str, Any]:
        """REST /jobs/:id/checkpoints body (CheckpointingStatistics shape:
        counts + summary + latest + bounded history, newest first)."""
        with self._lock:
            history = [self._records[cid].to_dict()
                       for cid in reversed(self._order)
                       if cid in self._records]
            completed_e2e = [r.end_to_end_duration_ms
                             for r in self._records.values()
                             if r.status == COMPLETED
                             and r.end_to_end_duration_ms is not None]
            completed_size = [r.state_size_bytes for r in self._records.values()
                              if r.status == COMPLETED]
            summary: Dict[str, Any] = {}
            for name, vals in (("end_to_end_duration_ms", completed_e2e),
                               ("state_size_bytes", completed_size)):
                if vals:
                    summary[name] = {
                        "min": min(vals), "max": max(vals),
                        "avg": sum(vals) / len(vals),
                    }
            pending = self._pending_count()
            return {
                "counts": {
                    "total": self.num_completed + self.num_failed + pending,
                    "in_progress": pending,
                    "completed": self.num_completed,
                    "failed": self.num_failed,
                },
                "summary": summary,
                "latest": {
                    "completed": (self._last_completed.to_dict()
                                  if self._last_completed else None),
                    "failed": (self._last_failed.to_dict()
                               if self._last_failed else None),
                    "restored": dict(self.last_restore)
                    if self.last_restore else None,
                },
                "history": history,
            }


def empty_checkpoints_payload() -> Dict[str, Any]:
    """What /jobs/:id/checkpoints returns for a job with no tracker (e.g.
    checkpointing disabled) — same shape, all zeros."""
    return {
        "counts": {"total": 0, "in_progress": 0, "completed": 0, "failed": 0},
        "summary": {},
        "latest": {"completed": None, "failed": None, "restored": None},
        "history": [],
    }


class ExceptionHistory:
    """Bounded per-job failure + recovery history (ExceptionHistoryEntry /
    JobExceptionsHandler analogue, with the RestartTimeGauge/DownTimeGauge
    signals folded into one recovery-timeline record per restart).

    A failure appends an exception entry (timestamp, task/TaskManager
    attribution, root-cause chain, restart number). If the job restarts,
    `begin_recovery` opens a timeline record at failure time and
    `complete_recovery` closes it when the new attempt reaches RUNNING —
    capturing restore duration, the checkpoint id rewound to, steps/events
    replayed, and downtime (fail → RUNNING)."""

    def __init__(self, size: int = 16, clock: Callable[[], float] = time.time):
        self._clock = clock
        self.entries: deque = deque(maxlen=max(int(size), 1))
        self.recoveries: deque = deque(maxlen=max(int(size), 1))
        self._open_recovery: Optional[Dict[str, Any]] = None
        # lifetime restart count: the numRestarts gauge must keep climbing
        # after the bounded recovery ring starts evicting (a flapping job is
        # exactly when the restart rate matters)
        self._num_restarts = 0
        self._lock = threading.Lock()

    # -- failures ----------------------------------------------------------
    def record_failure(self, cause: str, *, task: Optional[str] = None,
                       task_manager: Optional[str] = None,
                       restart_number: int = 0,
                       exception: Optional[BaseException] = None) -> Dict[str, Any]:
        chain = (root_cause_chain(exception)
                 if exception is not None else [str(cause)])
        entry = {
            "timestamp_ms": self._clock() * 1000.0,
            "exception": str(cause),
            "root_cause_chain": chain,
            "task": task,
            "task_manager": task_manager,
            "restart_number": int(restart_number),
            # chaos attribution: true when the failure was injected by an
            # installed FaultPlan (marker survives the distributed path's
            # repr()-over-RPC shipping) — scenarios assert WHERE the
            # runtime blamed each injected fault
            "injected": (INJECTED_MARKER in str(cause)
                         or any(INJECTED_MARKER in c for c in chain)),
        }
        with self._lock:
            self.entries.append(entry)
        return entry

    def latest(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return dict(self.entries[-1]) if self.entries else None

    # -- recovery timeline -------------------------------------------------
    def begin_recovery(self, restart_number: int, *, cause: str,
                       steps_at_failure: Optional[int] = None,
                       events_at_failure: Optional[int] = None,
                       kind: str = "restart") -> None:
        """`kind` distinguishes failure-driven restarts from deliberate
        autoscaler rescales — both rewind to a checkpoint and redeploy, so
        both ride this timeline (and both count toward numRestarts, as the
        reference's reactive mode does)."""
        with self._lock:
            self._num_restarts += 1
            self._open_recovery = {
                "kind": str(kind),
                "restart_number": int(restart_number),
                "failed_at_ms": self._clock() * 1000.0,
                "cause": str(cause),
                "injected": INJECTED_MARKER in str(cause),
                "steps_at_failure": steps_at_failure,
                "events_at_failure": events_at_failure,
                "restored_checkpoint_id": None,
                "restore_duration_ms": None,
                "steps_replayed": None,
                "events_replayed": None,
                "running_at_ms": None,
                "downtime_ms": None,
            }

    def complete_recovery(self, *, restored_checkpoint_id: Optional[int] = None,
                          restore_duration_ms: Optional[float] = None,
                          steps_replayed: Optional[int] = None,
                          events_replayed: Optional[int] = None,
                          restored_step: Optional[int] = None) -> None:
        """Close the open recovery record: the restarted attempt reached
        RUNNING. No-op when no recovery is open (initial schedules).
        `restored_step` derives steps_replayed from the failure-time step
        recorded by begin_recovery (rewind depth in steps)."""
        with self._lock:
            rec = self._open_recovery
            if rec is None:
                return
            self._open_recovery = None
            now_ms = self._clock() * 1000.0
            if (steps_replayed is None and restored_step is not None
                    and rec["steps_at_failure"] is not None):
                steps_replayed = max(rec["steps_at_failure"] - restored_step, 0)
            rec["restored_checkpoint_id"] = restored_checkpoint_id
            rec["restore_duration_ms"] = restore_duration_ms
            rec["steps_replayed"] = steps_replayed
            rec["events_replayed"] = events_replayed
            rec["running_at_ms"] = now_ms
            rec["downtime_ms"] = max(now_ms - rec["failed_at_ms"], 0.0)
            self.recoveries.append(rec)

    # -- reading -----------------------------------------------------------
    def gauge_values(self, prefix: str = "") -> Dict[str, float]:
        with self._lock:
            last = self.recoveries[-1] if self.recoveries else None
            return {
                prefix + "numRestarts": self._num_restarts,
                prefix + "lastRestartDowntimeMs": (
                    last["downtime_ms"] if last else 0),
                prefix + "lastCheckpointRestoreDurationMs": (
                    (last.get("restore_duration_ms") or 0) if last else 0),
            }

    def register_metrics(self, group) -> None:
        for name, fold, kind in (
                ("numRestarts", "sum", "counter"),
                ("lastRestartDowntimeMs", "max", None),
                ("lastCheckpointRestoreDurationMs", "max", None)):
            group.gauge(name, lambda n=name: self.gauge_values()[n],
                        fold=fold, kind=kind)

    def payload(self) -> Dict[str, Any]:
        """REST /jobs/:id/exceptions body: root exception + bounded entry
        list (newest first) + the recovery timeline (newest first)."""
        with self._lock:
            entries = [dict(e) for e in reversed(self.entries)]
            recoveries = [dict(r) for r in reversed(self.recoveries)]
            if self._open_recovery is not None:
                recoveries.insert(0, dict(self._open_recovery))
            root = entries[0] if entries else None
            return {
                "root_exception": root["exception"] if root else None,
                "timestamp_ms": root["timestamp_ms"] if root else None,
                "entries": entries,
                "recoveries": recoveries,
            }


def empty_exceptions_payload() -> Dict[str, Any]:
    return {"root_exception": None, "timestamp_ms": None,
            "entries": [], "recoveries": []}
