"""Device-plane observability: XLA compile/recompile tracking and
per-kernel cost/roofline attribution.

PR 2 instrumented the data path (latency markers, busy/idle ratios,
DeviceTimer wall times) and PR 4 the control plane (checkpoint/failure
stats); the device itself stayed a black box — the runtime could not say
whether a job is recompile-thrashing, where a laggard kernel's device time
goes, or how far a kernel sits from the HBM/FLOPs roofline. This module is
the third observability plane's core:

- **CompileTracker** wraps a jitted program's dispatch sites: per-program
  compile count and compile wall time, the triggering shape signature, a
  bounded recompile-event ring with *cause attribution* (ring doubling /
  batch-geometry churn / dtype change — inferred by diffing the signature
  that compiled against the program's previous one), and a
  ``recompileStorm`` warning gauge when N recompiles land within a sliding
  window. Detection uses the jitted callable's own executable cache
  (``_cache_size`` growth across a call — the call that grew it is the
  call that compiled), falling back to per-signature bookkeeping for
  callables that do not expose it.
- **Cost & roofline capture** — on each compile the tracker captures
  ``fn.lower(*args).cost_analysis()`` (FLOPs, bytes accessed; one extra
  trace, no compile) and optionally the AOT executable's
  ``memory_analysis()`` (temp/output HBM — costs an extra compile, off by
  default). Per-dispatch costs accumulate into lifetime bytes/FLOPs
  totals, which combined with the PR-2 DeviceTimer wall time give the
  ``hbmUtilizationPct``/``flopsUtilizationPct`` roofline gauges.

Layering: metrics sits below the runtime — this module never imports it.
The jitted callables and their arguments are handed IN by runtime callers;
jax itself is only touched through those objects (duck-typed), so plain
control-plane processes never pay a jax import for importing this module.
All tracker state is lock-protected: dispatch happens on task threads
while heartbeat/REST threads read gauges and payloads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: roofline denominators by jax backend platform when the
#: observability.device.hbm-gbps / .peak-tflops options are left at 0
#: (auto). Deliberately conservative datasheet-order numbers — utilization
#: gauges are for RELATIVE attribution across operators and PRs; calibrate
#: with the bench-measured hbm_gbps for absolute numbers.
PLATFORM_PEAKS: Dict[str, "tuple[float, float]"] = {
    # platform: (HBM GB/s, peak TFLOP/s)
    "tpu": (1200.0, 275.0),
    "gpu": (2000.0, 300.0),
    "cpu": (50.0, 0.2),
}


def platform_peaks(hbm_gbps: float = 0.0,
                   peak_tflops: float = 0.0) -> "tuple[float, float]":
    """Resolve the roofline denominators: configured values win, 0 falls
    back to the PLATFORM_PEAKS entry for the default jax backend (and to
    the cpu row when jax is unavailable entirely)."""
    if hbm_gbps > 0 and peak_tflops > 0:
        return hbm_gbps, peak_tflops
    platform = "cpu"
    try:
        import jax

        platform = jax.default_backend()
    except Exception:  # noqa: BLE001 — no jax, no device: cpu numbers
        pass
    dflt = PLATFORM_PEAKS.get(platform, PLATFORM_PEAKS["cpu"])
    return (hbm_gbps if hbm_gbps > 0 else dflt[0],
            peak_tflops if peak_tflops > 0 else dflt[1])


def _signature_str(signature: Dict[str, Any]) -> str:
    return ",".join(f"{k}={signature[k]}" for k in sorted(signature))


def attribute_cause(prev: Optional[Dict[str, Any]],
                    new: Dict[str, Any]) -> str:
    """Why did this signature recompile, given the program's previous one?

    Precedence mirrors how disruptive each churn source is: a dtype change
    is a program-semantics change (usually a bug), key-capacity growth is
    the ring-doubling cost model working as designed (amortized, but worth
    seeing), and T/B churn is batch-geometry instability (ragged tails,
    unstable source batching) — the classic silent-recompile thrash."""
    if prev is None:
        return "initial"
    changed = {k for k in set(prev) | set(new) if prev.get(k) != new.get(k)}
    if not changed:
        # same signature compiled again: the executable cache was evicted
        # or a sibling program shares the name — still worth flagging
        return "cache-eviction"
    if any("dtype" in k.lower() for k in changed):
        return "dtype-change"
    if "K" in changed:
        return "ring-doubling"
    if changed & {"T", "B"}:
        return "batch-geometry"
    return "other:" + "+".join(sorted(changed))


class _ProgramStats:
    __slots__ = ("compiles", "compile_ms", "dispatches", "last_signature",
                 "seen_signatures", "bytes_total", "flops_total",
                 "cost_by_signature")

    def __init__(self):
        self.compiles = 0
        self.compile_ms = 0.0
        self.dispatches = 0
        self.last_signature: Optional[Dict[str, Any]] = None
        self.seen_signatures: set = set()
        self.bytes_total = 0.0
        self.flops_total = 0.0
        # sig_str -> {"flops", "bytes_accessed", "temp_bytes"?, ...}
        self.cost_by_signature: Dict[str, Dict[str, float]] = {}


class CompileTracker:
    """Compile/recompile + cost accounting for one job's device programs.

    One tracker per operator (runner) keeps attribution local; job-level
    exposure merges the per-runner payloads (merge_compile_payloads)."""

    def __init__(self, *, history_size: int = 32, storm_threshold: int = 4,
                 storm_window_ms: int = 60_000, cost_analysis: bool = True,
                 memory_analysis: bool = False,
                 on_event: Optional[Callable[[Dict[str, Any]], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time):
        self.history_size = max(int(history_size), 1)
        self.storm_threshold = max(int(storm_threshold), 1)
        self.storm_window_ms = max(int(storm_window_ms), 1)
        self.cost_analysis = cost_analysis
        self.memory_analysis = memory_analysis
        self.on_event = on_event
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._programs: Dict[str, _ProgramStats] = {}
        self._events: deque = deque(maxlen=self.history_size)
        self._recompile_times: deque = deque(maxlen=256)  # monotonic stamps
        self.num_compiles = 0
        self.num_recompiles = 0
        self.compile_ms_total = 0.0

    # -- dispatch wrapper --------------------------------------------------
    def call(self, program: str, fn, args: tuple,
             signature: Dict[str, Any]):
        """Invoke ``fn(*args)``, recording a compile event if this call
        compiled. Non-compiling dispatches cost one cache-size probe and a
        dict increment — O(1) host work on the hot path."""
        probe = getattr(fn, "_cache_size", None)
        pre = None
        if probe is not None:
            try:
                pre = probe()
            except Exception:  # noqa: BLE001 — observability never fails
                probe = None   # the dispatch
        t0 = self._clock()
        out = fn(*args)
        elapsed_ms = (self._clock() - t0) * 1000.0
        sig_str = _signature_str(signature)
        compiled = False
        if probe is not None and pre is not None:
            try:
                compiled = probe() > pre
            except Exception:  # noqa: BLE001
                compiled = False
        needs_cost = False
        with self._lock:
            stats = self._programs.get(program)
            if stats is None:
                stats = self._programs[program] = _ProgramStats()
            new_signature = sig_str not in stats.seen_signatures
            if probe is None or pre is None:
                # no executable-cache introspection: first sighting of a
                # signature is the compile (an upper bound — a shared jax
                # cache may already hold it, but the signature is new to
                # THIS program's stream of dispatches)
                compiled = new_signature
            stats.seen_signatures.add(sig_str)
            stats.dispatches += 1
            if compiled:
                cause = attribute_cause(stats.last_signature, signature)
                recompile = stats.compiles > 0
                stats.compiles += 1
                stats.compile_ms += elapsed_ms
                self.num_compiles += 1
                self.compile_ms_total += elapsed_ms
                if recompile:
                    self.num_recompiles += 1
                    self._recompile_times.append(self._clock())
                event = {
                    "program": program,
                    "signature": sig_str,
                    "cause": cause,
                    "recompile": recompile,
                    "compile_count": stats.compiles,
                    # wall time of the compiling call: trace + XLA compile
                    # + the first execution (jax offers no finer split at
                    # dispatch time)
                    "duration_ms": round(elapsed_ms, 3),
                    "wall_ts_ms": self._wall() * 1000.0,
                }
                self._events.append(event)
            else:
                event = None
                cost = stats.cost_by_signature.get(sig_str)
                if cost is not None:
                    stats.bytes_total += cost.get("bytes_accessed", 0.0)
                    stats.flops_total += cost.get("flops", 0.0)
                elif new_signature:
                    # the process-wide jit caches already held this shape
                    # (a sibling pipeline or a previous job compiled it):
                    # no compile EVENT for this job, but the roofline
                    # still needs the per-dispatch cost — a warm-cache
                    # job must not read 0% utilization forever
                    needs_cost = True
            stats.last_signature = dict(signature)
        if event is not None or needs_cost:
            # analysis OUTSIDE the lock: lower() re-traces and the
            # optional memory pass compiles — seconds-long work that must
            # not block heartbeat/REST readers of the gauges
            cost = self._analyze(fn, args)
            if cost is not None:
                with self._lock:
                    stats.cost_by_signature[sig_str] = cost
                    stats.bytes_total += cost.get("bytes_accessed", 0.0)
                    stats.flops_total += cost.get("flops", 0.0)
                    if event is not None:
                        event["cost"] = dict(cost)
        if event is not None and self.on_event is not None:
            try:
                self.on_event(event)
            except Exception:  # noqa: BLE001 — a broken span sink
                pass           # must not fail the dispatch
        return out

    def _analyze(self, fn, args) -> Optional[Dict[str, float]]:
        """Best-effort cost/memory analysis of the program that just
        compiled. ``lower()`` re-traces (cheap, no XLA compile); the
        memory pass additionally AOT-compiles — gated separately."""
        if not self.cost_analysis:
            return None
        lower = getattr(fn, "lower", None)
        if lower is None:
            return None
        out: Dict[str, float] = {}
        try:
            lowered = lower(*args)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):   # some versions wrap per-device
                ca = ca[0] if ca else {}
            if isinstance(ca, dict):
                if isinstance(ca.get("flops"), (int, float)):
                    out["flops"] = float(ca["flops"])
                if isinstance(ca.get("bytes accessed"), (int, float)):
                    out["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:  # noqa: BLE001 — backends without cost analysis
            return None
        if self.memory_analysis:
            try:
                mem = lowered.compile().memory_analysis()
                for name, attr in (("temp_bytes", "temp_size_in_bytes"),
                                   ("output_bytes", "output_size_in_bytes"),
                                   ("argument_bytes",
                                    "argument_size_in_bytes"),
                                   ("code_bytes",
                                    "generated_code_size_in_bytes")):
                    v = getattr(mem, attr, None)
                    if isinstance(v, int):
                        out[name] = float(v)
            except Exception:  # noqa: BLE001
                pass
        return out or None

    # -- gauges ------------------------------------------------------------
    def recompile_storm(self) -> int:
        """1 when >= storm_threshold recompiles landed within the sliding
        storm window (a job paying compile latency on the hot path)."""
        with self._lock:
            return self.recompile_storm_unlocked()

    def bytes_accessed_total(self) -> float:
        with self._lock:
            return sum(s.bytes_total for s in self._programs.values())

    def flops_total(self) -> float:
        with self._lock:
            return sum(s.flops_total for s in self._programs.values())

    def dispatches_total(self) -> int:
        with self._lock:
            return sum(s.dispatches for s in self._programs.values())

    def register(self, group) -> None:
        """Register the compile-observability gauges on a metric group."""
        group.gauge("numCompiles", lambda: self.num_compiles,
                    fold="sum", kind="counter")
        group.gauge("numRecompiles", lambda: self.num_recompiles,
                    fold="sum", kind="counter")
        group.gauge("compileTimeMsTotal",
                    lambda: round(self.compile_ms_total, 3),
                    fold="sum", kind="counter")
        group.gauge("recompileStorm", self.recompile_storm, fold="max")

    # -- exposure ----------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def payload(self) -> Dict[str, Any]:
        """Plain-data compile block (REST /jobs/:id/device shape)."""
        with self._lock:
            return {
                "numCompiles": self.num_compiles,
                "numRecompiles": self.num_recompiles,
                "compileTimeMsTotal": round(self.compile_ms_total, 3),
                "recompileStorm": self.recompile_storm_unlocked(),
                "programs": {
                    name: {
                        "compiles": s.compiles,
                        "dispatches": s.dispatches,
                        "compileTimeMsTotal": round(s.compile_ms, 3),
                        "lastSignature": (_signature_str(s.last_signature)
                                          if s.last_signature else None),
                    }
                    for name, s in self._programs.items()
                },
                "events": [dict(e) for e in self._events],
            }

    def recompile_storm_unlocked(self) -> int:
        horizon = self._clock() - self.storm_window_ms / 1000.0
        recent = sum(1 for t in self._recompile_times if t >= horizon)
        return 1 if recent >= self.storm_threshold else 0


def roofline_pct(bytes_accessed: float, flops: float, device_time_s: float,
                 hbm_gbps: float, peak_tflops: float) -> Dict[str, float]:
    """Utilization of the memory/compute rooflines over a measured device
    wall-time window: achieved GB/s (or FLOP/s) as a percentage of the
    part's peak. The denominator is the PR-2 DeviceTimer's host-clock wall
    time around the already-synchronous dispatch/readback sections, so the
    figure slightly UNDER-reports (host overhead in the window) — right
    for cross-operator and cross-PR comparison, not for marketing."""
    if device_time_s <= 0:
        return {"hbmUtilizationPct": 0.0, "flopsUtilizationPct": 0.0}
    hbm = bytes_accessed / (device_time_s * max(hbm_gbps, 1e-9) * 1e9)
    fl = flops / (device_time_s * max(peak_tflops, 1e-9) * 1e12)
    return {
        "hbmUtilizationPct": round(min(hbm, 10.0) * 100.0, 3),
        "flopsUtilizationPct": round(min(fl, 10.0) * 100.0, 3),
    }


def compile_event_span(event: Dict[str, Any]):
    """One compile event as a trace span (scope 'device', name
    'XlaCompile') for the TraceRegistry / TM->JM span shipping. Attribute
    values are OTLP-scalar-safe (str/int/float/bool)."""
    from flink_tpu.metrics.traces import Span

    end = float(event.get("wall_ts_ms", 0.0))
    dur = float(event.get("duration_ms", 0.0))
    attrs: Dict[str, Any] = {
        "program": event.get("program"),
        "signature": event.get("signature"),
        "cause": event.get("cause"),
        "recompile": bool(event.get("recompile", False)),
        "compileCount": int(event.get("compile_count", 1)),
    }
    cost = event.get("cost") or {}
    if "flops" in cost:
        attrs["costFlops"] = float(cost["flops"])
    if "bytes_accessed" in cost:
        attrs["costBytesAccessed"] = float(cost["bytes_accessed"])
    return Span("device", "XlaCompile", end - dur, end, attrs)


def merge_compile_payloads(payloads: List[Dict[str, Any]],
                           history_size: int = 64) -> Dict[str, Any]:
    """Fold per-operator compile payloads into one job-level block: counts
    sum, storm ORs, program tables merge (names are per-program already),
    events interleave by wall timestamp, newest kept within the bound."""
    out: Dict[str, Any] = {
        "numCompiles": 0, "numRecompiles": 0, "compileTimeMsTotal": 0.0,
        "recompileStorm": 0, "programs": {}, "events": [],
    }
    events: List[Dict[str, Any]] = []
    for p in payloads:
        out["numCompiles"] += int(p.get("numCompiles", 0))
        out["numRecompiles"] += int(p.get("numRecompiles", 0))
        out["compileTimeMsTotal"] = round(
            out["compileTimeMsTotal"]
            + float(p.get("compileTimeMsTotal", 0.0)), 3)
        out["recompileStorm"] = max(out["recompileStorm"],
                                    int(p.get("recompileStorm", 0)))
        for name, s in (p.get("programs") or {}).items():
            cur = out["programs"].setdefault(
                name, {"compiles": 0, "dispatches": 0,
                       "compileTimeMsTotal": 0.0, "lastSignature": None})
            cur["compiles"] += int(s.get("compiles", 0))
            cur["dispatches"] += int(s.get("dispatches", 0))
            cur["compileTimeMsTotal"] = round(
                cur["compileTimeMsTotal"]
                + float(s.get("compileTimeMsTotal", 0.0)), 3)
            cur["lastSignature"] = s.get("lastSignature") or cur["lastSignature"]
        events.extend(p.get("events") or ())
    events.sort(key=lambda e: e.get("wall_ts_ms", 0.0))
    out["events"] = events[-history_size:]
    return out


def empty_device_payload() -> Dict[str, Any]:
    """REST /jobs/:id/device body for a job with no device plane (gates
    off, no device operators, or no attempt yet)."""
    return {
        "enabled": False,
        "compile": merge_compile_payloads([]),
        "operators": {},
        "profiler": {"enabled": False, "captures": 0,
                     "last_capture_dir": None},
    }
