"""Job doctor: ranked, evidence-attributed bottleneck diagnosis (ISSUE-19).

`diagnose()` joins the recent history windows across every observability
plane (backpressure ratios, phase counters, roofline gauges, tier
evictions/promotions, controller gauges, watermark lag) with the span
stream (`device.XlaCompile`, `checkpointing.*`, `recovery.JobRestart`,
rebalance, `latency.EmissionStall`) into a ranked list of diagnoses, each
carrying the evidence that produced its score. Served at
``GET /jobs/:id/doctor`` on both REST paths, rendered as a dashboard
panel, and stamped as the ``health`` block into every BENCH_*.json.

`HealthWatchdog` is the proactive half: it watches the same history rings
and turns threshold breaches — throughput collapse against the job's own
recent baseline, watermark stall, backpressure saturation, emission-p99
breach — into rate-limited ``health.*`` spans through the existing span
sink, so a breach is visible in the trace timeline (and the flamegraph)
even when nobody polled the doctor.

Scores are normalized to [0, 1]; a family crosses into the verdict at
``VERDICT_THRESHOLD``. When restarts landed inside the window, the
symptom families a restart *explains* (throughput collapse, watermark
stall, emission stall, the recompile burst) are attenuated and marked
``explained_by``, so the root cause outranks its own symptoms.

This module imports neither jax nor the runtime (ARCH001/DEV003): it
consumes a `MetricHistory` and a list of span dicts handed to it.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["diagnose", "HealthWatchdog", "VERDICT_THRESHOLD",
           "HEALTH_SPAN_SCOPE"]

VERDICT_THRESHOLD = 0.5
HEALTH_SPAN_SCOPE = "health"

# span sink signature shared with the emission-latency plane:
# (scope, name, start_ms, end_ms, attrs)
SpanSink = Callable[[str, str, float, float, Dict[str, Any]], None]

# symptom families a restart in the window explains — attenuated so the
# recovery-restart root cause outranks them. compile-stall is included
# (a restart rebuilds every executable, so the compile burst that
# follows is recovery fallout, not an independent compile regression);
# so are the churn families (the rebuilt attempt remaps its routing
# table and re-materializes its resident tier from the restored state)
_RESTART_SYMPTOMS = ("throughput-collapse", "watermark-stall",
                     "emission-stall", "compile-stall",
                     "rebalance-churn", "tier-churn")
_RESTART_ATTENUATION = 0.4


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _clip01(x: float) -> float:
    return 0.0 if x < 0.0 else (1.0 if x > 1.0 else x)


def _vals(pts: List[Tuple[float, float]]) -> List[float]:
    return [v for _, v in pts]


def _span_overlap_ms(span: Dict[str, Any], lo: float, hi: float) -> float:
    """Milliseconds of `span` inside [lo, hi] (0 if disjoint/malformed).
    Span dicts carry `start_ts_ms`/`end_ts_ms` (traces.Span.to_dict). A
    zero-length span ON the window edge still counts via the half-open
    membership check below, but contributes 0 ms."""
    try:
        s = float(span.get("start_ts_ms", 0.0))
        e = float(span.get("end_ts_ms", s))
    except (TypeError, ValueError):
        return 0.0
    return max(0.0, min(e, hi) - max(s, lo))


def _span_in_window(span: Dict[str, Any], lo: float, hi: float) -> bool:
    """Interval overlap, inclusive — a point span (watchdog health.*
    spans have start == end) inside the window must count even though
    its overlap length is 0 ms."""
    try:
        s = float(span.get("start_ts_ms", 0.0))
        e = float(span.get("end_ts_ms", s))
    except (TypeError, ValueError):
        return False
    return e >= lo and s <= hi


def _spans_in(spans: List[Dict[str, Any]], lo: float, hi: float,
              scope: Optional[str] = None,
              name: Optional[str] = None) -> List[Dict[str, Any]]:
    out = []
    for sp in spans or ():
        if scope is not None and sp.get("scope") != scope:
            continue
        if name is not None and sp.get("name") != name:
            continue
        if _span_in_window(sp, lo, hi):
            out.append(sp)
    return out


def _rate_collapse(pts: List[Tuple[float, float]], lo: float, hi: float
                   ) -> Tuple[float, Dict[str, Any]]:
    """Recent quarter of the window vs the prior baseline: a recent mean
    at half the baseline scores 1.0. Needs a real baseline (>= 4 points
    and a non-trivial rate) so startup never reads as a collapse."""
    split = hi - (hi - lo) / 4.0
    base = _vals([p for p in pts if p[0] < split])
    recent = _vals([p for p in pts if p[0] >= split])
    if len(base) < 3 or not recent:
        return 0.0, {}
    base_mean = _mean(base)
    recent_mean = _mean(recent)
    if base_mean <= 1e-9:
        return 0.0, {}
    drop = 1.0 - recent_mean / base_mean
    score = _clip01(drop / 0.5)
    return score, {
        "baseline_rate": round(base_mean, 3),
        "recent_rate": round(recent_mean, 3),
        "drop_fraction": round(max(0.0, drop), 4),
    }


def _lag_slope(pts: List[Tuple[float, float]]) -> Tuple[float, float]:
    """(slope, latest) of a watermark-lag series — slope in lag-ms per
    wall-ms; a frozen watermark under advancing time slopes at ~1.0."""
    if len(pts) < 3:
        return 0.0, (pts[-1][1] if pts else 0.0)
    t0, v0 = pts[0]
    t1, v1 = pts[-1]
    dt = t1 - t0
    if dt <= 0:
        return 0.0, v1
    return (v1 - v0) / dt, v1


def diagnose(history, spans: Optional[List[Dict[str, Any]]] = None, *,
             now_ms: Optional[float] = None,
             window_ms: float = 60000.0) -> Dict[str, Any]:
    """Rank bottleneck families over the last `window_ms` of history +
    spans. Returns ``{"verdict", "score", "diagnoses": [...], "window_ms",
    "samples"}`` — diagnoses sorted most-severe first, each
    ``{"family", "score", "evidence"}``."""
    spans = spans or []
    if now_ms is None:
        now_ms = time.time() * 1000.0
    lo, hi = now_ms - window_ms, now_ms
    win = lambda suffix: history.window(suffix, window_ms, now_ms=now_ms)

    diagnoses: List[Dict[str, Any]] = []

    def add(family: str, score: float, evidence: Dict[str, Any]) -> None:
        if score > 0.0:
            diagnoses.append({"family": family,
                              "score": round(_clip01(score), 4),
                              "evidence": evidence})

    # -- recovery-restart: restarts in the window are categorically the
    #    dominant event; symptom families below get attenuated
    restarts = _spans_in(spans, lo, hi, scope="recovery", name="JobRestart")
    if restarts:
        n = len(restarts)
        add("recovery-restart", 0.7 + 0.3 * _clip01((n - 1) / 2.0), {
            "restarts_in_window": n,
            "restart_ms": round(sum(_span_overlap_ms(s, lo, hi)
                                    for s in restarts), 3),
        })

    def attenuated(family: str, score: float,
                   evidence: Dict[str, Any]) -> None:
        if restarts and family in _RESTART_SYMPTOMS:
            evidence = dict(evidence, explained_by="recovery-restart")
            # clip BEFORE attenuating: a hugely over-threshold symptom
            # must still land below the root cause, not clip back to 1.0
            score = _clip01(score) * _RESTART_ATTENUATION
        add(family, score, evidence)

    # -- compile-stall: device.XlaCompile span share of the window
    compiles = _spans_in(spans, lo, hi, scope="device", name="XlaCompile")
    compile_ms = sum(_span_overlap_ms(s, lo, hi) for s in compiles)
    if compiles:
        # the window may extend before the job started — normalize by the
        # observed span of activity, bounded below to dodge division blowup
        seen = [p[0] for p in win("numRecordsIn")] or [lo]
        active_ms = max(hi - min(seen), compile_ms, 1.0)
        share = compile_ms / active_ms
        attenuated("compile-stall", share / 0.3, {
            "compiles_in_window": len(compiles),
            "compile_ms": round(compile_ms, 3),
            "time_share": round(share, 4),
        })

    # -- backpressure: mean backPressuredTimeRatio over the window
    bp = _vals(win("backPressuredTimeRatio"))
    if bp:
        mean_bp = _mean(bp)
        add("backpressure", mean_bp / 0.8, {
            "mean_backpressured_ratio": round(mean_bp, 4),
            "points": len(bp),
        })

    # -- tier-churn: eviction+promotion rate vs resident keys (>=10% of
    #    the resident set churning per second saturates the score)
    churn = _mean(_vals(win("evictions"))) + _mean(_vals(win("promotions")))
    if churn > 0.0:
        resident = _mean(_vals(win("residentKeys")))
        ref = max(1.0, 0.1 * resident) if resident > 0 else 50.0
        attenuated("tier-churn", churn / ref, {
            "churn_per_sec": round(churn, 3),
            "mean_resident_keys": round(resident, 1),
        })

    # -- rebalance-churn: rebalance spans + routing-table movement
    rebalances = [sp for sp in _spans_in(spans, lo, hi)
                  if sp.get("scope") == "rebalance"
                  or "Rebalance" in str(sp.get("name", ""))]
    rb_rate = _mean(_vals(win("meshRebalances")))
    if rebalances or rb_rate > 0.0:
        attenuated("rebalance-churn",
                   _clip01(len(rebalances) / 3.0 + rb_rate / 1.0), {
                "rebalance_spans": len(rebalances),
                "mesh_rebalances_per_sec": round(rb_rate, 4),
            })

    # -- emission-stall: latency.EmissionStall outlier spans
    stalls = _spans_in(spans, lo, hi, scope="latency", name="EmissionStall")
    if stalls:
        stall_ms = sum(_span_overlap_ms(s, lo, hi) for s in stalls)
        attenuated("emission-stall", len(stalls) / 3.0 + stall_ms / 1000.0, {
            "stalls_in_window": len(stalls),
            "stall_ms": round(stall_ms, 3),
        })

    # -- watermark-stall: lag growing at wall speed means the watermark
    #    froze (slope ~1.0); half wall speed scores 1.0
    lag_pts = win("watermarkLagMs")
    slope, latest_lag = _lag_slope(lag_pts)
    if slope > 0.05:
        attenuated("watermark-stall", slope / 0.5, {
            "lag_slope": round(slope, 4),
            "latest_lag_ms": round(latest_lag, 3),
        })

    # -- throughput-collapse vs the job's own recent baseline
    c_score, c_ev = _rate_collapse(win("numRecordsIn"), lo, hi)
    if c_score > 0.0:
        attenuated("throughput-collapse", c_score, c_ev)

    diagnoses.sort(key=lambda d: d["score"], reverse=True)
    samples = getattr(history, "sample_count", 0)
    watchdog_events = len([sp for sp in spans
                           if sp.get("scope") == HEALTH_SPAN_SCOPE
                           and _span_in_window(sp, lo, hi)])
    if diagnoses and diagnoses[0]["score"] >= VERDICT_THRESHOLD:
        verdict = diagnoses[0]["family"]
        score = diagnoses[0]["score"]
    elif samples > 0:
        verdict, score = "healthy", 0.0
    else:
        verdict, score = "unknown", 0.0
    return {
        "verdict": verdict,
        "score": score,
        "diagnoses": diagnoses,
        "window_ms": window_ms,
        "samples": samples,
        "watchdog_events": watchdog_events,
    }


class HealthWatchdog:
    """Threshold watchdog emitting rate-limited ``health.*`` spans.

    Observes the same history rings the doctor reads, on the same tick
    that samples them. Each breach family emits at most one span per
    `min_gap_ms`; span attrs carry the numbers that crossed the line.
    Never raises — observability must not fail the job."""

    def __init__(self, span_sink: SpanSink, *,
                 min_gap_ms: float = 5000.0,
                 window_ms: float = 30000.0,
                 collapse_ratio: float = 0.5,
                 bp_ratio: float = 0.8,
                 stall_slope: float = 0.5,
                 p99_breach_ms: float = 0.0,
                 clock=time.time):
        self._sink = span_sink
        self.min_gap_ms = float(min_gap_ms)
        self.window_ms = float(window_ms)
        self.collapse_ratio = float(collapse_ratio)
        self.bp_ratio = float(bp_ratio)
        self.stall_slope = float(stall_slope)
        self.p99_breach_ms = float(p99_breach_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_emit: Dict[str, float] = {}
        self.events = 0

    def _emit(self, name: str, now_ms: float,
              attrs: Dict[str, Any]) -> None:
        with self._lock:
            last = self._last_emit.get(name)
            if last is not None and now_ms - last < self.min_gap_ms:
                return
            self._last_emit[name] = now_ms
            self.events += 1
        try:
            self._sink(HEALTH_SPAN_SCOPE, name, now_ms, now_ms, attrs)
        except Exception:
            pass

    def observe(self, history, now_ms: Optional[float] = None) -> None:
        try:
            self._observe_inner(history, now_ms)
        except Exception:
            pass

    def _observe_inner(self, history, now_ms) -> None:
        if now_ms is None:
            now_ms = self._clock() * 1000.0
        w = self.window_ms
        lo = now_ms - w

        # throughput collapse vs the job's own recent baseline
        pts = history.window("numRecordsIn", w, now_ms=now_ms)
        score, ev = _rate_collapse(pts, lo, now_ms)
        if ev and ev["recent_rate"] < self.collapse_ratio * ev["baseline_rate"]:
            self._emit("ThroughputCollapse", now_ms, ev)

        # watermark stall
        slope, latest = _lag_slope(history.window("watermarkLagMs", w,
                                                  now_ms=now_ms))
        if slope >= self.stall_slope:
            self._emit("WatermarkStall", now_ms, {
                "lag_slope": round(slope, 4),
                "latest_lag_ms": round(latest, 3)})

        # backpressure saturation
        bp = _vals(history.window("backPressuredTimeRatio", w,
                                  now_ms=now_ms))
        if bp and _mean(bp) >= self.bp_ratio:
            self._emit("BackpressureSaturation", now_ms, {
                "mean_backpressured_ratio": round(_mean(bp), 4)})

        # emission p99 breach (opt-in: 0 disables)
        if self.p99_breach_ms > 0.0:
            p99 = _vals(history.window("emissionLatencyMs.p99", w,
                                       now_ms=now_ms))
            if p99 and p99[-1] > self.p99_breach_ms:
                self._emit("P99Breach", now_ms, {
                    "p99_ms": round(p99[-1], 3),
                    "breach_ms": self.p99_breach_ms})
