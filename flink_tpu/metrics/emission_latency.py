"""Emission-latency plane: event-time close → host-visible result.

The marker plane (StepRunner.on_marker) measures *pipeline transit* of a
wall-clock stamp; what a serving user feels is different — the delay from
a window's event-time close (`window.end + allowed_lateness`, the instant
the result *could* exist) to the moment its rows are actually resolved on
the host. This module is that quantity as a first-class metric:

- `EmissionHistogram` — an HDR-style log-bucketed histogram (power-of-two
  octaves, 8 sub-buckets each, ≤12.5% relative error). Its snapshot is a
  FLAT numeric dict (`b<idx>` keys carry the buckets), so it survives
  `metrics_snapshot`'s numeric-only filter, ships on TM heartbeats
  unchanged, and merges bucket-wise across mesh shards with exact
  percentile recomputation — unlike the reservoir `Histogram`, whose
  quantiles cannot be folded.
- `EmissionLatencyTracker` — the per-operator recorder. Operators call
  `record_fire(window_end_ms, ...)` exactly where deferred emissions
  resolve (never earlier: stamping a dispatch would measure the wrong
  thing; never via a forced sync: the call sites are already host-side).
  Outliers above a configured percentile land in a bounded ring AND are
  reported as `latency`-scope spans through whatever span sink the
  runtime wired (TraceRegistry on the MiniCluster path, the TM's
  heartbeat span buffer on the distributed path) — which is what makes
  tail attribution work identically everywhere, OTLP export included.
- `stall_attribution` / `build_latency_report` — pure functions that
  join outlier spans against concurrent control-plane spans (checkpoint
  trigger/align, restart/rescale rebuild, rebalance, degrade-replay,
  XLA recompile) by interval overlap: the report behind
  `GET /jobs/:id/latency` and the dashboard panel.

Int64 safety: window ends at the MIN/MAX watermark sentinels (global
windows fire at MAX_WATERMARK; a terminal watermark closes everything)
carry no meaningful event-time close — `record_fire` counts them in
`sentinel` instead of poisoning the histogram with ±2^63 arithmetic.
"""

from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

# -- log-bucketed histogram geometry ------------------------------------

SUBBUCKETS = 8           # per octave; relative error <= 1/8
_OCTAVES = 42            # covers (1ms, 2^42 ms] ~ 139 years
NUM_BUCKETS = 1 + _OCTAVES * SUBBUCKETS
_MAX_MS = float(1 << _OCTAVES)
# event-time sanity band: epoch-ms values far outside it are watermark
# sentinels (MIN_WATERMARK/MAX_WATERMARK are ±2^63-ish), not timestamps
_SANE_EVENT_MS = float(1 << 52)


def bucket_index(value_ms: float) -> int:
    """Bucket of a latency value; <=1ms collapses into bucket 0."""
    v = min(float(value_ms), _MAX_MS)
    if not v > 1.0 or v != v:        # <=1, negative, or NaN
        return 0
    m, e = math.frexp(v)             # v = m * 2^e, m in [0.5, 1)
    octave = e - 1                   # 2^octave <= v < 2^(octave+1)
    sub = min(SUBBUCKETS - 1,
              int((v / float(1 << octave) - 1.0) * SUBBUCKETS))
    return min(NUM_BUCKETS - 1, 1 + octave * SUBBUCKETS + sub)


def bucket_upper(idx: int) -> float:
    """Inclusive upper bound of a bucket — the reported percentile value."""
    if idx <= 0:
        return 1.0
    octave, sub = divmod(idx - 1, SUBBUCKETS)
    return float(1 << octave) * (1.0 + (sub + 1) / SUBBUCKETS)


class EmissionHistogram:
    """Mergeable log-bucketed latency histogram (sparse bucket counts)."""

    __slots__ = ("buckets", "count", "min", "max", "sum")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.min = math.inf
        self.max = 0.0
        self.sum = 0.0

    def record(self, value_ms: float, n: int = 1) -> None:
        if n <= 0:
            return
        v = max(0.0, min(float(value_ms), _MAX_MS))
        idx = bucket_index(v)
        self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.count += n
        self.sum += v * n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def value_at(self, pct: float) -> float:
        """Upper bound of the bucket where the cumulative count crosses
        `pct` percent of the total (0 on an empty histogram)."""
        if self.count == 0:
            return 0.0
        need = max(1, math.ceil(self.count * min(max(pct, 0.0), 100.0)
                                / 100.0))
        seen = 0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if seen >= need:
                # never report past the observed max (top bucket is coarse)
                return min(bucket_upper(idx), self.max) if self.max else 0.0
        return self.max

    def merge(self, other: "EmissionHistogram") -> "EmissionHistogram":
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def snapshot(self) -> Dict[str, float]:
        """Flat numeric dict: survives metrics_snapshot, folds bucket-wise
        (merge_snapshots), renders as a Prometheus summary (has `count`)."""
        out: Dict[str, float] = {
            "count": self.count,
            "min": 0.0 if self.count == 0 else round(self.min, 3),
            "max": round(self.max, 3),
            "mean": 0.0 if self.count == 0 else round(self.sum / self.count, 3),
            "p50": round(self.value_at(50.0), 3),
            "p95": round(self.value_at(95.0), 3),
            "p99": round(self.value_at(99.0), 3),
            "p999": round(self.value_at(99.9), 3),
        }
        for idx in sorted(self.buckets):
            out[f"b{idx}"] = self.buckets[idx]
        return out

    @staticmethod
    def from_snapshot(snap: Dict[str, Any]) -> "EmissionHistogram":
        h = EmissionHistogram()
        for k, n in snap.items():
            if k.startswith("b") and k[1:].isdigit():
                h.buckets[int(k[1:])] = int(n)
        h.count = int(snap.get("count", sum(h.buckets.values())))
        h.min = float(snap.get("min", 0.0)) if h.count else math.inf
        h.max = float(snap.get("max", 0.0))
        h.sum = float(snap.get("mean", 0.0)) * h.count
        return h


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, float]:
    """Bucket-wise fold of shard snapshots — the `aggregate_shard_metrics`
    rule for emission histograms. Associative and commutative: percentiles
    are recomputed from the merged buckets, never averaged."""
    merged = EmissionHistogram()
    for s in snaps:
        if isinstance(s, dict):
            merged.merge(EmissionHistogram.from_snapshot(s))
    return merged.snapshot()


def is_emission_snapshot(d: Dict[str, Any]) -> bool:
    return "count" in d and any(
        k.startswith("b") and k[1:].isdigit() for k in d)


def watermark_lag_ms(current_watermark: Any,
                     now_ms: Optional[float] = None) -> float:
    """Wall clock minus the operator's watermark, int64-safe: before the
    first watermark (MIN sentinel) and past the terminal MAX sentinel the
    lag is reported as 0 — there is nothing to lag behind."""
    try:
        wm = float(current_watermark)
    except (TypeError, ValueError):
        return 0.0
    if not (-_SANE_EVENT_MS < wm < _SANE_EVENT_MS) or wm <= 0:
        return 0.0
    now = time.time() * 1000.0 if now_ms is None else now_ms
    return round(max(0.0, min(now - wm, _MAX_MS)), 3)


# -- per-operator recorder ----------------------------------------------

SpanSink = Callable[[str, str, float, float, Dict[str, Any]], None]

LATENCY_SPAN_SCOPE = "latency"
LATENCY_SPAN_NAME = "EmissionStall"


class EmissionLatencyTracker:
    """Per-operator emission-latency recorder with outlier capture.

    `record_fire` is called at the host-resolve instant of every fired
    window; the call sites are already host-side (after `.resolve()` or
    inside a synchronous fire loop), so recording never adds a device
    sync. Cost with defaults on: one clock read + one dict update per
    fire batch — fires are superbatch-granular, not per-record.
    """

    def __init__(self, operator_uid: str, *,
                 outlier_pct: float = 99.0,
                 outlier_floor_ms: float = 5.0,
                 ring_size: int = 64,
                 min_samples: int = 16,
                 span_sink: Optional[SpanSink] = None,
                 span_min_gap_ms: float = 100.0,
                 clock=time.time) -> None:
        self.operator_uid = operator_uid
        self.histogram = EmissionHistogram()
        self.outlier_pct = float(outlier_pct)
        self.outlier_floor_ms = float(outlier_floor_ms)
        self.min_samples = max(1, int(min_samples))
        self.outliers: List[Dict[str, float]] = []
        self._ring = max(1, int(ring_size))
        self.span_sink = span_sink
        self._span_gap = float(span_min_gap_ms)
        self._last_span_ms = -math.inf
        self._clock = clock
        self._thr = math.inf
        self.sentinel = 0            # fires with no event-time close
        # liveness bound for outlier stall intervals: a stall cannot
        # predate the operator's birth or its previous resolve — without
        # this, synthetic-epoch jobs (event time near 1970) would report
        # stall spans covering all of history and attribution would
        # degenerate to "whichever control span is longest"
        self._last_resolve_ms = clock() * 1000.0

    def record_fire(self, window_end_ms: Any, *, lateness_ms: float = 0,
                    count: int = 1) -> Optional[float]:
        """Record one resolved fire; returns the latency, or None when the
        window end is a watermark sentinel (global/terminal windows)."""
        try:
            end = float(window_end_ms)
        except (TypeError, ValueError):
            return None
        if not (0.0 < end < _SANE_EVENT_MS):
            self.sentinel += max(1, int(count))
            return None
        now = self._clock() * 1000.0
        due = end + float(lateness_ms)
        lat = max(0.0, now - due)
        self.histogram.record(lat, max(1, int(count)))
        # refresh the outlier threshold every 32 fires (value_at walks the
        # sparse buckets; keeping it off the per-fire path keeps the plane
        # under its <2% throughput budget)
        if self.histogram.count & 31 == 0 or self._thr is math.inf:
            self._thr = max(self.histogram.value_at(self.outlier_pct),
                            self.outlier_floor_ms)
        if self.histogram.count >= self.min_samples and lat >= self._thr:
            self._capture_outlier(max(due, self._last_resolve_ms), now, lat)
        self._last_resolve_ms = now
        return lat

    def _capture_outlier(self, due_ms: float, now_ms: float,
                         lat_ms: float) -> None:
        self.outliers.append({
            "resolveWallMs": round(now_ms, 3),
            "latencyMs": round(lat_ms, 3),
        })
        del self.outliers[:-self._ring]
        sink = self.span_sink
        if sink is not None and now_ms - self._last_span_ms >= self._span_gap:
            self._last_span_ms = now_ms
            try:
                sink(LATENCY_SPAN_SCOPE, LATENCY_SPAN_NAME, due_ms, now_ms,
                     {"operator": self.operator_uid,
                      "latencyMs": round(lat_ms, 3)})
            except Exception:
                pass                 # observability must never fail the job

    def snapshot(self) -> Dict[str, float]:
        out = self.histogram.snapshot()
        if self.sentinel:
            out["sentinel"] = self.sentinel
        return out


# -- tail attribution ----------------------------------------------------

def _span_fields(s: Any) -> Tuple[str, str, float, float, Dict[str, Any]]:
    if isinstance(s, dict):
        return (s.get("scope", ""), s.get("name", ""),
                float(s.get("start_ts_ms", 0.0)),
                float(s.get("end_ts_ms", 0.0)),
                dict(s.get("attributes") or {}))
    return (s.scope, s.name, float(s.start_ts_ms), float(s.end_ts_ms),
            dict(s.attributes or {}))


def stall_attribution(spans: List[Any], *,
                      slack_ms: float = 50.0) -> Dict[str, Any]:
    """Join `latency`-scope outlier spans against every concurrent
    control-plane span by interval overlap. The owner of an outlier is
    the control span with the largest overlap of its stall interval
    `[due, resolve]`; outliers no control span touches stay unattributed
    (the stall was the data plane itself: superbatch depth, readback)."""
    outliers, controls = [], []
    for s in spans:
        scope, name, start, end, attrs = _span_fields(s)
        if scope == LATENCY_SPAN_SCOPE:
            outliers.append((start, end, attrs))
        else:
            controls.append((f"{scope}.{name}", start, end))
    attributed: Dict[str, Dict[str, float]] = {}
    unattributed = 0
    for start, end, attrs in outliers:
        best, best_overlap = None, 0.0
        for key, cs, ce in controls:
            overlap = min(end + slack_ms, ce) - max(start - slack_ms, cs)
            if overlap > best_overlap:
                best, best_overlap = key, overlap
        if best is None:
            unattributed += 1
            continue
        blk = attributed.setdefault(best, {"count": 0, "maxLatencyMs": 0.0})
        blk["count"] += 1
        blk["maxLatencyMs"] = max(blk["maxLatencyMs"],
                                  float(attrs.get("latencyMs", 0.0)))
    return {"outliers": len(outliers), "attributed": attributed,
            "unattributed": unattributed}


_EMISSION_SUFFIX = ".emissionLatencyMs"
_LAG_SUFFIX = ".watermarkLagMs"


def build_latency_report(metrics: Dict[str, Any], spans: List[Any], *,
                         slack_ms: float = 50.0) -> Dict[str, Any]:
    """The `GET /jobs/:id/latency` payload, from a flat metric mapping
    (job-level `metrics_snapshot` on the MiniCluster path, the shard-folded
    aggregate on the JM path — both carry the same key shapes) plus the
    job's span log."""
    operators: Dict[str, Dict[str, Any]] = {}
    per_op_snaps: List[Dict[str, Any]] = []
    for name, val in metrics.items():
        if name.endswith(_EMISSION_SUFFIX) and isinstance(val, dict):
            uid = name[:-len(_EMISSION_SUFFIX)].rsplit(".", 1)[-1]
            operators.setdefault(uid, {})["emissionLatencyMs"] = val
            per_op_snaps.append(val)
        elif name.endswith(_LAG_SUFFIX):
            uid = name[:-len(_LAG_SUFFIX)].rsplit(".", 1)[-1]
            try:
                operators.setdefault(uid, {})["watermarkLagMs"] = float(val)
            except (TypeError, ValueError):
                pass
    merged = merge_snapshots(per_op_snaps)
    lags = [op["watermarkLagMs"] for op in operators.values()
            if "watermarkLagMs" in op]
    return {
        "operators": operators,
        "emission": {k: v for k, v in merged.items()
                     if not k.startswith("b")},
        "p50_ms": merged.get("p50", 0.0),
        "p99_ms": merged.get("p99", 0.0),
        "p999_ms": merged.get("p999", 0.0),
        "samples": merged.get("count", 0),
        "watermarkLagMs": max(lags) if lags else 0.0,
        "latency_mode": _latency_mode_block(metrics),
        "attribution": stall_attribution(spans, slack_ms=slack_ms),
    }


#: the latency-mode controller gauge family the report folds — the same
#: leaves cluster._LATENCY_CONTROLLER_GAUGES MAX-folds across shards
_CONTROLLER_LEAVES = ("latencyModeActive", "currentBatchRung",
                      "inflightDepth", "ladderRecompiles")


def _latency_mode_block(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Controller decisions in the /jobs/:id/latency report: worst-shard
    (MAX) fold of the execution.latency.* gauges. `active` False with all
    zeros when the mode is off — the report shape never changes with the
    flag, only the values."""
    folded = {leaf: 0 for leaf in _CONTROLLER_LEAVES}
    for name, val in metrics.items():
        leaf = name.rsplit(".", 1)[-1]
        if leaf in folded:
            try:
                folded[leaf] = max(folded[leaf], int(val))
            except (TypeError, ValueError):
                pass
    return {
        "active": bool(folded["latencyModeActive"]),
        "currentBatchRung": folded["currentBatchRung"],
        "inflightDepth": folded["inflightDepth"],
        "ladderRecompiles": folded["ladderRecompiles"],
    }
