"""Per-channel exchange I/O metrics registration.

The reference's TaskIOMetricGroup registers numBytesIn/numBytesOut counters
plus *PerSecond meters per task and per channel
(TaskIOMetricGroup.java:48, ResultPartitionMetrics). The dataplane
channels (runtime/dataplane.py) maintain the raw byte counters and rate
meters themselves — this helper binds them into a MetricGroup under the
conventional names, one call per channel end, used by both cluster
execution paths (staged graph tasks and the keyed shard loop)."""

from __future__ import annotations

from typing import Any, Optional

from flink_tpu.metrics.registry import MetricGroup


def register_channel_metrics(
    group: MetricGroup,
    name: str,
    *,
    inbound: Optional[Any] = None,
    outbound: Optional[Any] = None,
) -> None:
    """Register numBytesIn/numBytesOut (+ *PerSecond) gauges for one
    exchange channel end. `inbound` is an InputChannel (bytes received off
    the wire, incl. frame overhead), `outbound` an OutputChannel (bytes
    written, incl. control frames on the channel's socket)."""
    if inbound is not None:
        group.gauge(f"numBytesIn.{name}", lambda ch=inbound: ch.bytes_in,
                    fold="sum", kind="counter")
        group.gauge(f"numBytesInPerSecond.{name}", inbound.in_rate,
                    fold="sum")
    if outbound is not None:
        group.gauge(f"numBytesOut.{name}", lambda ch=outbound: ch.bytes_out,
                    fold="sum", kind="counter")
        group.gauge(f"numBytesOutPerSecond.{name}", outbound.out_rate,
                    fold="sum")
