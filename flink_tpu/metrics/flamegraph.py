"""On-demand flame graphs from thread stack sampling (O4 analogue).

The reference samples task-thread stacks JM-side on request
(runtime/webmonitor/threadinfo/ThreadInfoRequestCoordinator.java,
taskexecutor/ThreadInfoSampleService.java) and folds them into a per-vertex
flame graph (VertexFlameGraphFactory.java) served over REST
(JobVertexFlameGraphHandler.java). Here the sampler walks
`sys._current_frames()` — every live thread of the process, including task
step loops and RPC mains — at a fixed rate and folds the samples into the
same collapsed-stack tree the web UI renders.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional


def sample_stacks(duration_s: float = 0.5, hz: float = 50.0,
                  thread_filter: Optional[str] = None) -> Dict[str, int]:
    """Collect folded stacks: {'frameA;frameB;frameC': count}."""
    folded: Dict[str, int] = {}
    names = {t.ident: t.name for t in threading.enumerate()}
    deadline = time.monotonic() + duration_s
    interval = 1.0 / hz
    me = threading.get_ident()
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            name = names.get(ident, str(ident))
            if thread_filter and thread_filter not in name:
                continue
            stack: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            key = name + ";" + ";".join(reversed(stack))
            folded[key] = folded.get(key, 0) + 1
        time.sleep(interval)
    return folded


def fold_to_tree(folded: Dict[str, int]) -> dict:
    """Collapsed stacks -> the nested {name, value, children} flame-graph
    tree shape the dashboard consumes (VertexFlameGraphFactory output)."""
    root = {"name": "root", "value": 0, "children": {}}
    for stack, count in folded.items():
        root["value"] += count
        node = root
        for frame in stack.split(";"):
            child = node["children"].get(frame)
            if child is None:
                child = {"name": frame, "value": 0, "children": {}}
                node["children"][frame] = child
            child["value"] += count
            node = child

    def finish(node: dict) -> dict:
        return {
            "name": node["name"],
            "value": node["value"],
            "children": [finish(c) for c in node["children"].values()],
        }

    return finish(root)


def flame_graph(duration_s: float = 0.5, hz: float = 50.0,
                thread_filter: Optional[str] = None) -> dict:
    """One-call REST payload: {samples, tree, folded}."""
    folded = sample_stacks(duration_s, hz, thread_filter)
    return {
        "samples": sum(folded.values()),
        "duration_s": duration_s,
        "tree": fold_to_tree(folded),
        "folded": folded,
    }
