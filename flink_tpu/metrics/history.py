"""Bounded ring time-series store for job/operator metrics (ISSUE-19).

Every observability plane before this one reported the *instant* or the
*lifetime*; the history plane retains the trajectory. A `MetricHistory`
samples a plain-data metric snapshot (the `metrics_snapshot` form) on the
caller's existing processing-time tick and keeps, per metric key, a
bounded deque of ``(t_ms, value)`` points:

- **counters** (kind ``"counter"`` — monotone totals, including gauges
  registered with ``kind="counter"``) are recorded as windowed *rates*
  (delta / dt, clamped at 0 so a restore rewind reads as a stall, which
  is exactly the signal the throughput-collapse watchdog keys on), with
  the recorded kind ``"counter-rate"``;
- **gauges/meters** are recorded as-is;
- **histogram-stats dicts** (emission-latency snapshots and reservoir
  stats alike) are recorded as derived per-sample sub-series
  ``<key>.p50`` / ``<key>.p99`` (plus ``<key>.count`` as a counter-rate,
  so fire *rates* are visible too).

The store is execution-path agnostic: the MiniCluster samples the
client's folded registry view; the distributed JobManager samples the
shard-folded snapshots it already assembles from heartbeats. Both serve
the same payload at ``GET /jobs/:id/history?metric=&since=``.

This module imports neither jax nor the runtime (ARCH001/DEV003): it
consumes snapshots handed to it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["MetricHistory", "DEFAULT_INTERVAL_MS", "DEFAULT_RETENTION"]

DEFAULT_INTERVAL_MS = 1000
DEFAULT_RETENTION = 256

# histogram-stats sub-series the ring derives (per-sample quantiles; the
# count rides along as a rate so "fires per second" is also a series)
_HIST_STATS = ("p50", "p99")


def _now_ms(clock) -> float:
    return clock() * 1000.0


class MetricHistory:
    """Per-key bounded rings of ``(t_ms, value)`` sampled on a tick.

    Thread-safe: the sampling tick (job thread / JM schedule loop) writes
    while REST handlers read. Sampling is self-timed — ``sample_time_ms``
    accumulates wall time spent inside ``sample()`` so the bench can
    stamp ``health.sampler_overhead_pct`` from measurements, not claims.
    """

    def __init__(self, interval_ms: int = DEFAULT_INTERVAL_MS,
                 retention_points: int = DEFAULT_RETENTION,
                 clock=time.time):
        self.interval_ms = max(1, int(interval_ms))
        self.retention_points = max(2, int(retention_points))
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[str, deque] = {}
        self._kinds: Dict[str, str] = {}
        # (t_ms, total) of the previous sample per counter key — the rate
        # window is sample-to-sample, so it tracks the configured interval
        self._last_totals: Dict[str, Tuple[float, float]] = {}
        self._last_sample_ms: Optional[float] = None
        self.sample_count = 0
        self.sample_time_ms = 0.0

    # -- sampling ----------------------------------------------------------

    def due(self, now_ms: Optional[float] = None) -> bool:
        """Cheap gate for the caller's tick — no lock, no allocation."""
        if now_ms is None:
            now_ms = _now_ms(self._clock)
        last = self._last_sample_ms
        return last is None or (now_ms - last) >= self.interval_ms

    def sample(self, snapshot: Dict[str, Any],
               kinds: Optional[Dict[str, str]] = None,
               now_ms: Optional[float] = None) -> None:
        """Record one point per metric in `snapshot`.

        `snapshot` is the plain-data `metrics_snapshot` form; its reserved
        ``__kinds__`` entry (when present) supplies sampling kinds, merged
        under any explicit `kinds` argument. Unknown keys default to
        gauge semantics. Never raises — observability must not fail the
        job."""
        t0 = time.perf_counter()
        try:
            self._sample_inner(snapshot, kinds, now_ms)
        except Exception:
            pass
        finally:
            self.sample_time_ms += (time.perf_counter() - t0) * 1000.0
            self.sample_count += 1

    def _sample_inner(self, snapshot, kinds, now_ms) -> None:
        if now_ms is None:
            now_ms = _now_ms(self._clock)
        merged_kinds: Dict[str, str] = {}
        embedded = snapshot.get("__kinds__")
        if isinstance(embedded, dict):
            merged_kinds.update(embedded)
        if kinds:
            merged_kinds.update(kinds)
        with self._lock:
            self._last_sample_ms = now_ms
            for key, val in snapshot.items():
                if key.startswith("__"):
                    continue
                kind = merged_kinds.get(key, "gauge")
                if isinstance(val, dict):
                    self._record_hist(key, val, now_ms)
                elif isinstance(val, (int, float)) \
                        and not isinstance(val, bool):
                    if kind == "counter":
                        self._record_rate(key, float(val), now_ms)
                    else:
                        self._record(key, float(val), now_ms, kind)

    def _record(self, key: str, value: float, t_ms: float,
                kind: str) -> None:
        ring = self._series.get(key)
        if ring is None:
            ring = self._series[key] = deque(maxlen=self.retention_points)
            self._kinds[key] = kind
        ring.append((t_ms, value))

    def _record_rate(self, key: str, total: float, t_ms: float) -> None:
        prev = self._last_totals.get(key)
        self._last_totals[key] = (t_ms, total)
        if prev is None:
            return                      # first sight: no window yet
        prev_t, prev_total = prev
        dt_s = (t_ms - prev_t) / 1000.0
        if dt_s <= 0:
            return
        # clamp: a counter rewind (restore from checkpoint) reads as rate
        # 0 — a visible stall, not a nonsense negative rate
        rate = max(0.0, total - prev_total) / dt_s
        self._record(key, rate, t_ms, "counter-rate")

    def _record_hist(self, key: str, stats: Dict[str, Any],
                     t_ms: float) -> None:
        if not any(s in stats for s in _HIST_STATS):
            return                      # not histogram-shaped (e.g. a map)
        for stat in _HIST_STATS:
            v = stats.get(stat)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v == v:         # NaN-safe
                self._record(f"{key}.{stat}", float(v), t_ms, "gauge")
        cnt = stats.get("count")
        if isinstance(cnt, (int, float)) and not isinstance(cnt, bool):
            self._record_rate(f"{key}.count", float(cnt), t_ms)

    # -- reads -------------------------------------------------------------

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def snapshot_series(self, since_ms: Optional[float] = None
                        ) -> Dict[str, List[Tuple[float, float]]]:
        """Plain copy of every ring — the doctor's input (it never holds
        the lock or the live store)."""
        with self._lock:
            out = {}
            for key, ring in self._series.items():
                pts = list(ring)
                if since_ms is not None:
                    pts = [p for p in pts if p[0] >= since_ms]
                if pts:
                    out[key] = pts
            return out

    def window(self, suffix: str, window_ms: float,
               now_ms: Optional[float] = None) -> List[Tuple[float, float]]:
        """All points within the last `window_ms` across every key that
        ends with `suffix` (operator scopes prefix the family name), time
        ordered."""
        if now_ms is None:
            now_ms = _now_ms(self._clock)
        cutoff = now_ms - window_ms
        with self._lock:
            pts = [p for key, ring in self._series.items()
                   if key.endswith(suffix)
                   for p in ring if p[0] >= cutoff]
        pts.sort(key=lambda p: p[0])
        return pts

    def payload(self, metric: Optional[str] = None,
                since_ms: Optional[float] = None) -> Dict[str, Any]:
        """REST shape for ``GET /jobs/:id/history?metric=&since=``.

        `metric` filters to keys equal to, suffixed by, or containing the
        string; `since_ms` drops points older than the epoch-ms bound."""
        with self._lock:
            series = {}
            for key, ring in sorted(self._series.items()):
                if metric and not (key == metric or key.endswith(metric)
                                   or metric in key):
                    continue
                pts = list(ring)
                if since_ms is not None:
                    pts = [p for p in pts if p[0] >= since_ms]
                series[key] = {
                    "kind": self._kinds.get(key, "gauge"),
                    "points": [[round(t, 3), v] for t, v in pts],
                }
            return {
                "interval_ms": self.interval_ms,
                "retention_points": self.retention_points,
                "sample_count": self.sample_count,
                "sample_time_ms": round(self.sample_time_ms, 3),
                "series": series,
            }
