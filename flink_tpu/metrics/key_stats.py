"""Key-space telemetry: per-key-group load, hot keys, and skew, folded on
device.

The telemetry ROADMAP item 5 (million-key tiered state) and the multichip
shard placement of item 1 both need as input: WHERE the keyed load sits.
The window operators already hold per-(key, slice) record counts resident
in HBM, so the whole fold is one device segment-sum over data already
there — per-key loads, a contiguous-range key-group histogram (the same
``kid * G // K`` ranges the sharded superscan partitions by), top-K hot
keys, and a skew coefficient:

    skew = max key-group load / mean key-group load

1.0 is a perfectly even key space; G (the key-group count) is one group
owning everything. The autoscaler consumes the job-level gauge as an
optional signal (scheduler/signals.py — absent reads as None, never 0.0).

Collection is PULL-based and throttled: ``maybe_collect`` costs one clock
read when the interval has not elapsed (the O(1)-host-work contract for
per-batch callers); a due collection runs the jitted fold and reads back a
few KB (the [G] histogram + top-K + scalars), never the [K] column.

Layering: metrics sits below the runtime. The operator hands in a
``loads_fn`` returning its device-resident per-key count column; jax is
only imported lazily inside the fold builder, so control-plane processes
importing this module never initialize a backend.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np


@functools.lru_cache(maxsize=None)
def _fold_fn(K: int, G: int, top_k: int):
    """Jitted device fold: per-key loads [K] -> ONE packed int32 vector
    [per-group histogram [G] | per-group active-key counts [G] |
    top-K values | top-K ids | total | max]. A single output array means a
    single device->host transfer per collection — the fold must not stall
    the deferred dispatch pipeline six times for six tiny reads.
    Key-group of dense key id: ``kid * G // K`` — the contiguous ranges
    the sharded superscan and key_group_range_for_operator partition by."""
    import jax
    import jax.numpy as jnp

    gids = jnp.asarray((np.arange(K, dtype=np.int64) * G) // K, jnp.int32)

    @jax.jit
    def fold(loads):
        # int32 throughout: these are RESIDENT record counts (the window
        # ring purges as the watermark advances), not lifetime counters —
        # x64-off jax would silently truncate an int64 request anyway
        loads = loads.astype(jnp.int32)
        per_group = jnp.zeros((G,), jnp.int32).at[gids].add(loads)
        active = jnp.zeros((G,), jnp.int32).at[gids].add(
            (loads > 0).astype(jnp.int32))
        top_v, top_i = jax.lax.top_k(loads, top_k)
        return jnp.concatenate([
            per_group, active, top_v, top_i,
            jnp.stack([loads.sum(), loads.max()]),
        ])

    return fold


def _stats(arr: np.ndarray) -> Dict[str, float]:
    """min/max/mean/percentile summary of a small host array (the [G]
    histogram) in the registry's histogram-stats dict shape, so the gauge
    ships over metrics_snapshot and renders as a Prometheus summary."""
    if arr.size == 0:
        return {"count": 0}
    s = np.sort(arr)
    return {
        "count": int(arr.size),
        "min": float(s[0]),
        "max": float(s[-1]),
        "mean": float(s.mean()),
        "p50": float(s[arr.size // 2]),
        "p95": float(s[min(int(0.95 * arr.size), arr.size - 1)]),
        "p99": float(s[min(int(0.99 * arr.size), arr.size - 1)]),
    }


class KeyStatsCollector:
    """Throttled device-fold collector for one keyed window operator."""

    def __init__(self, loads_fn: Callable[[], Any], *,
                 num_key_groups: int = 128, top_k: int = 8,
                 row_bytes_fn: Optional[Callable[[], int]] = None,
                 ready_fn: Optional[Callable[[], bool]] = None,
                 interval_ms: int = 1000,
                 clock: Callable[[], float] = time.monotonic,
                 mesh_loads_fn: Optional[Callable[[], Any]] = None):
        self._loads_fn = loads_fn
        # multichip (parallel/sharded_superscan.py): [n, K_local] per-device
        # local loads. The GLOBAL histogram cannot see device imbalance —
        # contiguous key ranges mean one device can own every hot key-group
        # while the global skew reads even per-group — so the mesh fold
        # keeps per-device load/skew and the scalar gauges take the MAX
        # across devices (never device 0's view)
        self._mesh_loads_fn = mesh_loads_fn
        self.num_key_groups = max(int(num_key_groups), 1)
        self.top_k = max(int(top_k), 1)
        self._row_bytes_fn = row_bytes_fn
        # O(1) host probe for "device state holds data": a fused operator
        # buffers steps host-side until its first superbatch dispatch, and
        # a fold before that would burn the whole interval reading an
        # empty ring (a short job would then finish with no skew
        # measurement at all). None = always ready (per-batch-ingest
        # operators fill state immediately).
        self._ready_fn = ready_fn
        self.interval_s = max(int(interval_ms), 0) / 1000.0
        self._clock = clock
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        # latest fold results (host scalars / small arrays)
        self._skew: Optional[float] = None
        self._total = 0
        self._max = 0
        self._active_keys = 0
        self._hot: List[List[int]] = []          # [[kid, count], ...]
        self._group_load: Dict[str, float] = {"count": 0}
        self._group_state_bytes: Dict[str, float] = {"count": 0}
        # per-mesh-device view: [{device, records, activeKeys, keySkew}]
        self._per_device: List[Dict[str, float]] = []
        self._mesh_load_skew: Optional[float] = None

    # -- collection --------------------------------------------------------
    def maybe_collect(self, now: Optional[float] = None) -> bool:
        """Run the fold when state is resident and the interval elapsed;
        O(1) host work otherwise (one readiness bool + one clock read)."""
        if self._ready_fn is not None:
            try:
                if not self._ready_fn():
                    return False
            except Exception:  # noqa: BLE001
                return False
        now = self._clock() if now is None else now
        if self._last_t is not None and now - self._last_t < self.interval_s:
            return False
        self._last_t = now
        return self.collect()

    def collect(self) -> bool:
        """One device fold + tiny host readback; safe anytime (reads the
        operator's immutable-per-step device arrays)."""
        try:
            loads = self._loads_fn()
        except Exception:  # noqa: BLE001 — a torn-down operator must not
            return False   # fail the sampling tick
        if loads is None:
            return False
        K = int(loads.shape[0])
        if K == 0:
            return False
        G = min(self.num_key_groups, K)
        k = min(self.top_k, K)
        try:
            packed = np.asarray(_fold_fn(K, G, k)(loads))
            per_group = packed[:G]
            active = packed[G:2 * G]
            top_v = packed[2 * G:2 * G + k]
            top_i = packed[2 * G + k:2 * G + 2 * k]
            total = int(packed[-2])
            mx = int(packed[-1])
        except Exception:  # noqa: BLE001 — observability never fails the job
            return False
        row_bytes = 0
        if self._row_bytes_fn is not None:
            try:
                row_bytes = int(self._row_bytes_fn())
            except Exception:  # noqa: BLE001
                row_bytes = 0
        per_device, mesh_load_skew = self._collect_per_device()
        mean_group = total / G
        with self._lock:
            self._total = total
            self._max = mx
            self._active_keys = int(active.sum())
            self._skew = (float(per_group.max()) / mean_group
                          if total > 0 else None)
            self._hot = [[int(i), int(v)] for i, v in zip(top_i, top_v)
                         if v > 0]
            self._group_load = _stats(per_group)
            self._group_state_bytes = _stats(
                active.astype(np.int64) * row_bytes)
            self._per_device = per_device
            self._mesh_load_skew = mesh_load_skew
        return True

    def _collect_per_device(self):
        """Mesh fold: one [n, K_local] readback -> per-device resident
        records, active keys, and the worst GLOBAL key-group load among
        the groups the device's key range intersects (against the global
        mean group load). Attributing the FULL global group load — not
        just the device's partial slice — keeps max-over-devices equal to
        the global skew even when a group straddles a device boundary
        (non-pow2 capacities after growth), so the scalar gauges stay
        path-independent. Returns ([], None) off the mesh."""
        if self._mesh_loads_fn is None:
            return [], None
        try:
            mloads = self._mesh_loads_fn()
        except Exception:  # noqa: BLE001 — observability never fails the job
            return [], None
        if mloads is None:
            return [], None
        m = np.asarray(mloads)
        if m.ndim != 2 or m.shape[0] < 2:
            return [], None
        n_dev, kl = m.shape
        k_total = n_dev * kl
        g = min(self.num_key_groups, k_total)
        gids = (np.arange(k_total, dtype=np.int64) * g) // k_total
        total = int(m.sum())
        mean_group = total / g if g else 0.0
        grp = np.zeros(g, np.int64)
        np.add.at(grp, gids, m.reshape(-1).astype(np.int64))
        per_device: List[Dict[str, Any]] = []
        for d in range(n_dev):
            loads_d = m[d].astype(np.int64)
            owned = grp[np.unique(gids[d * kl:(d + 1) * kl])]
            per_device.append({
                "device": d,
                "records": int(loads_d.sum()),
                "activeKeys": int((loads_d > 0).sum()),
                "hotKeyLoad": int(loads_d.max()) if kl else 0,
                "keySkew": (round(float(owned.max()) / mean_group, 4)
                            if mean_group > 0 and owned.size else None),
            })
        mesh_load_skew = None
        if total > 0:
            mean_dev = total / n_dev
            mesh_load_skew = round(
                max(e["records"] for e in per_device) / mean_dev, 4)
        return per_device, mesh_load_skew

    # -- gauges ------------------------------------------------------------
    def skew(self) -> Optional[float]:
        """max/mean key-group load; None until data has been folded (an
        absent gauge must read as absent downstream, never as 0 skew)."""
        with self._lock:
            return None if self._skew is None else round(self._skew, 4)

    def active_keys(self) -> int:
        with self._lock:
            return self._active_keys

    def hot_keys(self) -> List[List[int]]:
        with self._lock:
            return [list(e) for e in self._hot]

    def hot_key_load(self) -> int:
        """Resident record count of the hottest key (locked: collect()
        reassigns the list wholesale from the task thread)."""
        with self._lock:
            return self._hot[0][1] if self._hot else 0

    def mesh_load_skew(self) -> Optional[float]:
        """max/mean per-device resident records across the mesh (1.0 even,
        n = one device owns everything); None off the mesh or pre-fold."""
        with self._lock:
            return self._mesh_load_skew

    def per_device(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._per_device]

    def _per_device_map(self, field: str) -> Dict[str, float]:
        with self._lock:
            return {str(e["device"]): e[field] for e in self._per_device
                    if e.get(field) is not None}

    def register(self, group) -> None:
        # skew/hot-key gauges fold MAX: the job's skew is its worst shard
        group.gauge("keySkew", self.skew, fold="max")
        group.gauge("activeKeys", self.active_keys, fold="sum")
        group.gauge("hotKeyLoad", self.hot_key_load, fold="max")
        # histogram-stats-shaped dict gauges: ship on metrics_snapshot and
        # render as Prometheus summaries, like shipped histograms do
        # (fold "hist": the generic approx stats envelope)
        group.gauge("keyGroupLoad", lambda: dict(self._group_load),
                    fold="hist")
        group.gauge("keyGroupStateBytes",
                    lambda: dict(self._group_state_bytes),
                    fold="hist")
        if self._mesh_loads_fn is not None:
            # per-mesh-device maps ({device: value}): declared
            # "per-device-max" so the JM's aggregate_shard_metrics folds
            # MAX across the shard's own devices FIRST (an imbalanced mesh
            # must be visible as its WORST device, never device 0's view)
            group.gauge("meshLoadSkew", self.mesh_load_skew, fold="max")
            group.gauge("meshDeviceLoad",
                        lambda: self._per_device_map("records"),
                        fold="per-device-max")
            group.gauge("keySkewPerDevice",
                        lambda: self._per_device_map("keySkew"),
                        fold="per-device-max")
            group.gauge("hotKeyLoadPerDevice",
                        lambda: self._per_device_map("hotKeyLoad"),
                        fold="per-device-max")

    # -- exposure ----------------------------------------------------------
    def payload(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "keySkew": (None if self._skew is None
                            else round(self._skew, 4)),
                "activeKeys": self._active_keys,
                "totalRecordsResident": self._total,
                "maxKeyLoad": self._max,
                "numKeyGroups": self.num_key_groups,
                "hotKeys": [list(e) for e in self._hot],
                "keyGroupLoad": dict(self._group_load),
                "keyGroupStateBytes": dict(self._group_state_bytes),
                "perDevice": [dict(e) for e in self._per_device],
                "meshLoadSkew": self._mesh_load_skew,
            }
