"""OTel-shape trace export (OTLP/JSON).

The reference ships spans to OpenTelemetry through
flink-metrics/flink-metrics-otel (OpenTelemetryTraceReporter.java); here the
same reporter SPI (`TraceReporter`) encodes spans into OTLP/JSON —
`resourceSpans -> scopeSpans -> spans` with nanosecond timestamps and typed
attribute values — so any OTLP/HTTP collector or file-based pipeline can
ingest them. No network dependency: the reporter buffers and can flush to a
file; the REST server serves the same payload at /jobs/<id>/traces.
"""

from __future__ import annotations

import json
import secrets
import threading
from typing import Any, Dict, List, Optional

from flink_tpu.metrics.traces import Span, TraceReporter


def _attr_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}   # OTLP/JSON encodes int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def span_to_otlp(span: Span, trace_id: Optional[str] = None) -> Dict[str, Any]:
    """One Span -> OTLP/JSON span object (hex ids, unix-nano timestamps).
    Precedence for the trace id: explicit argument > the span's own
    correlation id (traces.job_trace_id propagation) > a fresh random id."""
    return {
        "traceId": trace_id or getattr(span, "trace_id", None) or secrets.token_hex(16),
        "spanId": secrets.token_hex(8),
        "name": f"{span.scope}.{span.name}",
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(span.start_ts_ms * 1e6)),
        "endTimeUnixNano": str(int(span.end_ts_ms * 1e6)),
        "attributes": [
            {"key": str(k), "value": _attr_value(v)}
            for k, v in span.attributes.items()
        ],
        "status": {},
    }


def spans_to_otlp(spans: List[Dict[str, Any]], service_name: str) -> Dict[str, Any]:
    """Wrap encoded spans in the OTLP resourceSpans envelope."""
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": service_name}},
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "flink_tpu", "version": "1"},
                        "spans": spans,
                    }
                ],
            }
        ]
    }


class OtlpJsonTraceReporter(TraceReporter):
    """Buffers spans in OTLP/JSON form; optionally appends one OTLP export
    envelope per span batch to a file (`path`). `payload()` returns the full
    resourceSpans document for the REST endpoint / an OTLP-HTTP pusher."""

    def __init__(self, service_name: str = "flink-tpu",
                 path: Optional[str] = None, max_spans: int = 4096):
        self.service_name = service_name
        self.path = path
        self.max_spans = max_spans
        self._spans: List[Dict[str, Any]] = []
        self._fh = None
        self._lock = threading.Lock()

    def report_span(self, span: Span) -> None:
        enc = span_to_otlp(span)
        with self._lock:
            self._spans.append(enc)
            if len(self._spans) > self.max_spans:
                self._spans = self._spans[-self.max_spans:]
            if self.path:
                # buffer append + file write under ONE lock acquisition so
                # the flushed file order always matches payload(); the
                # handle is kept open across spans
                if self._fh is None:
                    self._fh = open(self.path, "a")
                self._fh.write(
                    json.dumps(spans_to_otlp([enc], self.service_name)) + "\n")
                self._fh.flush()

    def payload(self) -> Dict[str, Any]:
        with self._lock:
            return spans_to_otlp(list(self._spans), self.service_name)

    def clear(self) -> None:
        with self._lock:
            self._spans = []
