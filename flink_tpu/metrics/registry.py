"""Metric types, hierarchical groups, registry, reporters.

Capability parity with the reference metrics stack (flink-metrics-core
MetricGroup.java:37, runtime/metrics/MetricRegistryImpl.java:74, reporter
modules under flink-metrics/*): Counter/Gauge/Meter/Histogram registered in
scoped groups (job → task → operator), reported by pluggable reporters —
Prometheus text exposition, logging, and an in-memory reporter for tests.
The built-in runtime gauges (records in/out, busy/ingest time, watermark
lag; TaskIOMetricGroup.java:48 analogue) are registered by the executor.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class Counter:
    def __init__(self):
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def count(self) -> int:
        return self._value

    def value(self):
        return self._value


class Gauge:
    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    def value(self):
        return self._fn()


class Meter:
    """Rate over a sliding 60s window + lifetime count (MeterView analogue)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._events = deque()  # (t, n)
        self._count = 0

    def mark(self, n: int = 1) -> None:
        now = self._clock()
        self._events.append((now, n))
        self._count += n
        self._trim(now)

    def _trim(self, now: float) -> None:
        while self._events and now - self._events[0][0] > 60.0:
            self._events.popleft()

    @property
    def count(self) -> int:
        return self._count

    def rate(self) -> float:
        now = self._clock()
        self._trim(now)
        if not self._events:
            return 0.0
        span = max(now - self._events[0][0], 1e-9)
        return sum(n for _, n in self._events) / span

    def value(self):
        return self.rate()


class Histogram:
    """Reservoir histogram with quantiles (DescriptiveStatisticsHistogram
    analogue; bounded ring reservoir)."""

    def __init__(self, size: int = 1024):
        self._values = deque(maxlen=size)
        self._count = 0

    def update(self, value: float) -> None:
        self._values.append(float(value))
        self._count += 1

    @property
    def count(self) -> int:
        return self._count

    def quantile(self, q: float) -> float:
        if not self._values:
            return float("nan")
        vals = sorted(self._values)
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    def stats(self) -> Dict[str, float]:
        if not self._values:
            return {"count": 0}
        vals = sorted(self._values)
        return {
            "count": self._count,
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2],
            "p95": vals[min(int(0.95 * len(vals)), len(vals) - 1)],
            "p99": vals[min(int(0.99 * len(vals)), len(vals) - 1)],
        }

    def value(self):
        return self.stats()


class MetricGroup:
    """Hierarchical scope (job.task.operator...) registering named metrics."""

    def __init__(self, registry: "MetricRegistry", scope: tuple):
        self._registry = registry
        self.scope = scope

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self.scope + (name,))

    def counter(self, name: str) -> Counter:
        return self._registry._register(self.scope, name, Counter())

    def gauge(self, name: str, fn: Callable[[], Any]) -> Gauge:
        return self._registry._register(self.scope, name, Gauge(fn))

    def meter(self, name: str) -> Meter:
        return self._registry._register(self.scope, name, Meter())

    def histogram(self, name: str, size: int = 1024) -> Histogram:
        return self._registry._register(self.scope, name, Histogram(size))

    def metric_identifier(self, name: str) -> str:
        return ".".join(self.scope + (name,))


class MetricRegistry:
    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._reporters: List["Reporter"] = []

    def group(self, *scope: str) -> MetricGroup:
        return MetricGroup(self, tuple(scope))

    def _register(self, scope: tuple, name: str, metric):
        key = ".".join(scope + (name,))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None and type(existing) is type(metric):
                return existing
            self._metrics[key] = metric
        return metric

    def all_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._metrics)

    def add_reporter(self, reporter: "Reporter") -> None:
        self._reporters.append(reporter)

    def report(self) -> None:
        snapshot = self.all_metrics()
        for r in self._reporters:
            r.report(snapshot)


class Reporter:
    def report(self, metrics: Dict[str, Any]) -> None:
        raise NotImplementedError


class InMemoryReporter(Reporter):
    def __init__(self):
        self.last: Dict[str, Any] = {}

    def report(self, metrics: Dict[str, Any]) -> None:
        self.last = {k: m.value() for k, m in metrics.items()}


class LoggingReporter(Reporter):
    def __init__(self, logger=None):
        import logging

        self._log = logger or logging.getLogger("flink_tpu.metrics")

    def report(self, metrics: Dict[str, Any]) -> None:
        for k, m in sorted(metrics.items()):
            self._log.info("%s = %s", k, m.value())


def prometheus_text(metrics: Dict[str, Any]) -> str:
    """Prometheus text exposition format (flink-metrics-prometheus
    PrometheusReporter analogue — here as an encoding; the REST server
    exposes it at /metrics)."""

    def sanitize(name: str) -> str:
        return "".join(c if c.isalnum() or c == "_" else "_" for c in name)

    lines = []
    for key, metric in sorted(metrics.items()):
        name = sanitize(key)
        val = metric.value()
        if isinstance(metric, Histogram):
            for stat, v in val.items():
                if not (isinstance(v, float) and math.isnan(v)):
                    lines.append(f'{name}{{stat="{stat}"}} {v}')
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            lines.append(f"{name} {val}")
    return "\n".join(lines) + "\n"


class PrometheusReporter(Reporter):
    """Holds the latest exposition text; served by the REST endpoint."""

    def __init__(self):
        self.text = ""

    def report(self, metrics: Dict[str, Any]) -> None:
        self.text = prometheus_text(metrics)
