"""Metric types, hierarchical groups, registry, reporters.

Capability parity with the reference metrics stack (flink-metrics-core
MetricGroup.java:37, runtime/metrics/MetricRegistryImpl.java:74, reporter
modules under flink-metrics/*): Counter/Gauge/Meter/Histogram registered in
scoped groups (job → task → operator), reported by pluggable reporters —
Prometheus text exposition, logging, and an in-memory reporter for tests.
The built-in runtime gauges (records in/out, busy/ingest time, watermark
lag; TaskIOMetricGroup.java:48 analogue) are registered by the executor.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


# Shard-fold vocabulary (consumed by runtime.cluster.aggregate_shard_metrics
# and declared at registration, never inferred from the metric name):
#   "sum"            add across shards (totals, counts, rates-of-totals)
#   "min"            min across shards (progress frontiers: currentWatermark)
#   "max"            max across shards (high-water marks, versions, worst-case)
#   "mean"           arithmetic mean (ratios, utilization percentages)
#   "emission"       emission-latency snapshot dict -> exact bucket-wise merge
#   "per-device-max" {device: value} dict -> max over devices, max over shards
#   "hist"           histogram stats dict -> approximate envelope fold
#                    (count sums, min mins, max/mean/quantiles max — marked
#                    "approx": true in the folded payload)
FOLD_KINDS = ("sum", "min", "max", "mean", "emission", "per-device-max",
              "hist")

# Sampling-kind vocabulary (consumed by metrics.history.MetricHistory):
#   "counter"    monotone total — history records the windowed RATE per sec
#   "gauge"      point-in-time value — recorded as-is
#   "meter"      already a rate — recorded as-is
#   "histogram"  stats dict — history derives p50/p99 sub-series
METRIC_KINDS = ("counter", "gauge", "meter", "histogram")


class Counter:
    fold = "sum"
    kind = "counter"

    def __init__(self):
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def count(self) -> int:
        return self._value

    def value(self):
        return self._value


class Gauge:
    """`fold` declares how shards combine (see FOLD_KINDS); `kind` declares
    how the history plane samples it — a gauge wrapping a monotone total
    (evictions, numRecordsIn) registers kind="counter" so history records
    its windowed rate instead of an ever-growing line. None means
    undeclared: the shard fold falls back to the DEPRECATED name heuristic
    in runtime.cluster (which warns), and the registry audit test fails
    unless the family is allowlisted."""

    def __init__(self, fn: Callable[[], Any], fold: Optional[str] = None,
                 kind: Optional[str] = None):
        if fold is not None and fold not in FOLD_KINDS:
            raise ValueError(f"unknown fold kind {fold!r} (one of "
                             f"{FOLD_KINDS})")
        if kind is not None and kind not in METRIC_KINDS:
            raise ValueError(f"unknown metric kind {kind!r} (one of "
                             f"{METRIC_KINDS})")
        self._fn = fn
        self.fold = fold
        self.kind = kind or "gauge"

    def value(self):
        return self._fn()


class Meter:
    """Rate over a sliding 60s window + lifetime count (MeterView analogue).

    Marks COALESCE into 100 ms buckets, so memory stays O(window) no matter
    the event rate (the reference MeterView keeps fixed per-second buckets
    for the same reason) — a dataplane channel marking per frame must not
    grow a tuple per frame. Lock-protected: senders mark() from their own
    threads while the heartbeat/snapshot thread reads rate()."""

    fold = "sum"
    kind = "meter"

    BUCKET_S = 0.1

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._events = deque()  # [bucket_start_t, n] buckets, oldest first
        self._count = 0
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        now = self._clock()
        with self._lock:
            self._count += n
            if self._events and now - self._events[-1][0] < self.BUCKET_S:
                self._events[-1][1] += n
            else:
                self._events.append([now, n])
            self._trim(now)

    def _trim(self, now: float) -> None:
        while self._events and now - self._events[0][0] > 60.0:
            self._events.popleft()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def rate(self) -> float:
        now = self._clock()
        with self._lock:
            self._trim(now)
            if not self._events:
                return 0.0
            span = max(now - self._events[0][0], 1e-9)
            return sum(n for _, n in self._events) / span

    def value(self):
        return self.rate()


class Histogram:
    """Reservoir histogram with quantiles (DescriptiveStatisticsHistogram
    analogue; bounded ring reservoir)."""

    fold = "hist"
    kind = "histogram"

    def __init__(self, size: int = 1024):
        self._values = deque(maxlen=size)
        self._count = 0

    def update(self, value: float) -> None:
        self._values.append(float(value))
        self._count += 1

    @property
    def count(self) -> int:
        return self._count

    def quantile(self, q: float) -> float:
        if not self._values:
            return float("nan")
        vals = sorted(self._values)
        idx = min(int(q * len(vals)), len(vals) - 1)
        return vals[idx]

    def stats(self) -> Dict[str, float]:
        if not self._values:
            return {"count": 0}
        vals = sorted(self._values)
        return {
            "count": self._count,
            "min": vals[0],
            "max": vals[-1],
            "mean": sum(vals) / len(vals),
            "p50": vals[len(vals) // 2],
            "p95": vals[min(int(0.95 * len(vals)), len(vals) - 1)],
            "p99": vals[min(int(0.99 * len(vals)), len(vals) - 1)],
        }

    def value(self):
        return self.stats()


class MetricGroup:
    """Hierarchical scope (job.task.operator...) registering named metrics."""

    def __init__(self, registry: "MetricRegistry", scope: tuple):
        self._registry = registry
        self.scope = scope

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self.scope + (name,))

    def counter(self, name: str) -> Counter:
        return self._registry._register(self.scope, name, Counter())

    def gauge(self, name: str, fn: Callable[[], Any],
              fold: Optional[str] = None,
              kind: Optional[str] = None) -> Gauge:
        return self._registry._register(self.scope, name,
                                        Gauge(fn, fold=fold, kind=kind))

    def meter(self, name: str) -> Meter:
        return self._registry._register(self.scope, name, Meter())

    def histogram(self, name: str, size: int = 1024) -> Histogram:
        return self._registry._register(self.scope, name, Histogram(size))

    def metric_identifier(self, name: str) -> str:
        return ".".join(self.scope + (name,))


class MetricRegistry:
    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()
        self._reporters: List["Reporter"] = []

    def group(self, *scope: str) -> MetricGroup:
        return MetricGroup(self, tuple(scope))

    def _register(self, scope: tuple, name: str, metric):
        key = ".".join(scope + (name,))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if type(existing) is not type(metric):
                    # collision: keep the FIRST registration (the reference
                    # registry logs and refuses the replacement —
                    # MetricRegistryImpl "Name collision" warning) and hand
                    # the caller its new metric UNREGISTERED: it is the
                    # right type for the caller's code (updates just go
                    # nowhere), whereas returning the existing wrong-typed
                    # metric would defer the failure to a crash at the
                    # first update call
                    import logging

                    logging.getLogger("flink_tpu.metrics").warning(
                        "metric %r already registered as %s; the conflicting "
                        "%s registration is ignored (detached instance "
                        "returned)",
                        key, type(existing).__name__, type(metric).__name__,
                    )
                    return metric
                return existing
            self._metrics[key] = metric
        return metric

    def all_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._metrics)

    def add_reporter(self, reporter: "Reporter") -> None:
        self._reporters.append(reporter)

    def report(self) -> None:
        snapshot = self.all_metrics()
        for r in self._reporters:
            r.report(snapshot)


class Reporter:
    def report(self, metrics: Dict[str, Any]) -> None:
        raise NotImplementedError


class InMemoryReporter(Reporter):
    def __init__(self):
        self.last: Dict[str, Any] = {}

    def report(self, metrics: Dict[str, Any]) -> None:
        self.last = {k: m.value() for k, m in metrics.items()}


class LoggingReporter(Reporter):
    def __init__(self, logger=None):
        import logging

        self._log = logger or logging.getLogger("flink_tpu.metrics")

    def report(self, metrics: Dict[str, Any]) -> None:
        for k, m in sorted(metrics.items()):
            self._log.info("%s = %s", k, m.value())


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name grammar
    [a-zA-Z_:][a-zA-Z0-9_:]* — non-conforming characters become '_' and a
    leading digit gets an '_' prefix (a dotted scope like '0ff.x' must not
    produce an invalid exposition)."""
    s = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def _prom_label_value(value: Any) -> str:
    """Escape a label value per the text exposition format (backslash,
    double-quote, and newline must be escaped inside the quotes)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _render_labels(labels: Optional[Dict[str, Any]]) -> str:
    """'{k="v",...}' or '' — base labelset attached to every sample."""
    if not labels:
        return ""
    pairs = ",".join(
        f'{_prom_name(str(k))}="{_prom_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + pairs + "}"


def _with_extra_label(lbl: str, extra: str) -> str:
    """Join a rendered base labelset with one more pair."""
    return lbl[:-1] + "," + extra + "}" if lbl else "{" + extra + "}"


def _render_summary(name: str, stats: Dict[str, Any], lbl: str) -> List[str]:
    """`# TYPE ... summary` + quantile series + _count for one histogram
    family — the ONE rendering both the live-metric and snapshot
    expositions use, so shard samples of a family can never drift to
    different quantile sets."""
    lines = [f"# TYPE {name} summary"]
    for q, stat in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"),
                    ("0.999", "p999")):
        v = stats.get(stat)
        if isinstance(v, (int, float)) and not (
                isinstance(v, float) and math.isnan(v)):
            extra = f'quantile="{q}"'
            lines.append(f"{name}{_with_extra_label(lbl, extra)} {v}")
    lines.append(f'{name}_count{lbl} {stats.get("count", 0)}')
    return lines


def prometheus_text(metrics: Dict[str, Any],
                    labels: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition format (flink-metrics-prometheus
    PrometheusReporter analogue — here as an encoding; the REST server
    exposes it at /metrics). Emits `# TYPE` metadata per family: Counter ->
    counter, Gauge/Meter -> gauge, Histogram -> summary (quantile series +
    _count, the reference reporter's HistogramSummaryProxy shape).
    `labels` (e.g. {'job': id}) attach to every sample — required whenever
    several registries share family names in one exposition, or the merged
    document would carry duplicate samples."""

    lbl = _render_labels(labels)
    lines = []
    for key, metric in sorted(metrics.items()):
        name = _prom_name(key)
        val = metric.value()
        if isinstance(metric, Histogram):
            lines.extend(_render_summary(name, val, lbl))
        elif isinstance(val, dict) and "count" in val:
            # histogram-stats-shaped dict behind a NON-Histogram metric —
            # e.g. the emission-latency plane's log-bucket snapshot gauge.
            # Render it as the same summary family instead of silently
            # dropping it: every registered histogram exports uniformly,
            # whatever metric class carries it.
            lines.extend(_render_summary(name, val, lbl))
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            kind = "counter" if isinstance(metric, Counter) else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name}{lbl} {val}")
    return "\n".join(lines) + "\n"


def prometheus_text_from_snapshot(snapshot: Dict[str, Any],
                                  labels: Optional[Dict[str, Any]] = None) -> str:
    """Exposition for a PLAIN-DATA metric snapshot (metrics_snapshot form —
    what TaskExecutors ship to the JobManager over RPC): numeric values
    become untyped gauges, histogram-stat dicts become quantile series.
    `labels` (e.g. {'shard': 3}) are attached to every sample."""
    lbl = _render_labels(labels)
    lines = []
    for key, val in sorted(snapshot.items()):
        if key.startswith("__"):      # reserved metadata (__folds__ etc.)
            continue
        name = _prom_name(key)
        if isinstance(val, dict):
            lines.extend(_render_summary(name, val, lbl))
        elif isinstance(val, (int, float)) and not isinstance(val, bool):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{lbl} {val}")
    return "\n".join(lines) + "\n"


def merge_prometheus_text(texts: "List[str]") -> str:
    """Merge several expositions into one valid document: the text format
    allows at most ONE `# TYPE` line per metric family with all of the
    family's samples grouped under it, so naive concatenation of per-job /
    per-shard expositions (repeated TYPE lines, interleaved families) is
    rejected by strict parsers. Keeps the first declared type per family
    and groups samples; a summary's `_count`/`_sum` series stay with their
    parent family."""
    types: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    order: List[str] = []
    summaries = set()

    def family_of(sample_line: str) -> str:
        name = sample_line.split("{", 1)[0].split(" ", 1)[0]
        for suffix in ("_count", "_sum"):
            if name.endswith(suffix) and name[: -len(suffix)] in summaries:
                return name[: -len(suffix)]
        return name

    for text in texts:
        for line in text.splitlines():
            if not line.strip():
                continue
            if line.startswith("# TYPE "):
                _, _, name, kind = line.split(" ", 3)
                if name not in types:
                    types[name] = kind
                    order.append(name)
                    if kind == "summary":
                        summaries.add(name)
                continue
            if line.startswith("#"):
                continue
            fam = family_of(line)
            if fam not in samples and fam not in types:
                order.append(fam)
            samples.setdefault(fam, []).append(line)
    out = []
    for fam in order:
        kind = types.get(fam)
        if kind:
            out.append(f"# TYPE {fam} {kind}")
        out.extend(samples.get(fam, ()))
    return "\n".join(out) + "\n"


def metrics_snapshot(metrics: Dict[str, Any]) -> Dict[str, Any]:
    """Plain-data view of a metric table — int/float scalars and histogram
    stat dicts only — safe to JSON-encode or ship over the restricted RPC
    wire (TM -> JM metric shipping).

    Two reserved metadata keys ride along under dunder names (so every
    existing consumer's numeric/suffix filters skip them naturally):
    ``__folds__`` maps metric key -> declared shard-fold kind (only keys
    that DECLARED one — aggregate_shard_metrics reads these instead of the
    deprecated name heuristic) and ``__kinds__`` maps metric key ->
    sampling kind (counter/gauge/meter/histogram — the history plane reads
    these to record counters as windowed rates)."""
    out: Dict[str, Any] = {}
    folds: Dict[str, str] = {}
    kinds: Dict[str, str] = {}
    for key, metric in metrics.items():
        try:
            val = metric.value()
        except Exception:  # a gauge closure over torn-down state must not
            continue       # poison the whole snapshot
        if hasattr(val, "item"):   # numpy scalar
            val = val.item()
        if isinstance(val, bool):
            continue
        if isinstance(val, dict):
            out[key] = {
                str(k): (v.item() if hasattr(v, "item") else v)
                for k, v in val.items()
                if isinstance(v, (int, float)) or hasattr(v, "item")
            }
        elif isinstance(val, (int, float)):
            out[key] = val
        else:
            continue
        fold = getattr(metric, "fold", None)
        if fold is not None:
            folds[key] = fold
        kind = getattr(metric, "kind", None)
        if kind is not None:
            kinds[key] = kind
    if folds:
        out["__folds__"] = folds
    if kinds:
        out["__kinds__"] = kinds
    return out


class PrometheusReporter(Reporter):
    """Holds the latest exposition text; served by the REST endpoint."""

    def __init__(self):
        self.text = ""

    def report(self, metrics: Dict[str, Any]) -> None:
        self.text = prometheus_text(metrics)
