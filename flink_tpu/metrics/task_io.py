"""Per-task busy/idle/backpressure accounting and device-time attribution.

The reference tracks these in TaskIOMetricGroup (busyTimeMsPerSecond,
idleTimeMsPerSecond, backPressuredTimeMsPerSecond; TaskIOMetricGroup.java:48)
and samples them for the REST backpressure handlers
(JobVertexBackPressureHandler). The stepped executor's analogue:

- **busy** — time the run loop spends pushing a batch through the runner
  DAG (device dispatch included), minus time blocked on downstream credits;
- **backpressured** — time blocked inside an exchange sender waiting for
  credits (dataplane OutputChannel.send), i.e. the downstream stage's
  backlog surfacing in THIS task's loop — the "writer blocks on
  LocalBufferPool" condition;
- **idle** — everything else: source poll timeouts, starved stage-input
  channels, scheduling gaps.

Lifetime ratios are maintained continuously from these counters; the
windowed `*MsPerSecond` gauges are sampled on the run loop's
processing-time tick every `observability.sampling.interval-ms` (the
backpressure-sampling period), so REST/dashboard readers see the RECENT
state of the task, not its lifetime average.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List


def backpressure_level(ratio: float) -> str:
    """The reference's backpressure classification thresholds
    (JobVertexBackPressureHandler: ok <= 0.10 < low <= 0.5 < high)."""
    if ratio <= 0.10:
        return "ok"
    if ratio <= 0.5:
        return "low"
    return "high"


class TaskIOMetrics:
    """Busy/idle/backPressured time accounting for one task run loop."""

    def __init__(self):
        self.busy_s = 0.0
        self.loop_s = 1e-9
        # callables returning cumulative seconds blocked on credits (one per
        # exchange sender feeding a downstream stage)
        self._bp_sources: List[Callable[[], float]] = []
        # windowed sample state
        self._last_sample_t = time.monotonic()
        self._last = (0.0, 0.0, 0.0)          # (busy, bp, loop) at last sample
        self._rates = {"busy": 0.0, "idle": 0.0, "backPressured": 0.0}

    def add_backpressure_source(self, fn: Callable[[], float]) -> None:
        self._bp_sources.append(fn)

    def backpressured_s(self) -> float:
        return sum(fn() for fn in self._bp_sources)

    # -- run-loop feed -----------------------------------------------------
    def record_step(self, busy_dt: float, loop_dt: float) -> None:
        """One source turn: `busy_dt` spent pushing (includes any credit
        waits — they are separated out at read time), `loop_dt` total."""
        self.busy_s += busy_dt
        self.loop_s += loop_dt

    # -- lifetime ratios ---------------------------------------------------
    def ratios(self) -> Dict[str, float]:
        bp = min(self.backpressured_s(), self.busy_s)
        busy = self.busy_s - bp
        loop = max(self.loop_s, busy + bp, 1e-9)
        idle = max(loop - busy - bp, 0.0)
        return {
            "busyRatio": busy / loop,
            "idleRatio": idle / loop,
            "backPressuredRatio": bp / loop,
        }

    # -- windowed sampling -------------------------------------------------
    def maybe_sample(self, interval_ms: int, now: float = None) -> None:
        """Fold the deltas since the last sample into the msPerSecond rates;
        called from the processing-time tick (cheap: pure arithmetic)."""
        now = time.monotonic() if now is None else now
        dt = now - self._last_sample_t
        if dt * 1000.0 < max(interval_ms, 1):
            return
        bp_total = min(self.backpressured_s(), self.busy_s)
        d_busy = self.busy_s - self._last[0]
        d_bp = bp_total - self._last[1]
        d_loop = self.loop_s - self._last[2]
        self._last = (self.busy_s, bp_total, self.loop_s)
        self._last_sample_t = now
        del d_loop  # wall clock, not loop time, is the msPerSecond base
        wall = max(dt, 1e-9)
        bp = max(d_bp, 0.0)
        busy = max(d_busy - bp, 0.0)
        idle = max(wall - busy - bp, 0.0)
        self._rates = {
            "busy": min(busy / wall, 1.0) * 1000.0,
            "backPressured": min(bp / wall, 1.0) * 1000.0,
            "idle": min(idle / wall, 1.0) * 1000.0,
        }

    def ms_per_second(self, kind: str) -> float:
        return self._rates[kind]

    def register(self, group) -> None:
        """Register the TaskIOMetricGroup-analogue gauges on `group`."""
        r = self.ratios
        # per-task fractions (each bounded per task) fold MEAN
        group.gauge("busyTimeRatio", lambda: r()["busyRatio"], fold="mean")
        group.gauge("idleTimeRatio", lambda: r()["idleRatio"], fold="mean")
        group.gauge("backPressuredTimeRatio",
                    lambda: r()["backPressuredRatio"], fold="mean")
        group.gauge("busyTimeMsPerSecond",
                    lambda: self.ms_per_second("busy"), fold="mean")
        group.gauge("idleTimeMsPerSecond",
                    lambda: self.ms_per_second("idle"), fold="mean")
        group.gauge("backPressuredTimeMsPerSecond",
                    lambda: self.ms_per_second("backPressured"), fold="mean")


class DeviceTimer:
    """Host-clock attribution of one operator's device sections (dispatch +
    blocking readback). Wrap already-synchronous sections only — this is an
    observer, it must never add block_until_ready syncs of its own."""

    def __init__(self, histogram=None):
        self.total_s = 0.0
        self.dispatches = 0
        self._hist = histogram

    class _Section:
        __slots__ = ("timer", "t0")

        def __init__(self, timer: "DeviceTimer"):
            self.timer = timer

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dt = time.perf_counter() - self.t0
            self.timer.total_s += dt
            self.timer.dispatches += 1
            if self.timer._hist is not None:
                self.timer._hist.update(dt * 1000.0)
            return False

    def section(self) -> "_Section":
        return DeviceTimer._Section(self)

    def register(self, group) -> None:
        group.gauge("deviceTimeMsTotal", lambda: self.total_s * 1000.0,
                    fold="sum", kind="counter")
        group.gauge("deviceDispatches", lambda: self.dispatches,
                    fold="sum", kind="counter")
