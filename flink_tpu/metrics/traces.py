"""Spans and trace reporting (reference: flink-metrics-core traces/Span.java,
SpanBuilder.java, reporter/TraceReporter.java; used by checkpoint/recovery
lifecycles via DefaultCheckpointStatsTracker).

Checkpoint trigger/complete, job restart, and distributed checkpoint-ack
paths emit spans; reporters are pluggable (logging, in-memory, OTLP/JSON in
metrics/otel.py).

Correlation: a TraceRegistry may carry a default `trace_id` (32 hex chars,
the OTel trace-id width). Every span built through it inherits that id, so
spans emitted by DIFFERENT processes about the same job — the JM's
checkpoint-trigger span and a TM's checkpoint-ack span shipped back over
RPC — stitch into one trace. `job_trace_id` derives the id
deterministically from the job id, which is exactly what lets two
processes agree on it without an extra coordination round-trip.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any, Dict, List, Optional


def job_trace_id(job_id: str) -> str:
    """Deterministic 32-hex OTel-width trace id for a job: every process
    that knows the job id derives the same trace id, so JM- and TM-side
    spans correlate without shipping extra context."""
    return hashlib.sha256(f"flink-tpu-job:{job_id}".encode()).hexdigest()[:32]


@dataclasses.dataclass
class Span:
    scope: str
    name: str
    start_ts_ms: float
    end_ts_ms: float
    attributes: Dict[str, Any]
    trace_id: Optional[str] = None

    @property
    def duration_ms(self) -> float:
        return self.end_ts_ms - self.start_ts_ms

    def to_dict(self) -> Dict[str, Any]:
        """Plain-data form for RPC shipping (restricted-pickle safe)."""
        return {
            "scope": self.scope, "name": self.name,
            "start_ts_ms": self.start_ts_ms, "end_ts_ms": self.end_ts_ms,
            "attributes": dict(self.attributes), "trace_id": self.trace_id,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Span":
        return Span(d["scope"], d["name"], d["start_ts_ms"], d["end_ts_ms"],
                    dict(d.get("attributes") or {}), d.get("trace_id"))


class SpanBuilder:
    def __init__(self, scope: str, name: str, clock=time.time,
                 trace_id: Optional[str] = None):
        self._scope = scope
        self._name = name
        self._clock = clock
        self._start = clock() * 1000
        self._end: Optional[float] = None
        self._attrs: Dict[str, Any] = {}
        self._trace_id = trace_id

    def set_attribute(self, key: str, value) -> "SpanBuilder":
        self._attrs[key] = value
        return self

    def set_start(self, ts_ms: float) -> "SpanBuilder":
        self._start = ts_ms
        return self

    def set_trace_id(self, trace_id: str) -> "SpanBuilder":
        self._trace_id = trace_id
        return self

    def end(self) -> Span:
        return Span(self._scope, self._name, self._start,
                    self._clock() * 1000, dict(self._attrs), self._trace_id)


class TraceReporter:
    def report_span(self, span: Span) -> None:
        raise NotImplementedError


class InMemoryTraceReporter(TraceReporter):
    def __init__(self, max_spans: Optional[int] = None):
        self.spans: List[Span] = []
        self._max = max_spans

    def report_span(self, span: Span) -> None:
        self.spans.append(span)
        if self._max is not None:
            del self.spans[:-self._max]


class LoggingTraceReporter(TraceReporter):
    def __init__(self, logger=None):
        import logging

        self._log = logger or logging.getLogger("flink_tpu.traces")

    def report_span(self, span: Span) -> None:
        self._log.info(
            "span %s/%s %.2fms %s", span.scope, span.name, span.duration_ms, span.attributes
        )


class TraceRegistry:
    def __init__(self, trace_id: Optional[str] = None):
        self._reporters: List[TraceReporter] = []
        self.trace_id = trace_id

    def add_reporter(self, reporter: TraceReporter) -> None:
        self._reporters.append(reporter)

    def span(self, scope: str, name: str) -> SpanBuilder:
        return SpanBuilder(scope, name, trace_id=self.trace_id)

    def report(self, span: Span) -> None:
        if span.trace_id is None and self.trace_id is not None:
            span = dataclasses.replace(span, trace_id=self.trace_id)
        for r in self._reporters:
            r.report_span(span)
