"""Spans and trace reporting (reference: flink-metrics-core traces/Span.java,
SpanBuilder.java, reporter/TraceReporter.java; used by checkpoint/recovery
lifecycles via DefaultCheckpointStatsTracker).

Checkpoint trigger/complete and job restart paths emit spans; reporters are
pluggable (logging, in-memory; OTel-wire export would slot in the same SPI)."""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    scope: str
    name: str
    start_ts_ms: float
    end_ts_ms: float
    attributes: Dict[str, Any]

    @property
    def duration_ms(self) -> float:
        return self.end_ts_ms - self.start_ts_ms


class SpanBuilder:
    def __init__(self, scope: str, name: str, clock=time.time):
        self._scope = scope
        self._name = name
        self._clock = clock
        self._start = clock() * 1000
        self._end: Optional[float] = None
        self._attrs: Dict[str, Any] = {}

    def set_attribute(self, key: str, value) -> "SpanBuilder":
        self._attrs[key] = value
        return self

    def set_start(self, ts_ms: float) -> "SpanBuilder":
        self._start = ts_ms
        return self

    def end(self) -> Span:
        return Span(self._scope, self._name, self._start, self._clock() * 1000, dict(self._attrs))


class TraceReporter:
    def report_span(self, span: Span) -> None:
        raise NotImplementedError


class InMemoryTraceReporter(TraceReporter):
    def __init__(self):
        self.spans: List[Span] = []

    def report_span(self, span: Span) -> None:
        self.spans.append(span)


class LoggingTraceReporter(TraceReporter):
    def __init__(self, logger=None):
        import logging

        self._log = logger or logging.getLogger("flink_tpu.traces")

    def report_span(self, span: Span) -> None:
        self._log.info(
            "span %s/%s %.2fms %s", span.scope, span.name, span.duration_ms, span.attributes
        )


class TraceRegistry:
    def __init__(self):
        self._reporters: List[TraceReporter] = []

    def add_reporter(self, reporter: TraceReporter) -> None:
        self._reporters.append(reporter)

    def span(self, scope: str, name: str) -> SpanBuilder:
        return SpanBuilder(scope, name)

    def report(self, span: Span) -> None:
        for r in self._reporters:
            r.report_span(span)
