"""Device compute kernels: segment reduction, window firing, top-k."""
