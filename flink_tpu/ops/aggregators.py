"""Aggregator specs usable on both the device path and the Python oracle.

The reference folds window contents into a single accumulator per
(key, window) via ReducingState/AggregatingState
(HeapAggregatingState.add:94) — state per key×window is one ACC. The device
path makes the ACC *columnar*: each accumulator field is one [keys, slices]
array in HBM, updated by scatter-combine and merged across slices by a
segment reduce at fire time.

A `DeviceAggregator` therefore restricts accumulators to a flat dict of
numeric fields, each with a scatter combiner in {add, min, max} — enough for
sum/count/min/max/mean/sum-of-squares-style analytics (the YSB/Nexmark
baseline set). Arbitrary Python `AggregateFunction`s run on the oracle
operator instead (same split as the reference, where only
Reducing/AggregatingState windows pre-aggregate).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from flink_tpu.core.functions import AggregateFunction

# scatter sources
VALUE = "value"   # scatter the record's value column
ONE = "one"       # scatter constant 1 (count)


@dataclasses.dataclass(frozen=True)
class AccField:
    """One columnar accumulator field: a [keys, slices] device array."""

    name: str
    dtype: Any            # numpy dtype of the field
    identity: float       # padding / empty-slice value
    scatter: str          # 'add' | 'min' | 'max'
    source: str = VALUE   # which input column feeds the scatter
    # declared value domain: non-negative ints < 2**domain_bits. Unlocks the
    # MXU fast path for order statistics (pallas nibble-histogram max, ~5x
    # the scatter unit); None = unbounded, order statistics scatter-combine
    domain_bits: Any = None


@dataclasses.dataclass(frozen=True, eq=False)
class DeviceAggregator:
    """Columnar aggregator: fields + an extract over the combined fields.

    `extract` maps {field_name: array} -> result array (any backend: works
    with both numpy and jnp inputs since it must use only ufunc-style ops).

    eq=False ⇒ identity hashing: instances are cache keys for compiled
    kernels (segment_ops builders are lru_cached on them), so builtin
    factories below memoize and return singletons per dtype.
    """

    name: str
    fields: Tuple[AccField, ...]
    extract: Callable[[Dict[str, Any]], Any]
    result_dtype: Any = np.float32
    # pre-aggregation contract: True means per-(key, slice) partials of the
    # fields, merged by each field's own scatter combiner, reconstruct the
    # exact ring state — the property the mesh map-side combiner
    # (parallel.mesh.local-combine) relies on. Every builtin holds it by
    # construction (add/min/max are associative + commutative); closure-tier
    # aggregates (e.g. the q5 top-K post-processing) never resolve to a
    # DeviceAggregator at all, and a custom spec whose extract depends on
    # more than the combined fields can opt out here.
    combinable: bool = True

    def field(self, name: str) -> AccField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def python_equivalent(self) -> AggregateFunction:
        """Scalar AggregateFunction with identical math, for the oracle."""
        return _ColumnarAsPython(self)


_SCATTER_NP = {
    "add": lambda a, b: a + b,
    "min": np.minimum,
    "max": np.maximum,
}


def combine_binary(op: str):
    """Elementwise jnp combine for a scatter kind — the single dispatch
    table the in-scan session merge carry and the global-fold kernels
    share (numpy ufuncs do NOT dispatch on jit tracers, so the host
    oracle's `_SCATTER_NP` table above cannot serve the kernels; the jax
    import is deferred to kernel-build time)."""
    import jax.numpy as jnp

    table = {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}
    if op not in table:
        raise ValueError(op)
    return table[op]


def combine_reduce(op: str):
    """Axis reduction for a scatter kind (works on numpy and jnp arrays):
    the fire-time segment fold over a window's slice columns."""
    if op == "add":
        return lambda a, axis: a.sum(axis=axis)
    if op == "min":
        return lambda a, axis: a.min(axis=axis)
    if op == "max":
        return lambda a, axis: a.max(axis=axis)
    raise ValueError(op)


def scan_identity(dtype, scatter: str):
    """The neutral element of a scatter kind at a dtype — what purged ring
    cells and empty fold lanes must hold so combining them is a no-op."""
    if scatter == "add":
        return 0
    if scatter == "min":
        return _max_of(dtype)
    if scatter == "max":
        return _min_of(dtype)
    raise ValueError(scatter)


class _ColumnarAsPython(AggregateFunction):
    """Scalar-dict interpretation of a DeviceAggregator (oracle parity)."""

    def __init__(self, spec: DeviceAggregator):
        self.spec = spec

    def create_accumulator(self):
        return {f.name: f.identity for f in self.spec.fields}

    def add(self, value, acc):
        out = dict(acc)
        for f in self.spec.fields:
            v = 1 if f.source == ONE else value
            out[f.name] = _SCATTER_NP[f.scatter](acc[f.name], v)
        return out

    def get_result(self, acc):
        res = self.spec.extract({k: np.asarray(v) for k, v in acc.items()})
        arr = np.asarray(res)
        return arr.item() if arr.ndim == 0 else arr

    def merge(self, a, b):
        return {
            f.name: _SCATTER_NP[f.scatter](a[f.name], b[f.name])
            for f in self.spec.fields
        }


# ---------------------------------------------------------------------------
# Built-ins
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def sum_agg(dtype=np.float32) -> DeviceAggregator:
    return DeviceAggregator(
        "sum",
        (AccField("sum", dtype, 0, "add"),),
        lambda f: f["sum"],
        result_dtype=dtype,
    )


@functools.lru_cache(maxsize=None)
def count_agg() -> DeviceAggregator:
    return DeviceAggregator(
        "count",
        (AccField("count", np.int32, 0, "add", source=ONE),),
        lambda f: f["count"],
        result_dtype=np.int32,
    )


@functools.lru_cache(maxsize=None)
def min_agg(dtype=np.float32) -> DeviceAggregator:
    ident = _max_of(dtype)
    return DeviceAggregator(
        "min", (AccField("min", dtype, ident, "min"),), lambda f: f["min"], result_dtype=dtype
    )


@functools.lru_cache(maxsize=None)
def max_agg(dtype=np.float32, domain_bits=None) -> DeviceAggregator:
    """Windowed max. With `domain_bits` set, values are declared to be
    non-negative ints < 2**domain_bits: the accumulator becomes int32 with
    identity -1 ("absent") and the pallas superscan runs max on the MXU via
    two conditional nibble histograms instead of the serial scatter unit."""
    if domain_bits is not None:
        if domain_bits > 8:
            raise ValueError("bounded max supports domain_bits <= 8")
        return DeviceAggregator(
            "max8",
            (AccField("max", np.int32, -1, "max", domain_bits=domain_bits),),
            lambda f: f["max"],
            result_dtype=np.int32,
        )
    ident = _min_of(dtype)
    return DeviceAggregator(
        "max", (AccField("max", dtype, ident, "max"),), lambda f: f["max"], result_dtype=dtype
    )


@functools.lru_cache(maxsize=None)
def mean_agg(dtype=np.float32) -> DeviceAggregator:
    return DeviceAggregator(
        "mean",
        (
            AccField("sum", dtype, 0, "add"),
            AccField("count", np.int32, 0, "add", source=ONE),
        ),
        lambda f: f["sum"] / _maximum(f["count"], 1),
        result_dtype=dtype,
    )


def _maximum(a, b):
    # dispatches correctly for both numpy and jax array inputs
    if isinstance(a, np.ndarray) or np.isscalar(a):
        return np.maximum(a, b)
    import jax.numpy as jnp
    return jnp.maximum(a, b)


def _max_of(dtype) -> float:
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return float(np.finfo(dt).max)
    return int(np.iinfo(dt).max)


def _min_of(dtype) -> float:
    dt = np.dtype(dtype)
    if dt.kind == "f":
        return float(np.finfo(dt).min)
    return int(np.iinfo(dt).min)


BUILTINS = {
    "sum": sum_agg,
    "count": count_agg,
    "min": min_agg,
    "max": max_agg,
    "mean": mean_agg,
}


def decomposable(agg: DeviceAggregator) -> bool:
    """True when the mesh map-side combiner may pre-reduce this aggregate:
    every field's scatter kind is one of the associative+commutative
    combiners and the spec has not opted out. The combine path sends one
    partial per (key, rel-slice) per source shard — merged by the SAME
    scatter ops the ring ingest applies, so pre-reduction is exact by
    construction. Non-decomposable aggregates route raw records instead."""
    return bool(getattr(agg, "combinable", True)) and all(
        f.scatter in _SCATTER_NP for f in agg.fields
    )


def resolve(agg) -> Optional[DeviceAggregator]:
    """Resolve a user-provided aggregate spec to a DeviceAggregator if it can
    run on the device path; None means fall back to the oracle operator."""
    if isinstance(agg, DeviceAggregator):
        return agg
    if isinstance(agg, str) and agg in BUILTINS:
        return BUILTINS[agg]()
    return None
