"""On-device keyBy exchange: the ICI all-to-all replacing the network shuffle.

The reference's keyBy moves serialized records through Netty with
credit-based flow control (KeyGroupStreamPartitioner →
RecordWriter.emit:105 → … → RemoteInputChannel.onBuffer:590). On a TPU
slice there is no serialization and no credit protocol: the shuffle is ONE
`lax.all_to_all` over ICI inside a shard_map program — records stay columnar
end to end, and "flow control" is the static step batch size.

Lane protocol: each source shard holds B lanes (kid, slice-pos, value);
destination shard = key_group * n // max_parallelism, computed on device
from the key-group column. Lanes are routed positionally: the send buffer is
[n_shards, B] per column with non-destination lanes masked INVALID, so the
all-to-all needs no compaction/sort (bandwidth cost n×B lanes; dense
compaction via on-device sort is a later optimization once profiling says
the exchange is bandwidth-bound).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from flink_tpu.utils.jax_compat import shard_map

from flink_tpu.ops.segment_ops import INVALID_INDEX


def keyby_exchange_fn(n_shards: int, max_parallelism: int, axis_name: str):
    """Per-shard body: route lanes to their key-group owners.

    inputs (per-shard view):
      key_groups: i32[B]   (INVALID_INDEX for padding lanes)
      columns:    dict of [B] arrays to route alongside (kid/spos/values)
    returns dict of [n_shards * B] arrays: the lanes this shard received
    (INVALID-masked lanes preserved as padding).
    """

    def body(key_groups: jnp.ndarray, columns: Dict[str, jnp.ndarray]):
        B = key_groups.shape[0]
        valid = key_groups != INVALID_INDEX
        dst = jnp.where(
            valid,
            key_groups * jnp.int32(n_shards) // jnp.int32(max_parallelism),
            jnp.int32(-1),
        )
        # send buffer row d = lanes destined for shard d, else INVALID
        rows = jnp.arange(n_shards, dtype=jnp.int32)[:, None]          # [n, 1]
        route = rows == dst[None, :]                                    # [n, B]
        out = {}
        for name, col in columns.items():
            if col.dtype in (jnp.int32, jnp.int64):
                pad = jnp.array(INVALID_INDEX, dtype=col.dtype)
            else:
                pad = jnp.zeros((), dtype=col.dtype)
            send = jnp.where(route, col[None, :], pad)                  # [n, B]
            recv = jax.lax.all_to_all(
                send, axis_name, split_axis=0, concat_axis=0, tiled=False
            )                                                           # [n, B]
            out[name] = recv.reshape(n_shards * B)
        kg_send = jnp.where(route, key_groups[None, :], jnp.int32(INVALID_INDEX))
        kg_recv = jax.lax.all_to_all(
            kg_send, axis_name, split_axis=0, concat_axis=0, tiled=False
        ).reshape(n_shards * B)
        return kg_recv, out

    return body


def make_keyby_exchange(mesh: Mesh, max_parallelism: int, axis_name: str = "shards"):
    """Jitted whole-mesh exchange: [n, B] sharded columns -> [n, n*B] sharded."""
    n = mesh.shape[axis_name]
    body = keyby_exchange_fn(n, max_parallelism, axis_name)

    def mesh_fn(key_groups, columns):
        # per-shard views arrive as [1, B]; strip/restore the leading axis
        kg, cols = body(key_groups[0], {k: v[0] for k, v in columns.items()})
        return kg[None], {k: v[None] for k, v in cols.items()}

    spec = P(axis_name, None)
    fn = shard_map(
        mesh_fn,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec),
    )
    return jax.jit(fn)
