"""Device kernels for the two-input keyed join ring (flink_tpu/joins).

The join state is a pair of per-key time-bucketed rings resident in HBM:
for each side an int32 row-index array and an int32 relative-timestamp
array, both shaped [NB, K, C] — NB ring bucket slots on the event-time
bucket granule (gcd of window size and slide), K dense key ids, C record
slots per (bucket, key). The host owns an occupancy mirror (counts per
bucket x key), plans every record's (ring-bucket, key, slot) coordinate,
and detects overflow BEFORE dispatch — so the ingest kernel is a pure
vectorized scatter and the fire kernel a pure gather + segment-wise
cross-match, with no data-dependent control flow on device (the superscan
discipline: one compiled program per geometry, cached module-level).

Two kernels:

  ingest    scatter a staged batch of (ring-bucket, kid, slot) -> (row
            index, rel-ts) writes into both ring arrays in one dispatch.

  match     gather the bucket run one window (or interval frontier)
            covers from BOTH rings and lay each side out as [K, L] slot
            lanes (L = buckets x C). Validity comes from the host-shipped
            occupancy counts, never from device state, so purged buckets
            need no device-side zeroing. For window joins the per-key
            match set is the full cross product of valid lanes — the pair
            count is lcnt * rcnt and the host expands pairs from the
            gathered index lanes. For interval joins the kernel
            additionally emits the pair mask [K, L, R] restricted by the
            relative-time bound (arXiv 2303.00793: window join, interval
            join, and windowed enrich share this one bucketed-ring core —
            the window join is the mask-free special case).

Both kernels are jitted per geometry via module-level lru_cache, exactly
like ops/superscan.py, so repeated operators of the same shape share one
compiled executable. Arrays stay un-donated: the ring is operator state
and the caller re-binds the returned buffers.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp

__all__ = ["build_join_ingest", "build_join_match"]


@lru_cache(maxsize=None)
def build_join_ingest(NB: int, K: int, C: int):
    """Jitted scatter of one staged batch into a side's ring arrays.

    fn(idx_arr [NB,K,C] i32, ts_arr [NB,K,C] i32,
       rb [n] i32, kid [n] i32, slot [n] i32, rowidx [n] i32, tsrel [n] i32)
      -> (idx_arr', ts_arr')

    Coordinates are host-planned and in-bounds by construction (the host
    mirror raised on overflow before dispatch); padding lanes point at
    slot C-1 of ring bucket 0 with rowidx/tsrel repeating the real last
    lane, so `mode="drop"` is never load-bearing for correctness.
    """

    def ingest(idx_arr, ts_arr, rb, kid, slot, rowidx, tsrel):
        idx_arr = idx_arr.at[rb, kid, slot].set(rowidx, mode="drop")
        ts_arr = ts_arr.at[rb, kid, slot].set(tsrel, mode="drop")
        return idx_arr, ts_arr

    return jax.jit(ingest)


@lru_cache(maxsize=None)
def build_join_match(NB: int, K: int, C: int, n_lb: int, n_rb: int,
                     interval: bool):
    """Jitted gather + cross-match over one fired window's bucket run.

    fn(idx_l, ts_l [NB,K,C], cnt_l [n_lb,K] i32, rbs_l [n_lb] i32,
       idx_r, ts_r [NB,K,C], cnt_r [n_rb,K] i32, rbs_r [n_rb] i32,
       lo i32, hi i32)
      -> window join: (lidx [K,L], lts [K,L], lval [K,L] bool,
                       ridx [K,R], rts [K,R], rval [K,R] bool,
                       pairs [K] i32)
      -> interval:    the same, plus mask [K,L,R] bool where the pair's
                      rel-time delta (rts - lts) lies in [lo, hi]

    L = n_lb*C, R = n_rb*C. The gathered lanes are what the host expands
    emissions from; for the window join the mask is implied by the
    validity lanes (full per-key cross product), so it is never
    materialized or read back.
    """

    def match(idx_l, ts_l, cnt_l, rbs_l, idx_r, ts_r, cnt_r, rbs_r, lo, hi):
        def lanes(idx_arr, ts_arr, cnt, rbs, nb):
            # [nb, K, C] -> [K, nb*C]: per-key slot lanes over the run
            gi = jnp.transpose(idx_arr[rbs], (1, 0, 2)).reshape(K, nb * C)
            gt = jnp.transpose(ts_arr[rbs], (1, 0, 2)).reshape(K, nb * C)
            # valid: [K, nb, C] -> [K, nb*C], matching the gather layout
            valid = (jnp.arange(C, dtype=jnp.int32)[None, None, :]
                     < cnt.T[:, :, None])
            return gi, gt, valid.reshape(K, nb * C)

        lidx, lts, lval = lanes(idx_l, ts_l, cnt_l, rbs_l, n_lb)
        ridx, rts, rval = lanes(idx_r, ts_r, cnt_r, rbs_r, n_rb)
        lcnt = jnp.sum(lval, axis=1, dtype=jnp.int32)
        rcnt = jnp.sum(rval, axis=1, dtype=jnp.int32)
        if not interval:
            pairs = lcnt * rcnt
            return lidx, lts, lval, ridx, rts, rval, pairs
        delta = rts[:, None, :] - lts[:, :, None]          # [K, L, R]
        mask = (lval[:, :, None] & rval[:, None, :]
                & (delta >= lo) & (delta <= hi))
        pairs = jnp.sum(mask, axis=(1, 2), dtype=jnp.int32)
        return lidx, lts, lval, ridx, rts, rval, pairs, mask

    return jax.jit(match)
