"""MXU histogram: segment aggregation as one-hot matmuls.

The scatter that the reference performs per record
(WindowOperator.processElement -> HeapAggregatingState.add, per-(key,window)
hash-map mutation) is re-expressed as dense linear algebra so it lands on the
TPU's systolic array instead of the (slow, serialized) scatter unit:

    count[seg]   = sum_b  1[idx_b == seg]
    sum[seg]     = sum_b  v_b * 1[idx_b == seg]

with the segment id factored two-level, ``idx = hi * LANES + lo``:

    H[hi, lo] = one_hot(hi_b)^T  @  one_hot(lo_b)        # [B,HI]x[B,LO] matmul

One [B, HI] x [B, LO] contraction over the batch axis replaces B random
scatters; HI*LO = num_segments. Counts run as int8 one-hots accumulating into
int32 (exact); weighted sums run as bf16 with an optional THREE-term
split-float pass (8+8+8 mantissa bits cover f32's 24) so each record's value
enters the f32 accumulator without quantization — see weighted_hist for the
precise exactness contract.

Out-of-range segment ids (idx < 0 or >= num_segments) contribute nothing:
their `hi` row matches no column of the iota, so they vanish from the
product — this is the INVALID_INDEX drop semantics of segment_ops without
any masking cost.

The batch is processed in static chunks via lax.scan so the one-hot
intermediates stay VMEM-sized.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128  # TPU lane width: the `lo` one-hot dimension


def plan_segments(num_segments: int) -> Tuple[int, int]:
    """Factor num_segments as HI * LANES (rounded up)."""
    hi = -(-num_segments // LANES)
    return hi, LANES


def _one_hots(idx: jnp.ndarray, hi_n: int, dtype) -> Tuple[jnp.ndarray, jnp.ndarray]:
    hi = (idx // LANES).astype(jnp.int32)
    lo = (idx % LANES).astype(jnp.int32)
    oh_hi = (hi[:, None] == jnp.arange(hi_n, dtype=jnp.int32)[None, :]).astype(dtype)
    oh_lo = (lo[:, None] == jnp.arange(LANES, dtype=jnp.int32)[None, :]).astype(dtype)
    return oh_hi, oh_lo


def _dot(a: jnp.ndarray, b: jnp.ndarray, out_dtype) -> jnp.ndarray:
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=out_dtype
    )


def count_hist(idx: jnp.ndarray, num_segments: int, *, chunk: int = 8192) -> jnp.ndarray:
    """int32[num_segments] counts of idx values; out-of-range ids dropped.

    idx length must be a multiple of `chunk` (pad with -1).
    """
    hi_n, _ = plan_segments(num_segments)

    def body(acc, ii):
        oh_hi, oh_lo = _one_hots(ii, hi_n, jnp.int8)
        return acc + _dot(oh_hi, oh_lo, jnp.int32), None

    n = idx.shape[0] // chunk
    acc, _ = jax.lax.scan(body, jnp.zeros((hi_n, LANES), jnp.int32), idx.reshape(n, chunk))
    return acc.reshape(-1)[:num_segments]


def weighted_hist(
    idx: jnp.ndarray,
    vals: jnp.ndarray,
    num_segments: int,
    *,
    chunk: int = 8192,
    exact: bool = True,
) -> jnp.ndarray:
    """f32[num_segments] per-segment sums of vals; out-of-range ids dropped.

    Exactness contract (honest version):
    - exact=True splits each f32 value into THREE bf16 terms, v == t0+t1+t2
      bit-exactly for every finite f32 whose twice-reduced residual does not
      underflow bf16's subnormal floor (all values with |v| >= ~2**-110,
      and 0). Each bf16 x {0,1} one-hot product is exact, so every record's
      value enters the f32 accumulator unquantized; the per-segment SUM is
      then an f32 accumulation, equal to a per-record f32 sum up to
      addition order. It is NOT f64 accumulation (the reference's
      per-record path sums in double): results are bit-equal to the oracle
      for integer-valued / short-mantissa payloads and f32-rounded
      otherwise — the parity tests compare under f32 tolerance.
    - exact=False uses a single bf16 term: ~8 mantissa bits per value,
      3x less matmul work; for count-like payloads (small integers) it is
      still exact.
    """
    hi_n, _ = plan_segments(num_segments)

    def body(acc, args):
        ii, vv = args
        oh_hi, oh_lo = _one_hots(ii, hi_n, jnp.bfloat16)
        if exact:
            t0 = vv.astype(jnp.bfloat16)
            r1 = vv - t0.astype(jnp.float32)
            t1 = r1.astype(jnp.bfloat16)
            r2 = r1 - t1.astype(jnp.float32)
            t2 = r2.astype(jnp.bfloat16)
            for t in (t0, t1, t2):
                acc = acc + _dot(oh_hi * t[:, None], oh_lo, jnp.float32)
        else:
            acc = acc + _dot(oh_hi * vv[:, None].astype(jnp.bfloat16), oh_lo, jnp.float32)
        return acc, None

    n = idx.shape[0] // chunk
    acc, _ = jax.lax.scan(
        body, jnp.zeros((hi_n, LANES), jnp.float32), (idx.reshape(n, chunk), vals.reshape(n, chunk))
    )
    return acc.reshape(-1)[:num_segments]


def pad_batch(arrs, n: int, chunk: int, fill_idx: int = -1):
    """Host-side: pad (idx, *value arrays) up to a chunk multiple."""
    padded = -(-max(n, 1) // chunk) * chunk
    if padded == n:
        return arrs, n
    out = []
    for i, a in enumerate(arrs):
        fill = fill_idx if i == 0 else 0
        pad = np.full(padded - n, fill, dtype=a.dtype)
        out.append(np.concatenate([a, pad]))
    return out, padded
